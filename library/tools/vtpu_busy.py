#!/usr/bin/env python3
"""vtpu_busy: drive a TPU chip at a target duty cycle.

Reference analogue: library/tools/gpu_busy.cu — the operator's manual
load generator for validating quota enforcement: run it in a tenant
container at --duty 100 and watch the shim pace it to the container's
core limit (nvidia-smi's role is played by the device-monitor gauges or
`vtpu_inspect`).

Duty cycling: each period runs back-to-back matmul steps for
duty% × period, then sleeps the rest. With the shim loaded the *achieved*
rate is min(--duty, container core limit); unmanaged it holds --duty.

    python library/tools/vtpu_busy.py --duty 60 --seconds 30
    python library/tools/vtpu_busy.py --dim 4096 --report-every 5
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duty", type=int, default=100,
                        help="target busy percent per period")
    parser.add_argument("--period-ms", type=int, default=500)
    parser.add_argument("--seconds", type=float, default=0,
                        help="0 = run until interrupted")
    parser.add_argument("--dim", type=int, default=4096,
                        help="bf16 matmul edge (sizes one step)")
    parser.add_argument("--report-every", type=float, default=2.0)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(a):
        return jnp.tanh(a @ a) * 1e-3

    x = jax.random.normal(jax.random.PRNGKey(0), (args.dim, args.dim),
                          jnp.bfloat16)
    # warmup + per-step cost estimate (sync via scalar readback so the
    # measurement is honest on lying-event transports)
    for _ in range(2):
        x = step(x)
        _ = float(x[0, 0])
    t0 = time.perf_counter()
    x = step(x)
    _ = float(x[0, 0])
    step_s = time.perf_counter() - t0

    period_s = args.period_ms / 1000.0
    busy_target = period_s * min(max(args.duty, 0), 100) / 100.0
    deadline = time.time() + args.seconds if args.seconds else None
    busy_acc = 0.0
    wall_start = time.perf_counter()
    last_report = wall_start
    steps = 0
    print(f"step ~{step_s * 1000:.1f} ms, duty {args.duty}% of "
          f"{args.period_ms} ms periods; ctrl-c to stop", flush=True)
    try:
        while deadline is None or time.time() < deadline:
            period_start = time.perf_counter()
            while time.perf_counter() - period_start < busy_target:
                t = time.perf_counter()
                x = step(x)
                _ = float(x[0, 0])
                busy_acc += time.perf_counter() - t
                steps += 1
            rest = period_s - (time.perf_counter() - period_start)
            if rest > 0:
                time.sleep(rest)
            now = time.perf_counter()
            if now - last_report >= args.report_every:
                wall = now - wall_start
                print(f"achieved {100 * busy_acc / wall:5.1f}% busy "
                      f"({steps} steps, {wall:.1f}s)", flush=True)
                last_report = now
    except KeyboardInterrupt:
        pass
    wall = time.perf_counter() - wall_start
    if wall > 0:
        print(f"final: {100 * busy_acc / wall:.1f}% busy over {wall:.1f}s "
              f"({steps} steps)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
