#!/usr/bin/env python3
"""vtpu_busy: drive a TPU chip at a target duty cycle.

Reference analogue: library/tools/gpu_busy.cu — the operator's manual
load generator for validating quota enforcement: run it in a tenant
container at --duty 100 and watch the shim pace it to the container's
core limit (nvidia-smi's role is played by the device-monitor gauges or
`vtpu_inspect`).

Duty cycling: each period runs back-to-back matmul steps for
duty% × period, then sleeps the rest. With the shim loaded the *achieved*
rate is min(--duty, container core limit); unmanaged it holds --duty.

    python library/tools/vtpu_busy.py --duty 60 --seconds 30
    python library/tools/vtpu_busy.py --dim 4096 --report-every 5
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main() -> int:
    # honor an explicit CPU request even under an ambient tunnel
    # registration (same guard as __graft_entry__: sitecustomize overrides
    # platform selection through jax.config and a wedged tunnel would
    # hang a plain JAX_PLATFORMS=cpu run)
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
    return _main()


def _main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duty", type=int, default=100,
                        help="target busy percent per period")
    parser.add_argument("--period-ms", type=int, default=500)
    parser.add_argument("--seconds", type=float, default=0,
                        help="0 = run until interrupted")
    parser.add_argument("--dim", type=int, default=4096,
                        help="bf16 matmul edge (sizes one step)")
    parser.add_argument("--report-every", type=float, default=2.0)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(a):
        return jnp.tanh(a @ a) * 1e-3

    x = jax.random.normal(jax.random.PRNGKey(0), (args.dim, args.dim),
                          jnp.bfloat16)
    # Warmup (compile + caches; excluded from the counts). The
    # unthrottled per-step cost is tracked as a RUNNING MIN over every
    # step of the run: under a core cap most steps are paced, but the
    # shim's GAP bypass lets the first step after each long idle proceed
    # unthrottled, so the minimum span keeps converging to the true cost
    # even inside an enforced container — the yardstick that makes the
    # effective-share report meaningful (wall time blocked in the rate
    # limiter must NOT count as busy).
    step_s = float("inf")
    for i in range(4):
        t0 = time.perf_counter()
        x = step(x)
        _ = float(x[0, 0])
        if i > 0:   # first call carries compile
            step_s = min(step_s, time.perf_counter() - t0)

    period_s = args.period_ms / 1000.0
    busy_target = period_s * min(max(args.duty, 0), 100) / 100.0
    deadline = time.time() + args.seconds if args.seconds else None
    wall_start = time.perf_counter()
    last_report = wall_start
    steps = 0

    def effective_pct(wall: float) -> float:
        # device share actually delivered: completed steps x unthrottled
        # step cost over wall. Wall time spent BLOCKED in the shim's rate
        # limiter must not count as busy — a naive busy-wall accumulator
        # would read ~--duty even while enforcement paces the chip.
        return 100.0 * steps * step_s / wall if wall > 0 else 0.0

    print(f"step ~{step_s * 1000:.1f} ms, duty {args.duty}% of "
          f"{args.period_ms} ms periods; ctrl-c to stop", flush=True)
    try:
        while deadline is None or time.time() < deadline:
            period_start = time.perf_counter()
            while time.perf_counter() - period_start < busy_target:
                t0 = time.perf_counter()
                x = step(x)
                _ = float(x[0, 0])
                step_s = min(step_s, time.perf_counter() - t0)
                steps += 1
            rest = period_s - (time.perf_counter() - period_start)
            if rest > 0:
                time.sleep(rest)
            now = time.perf_counter()
            if now - last_report >= args.report_every:
                print(f"effective {effective_pct(now - wall_start):5.1f}% "
                      f"of chip ({steps} steps, "
                      f"{now - wall_start:.1f}s)", flush=True)
                last_report = now
    except KeyboardInterrupt:
        pass
    wall = time.perf_counter() - wall_start
    if wall > 0:
        print(f"final: effective {effective_pct(wall):.1f}% of chip over "
              f"{wall:.1f}s ({steps} steps)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
