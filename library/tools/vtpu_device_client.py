#!/usr/bin/env python3
"""Standalone device-client registrar (stdlib only, no vtpu_manager).

Reference: cmd/device-client/main.go — a tiny static binary the intercept
library execs inside the tenant container to announce it to the node
registry (CLIENT compat mode). Tenant images do not carry the
vtpu_manager package, so this single file is installed next to the shim
in the driver dir (mounted into every tenant) and the shim runs it with
whatever python3 the image has. Protocol: length-prefixed JSON over the
registry's unix socket; the server authenticates via SO_PEERCRED +
cgroup attestation, we only present pod identity from the env the
device plugin injected.

Retries briefly: container start races the registry daemon's restart
window, and a missed registration would silently break per-process
attribution for the container's lifetime.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import sys
import time

DEFAULT_SOCKET = "/etc/vtpu-manager/registry/socket.sock"


def register_once(path: str, timeout_s: float = 5.0) -> bool:
    payload = json.dumps({
        "pod_name": os.environ.get("VTPU_POD_NAME", ""),
        "pod_namespace": os.environ.get("VTPU_POD_NAMESPACE", ""),
        "pod_uid": os.environ.get("VTPU_POD_UID", ""),
        "container": os.environ.get("VTPU_CONTAINER_NAME", ""),
        "register_uuid": os.environ.get("VTPU_REGISTER_UUID", ""),
    }).encode()
    try:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as sock:
            sock.settimeout(timeout_s)
            sock.connect(path)
            sock.sendall(struct.pack("<I", len(payload)) + payload)
            raw = sock.recv(4)
            if len(raw) < 4:
                return False
            (status,) = struct.unpack("<i", raw)
            return status == 0
    except OSError:
        return False


def main() -> int:
    path = os.environ.get("VTPU_REGISTRY_SOCKET", DEFAULT_SOCKET)
    delay = 0.5
    for attempt in range(6):
        if register_once(path):
            print("vtpu device-client: registered", file=sys.stderr)
            return 0
        time.sleep(delay)
        delay = min(delay * 2, 8.0)
    print(f"vtpu device-client: registration FAILED after {attempt + 1} "
          f"attempts ({path})", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
