/* vtpu_cache_client.h — the C++ shim's node-shared compile-cache client.
 *
 * vtcc follow-up (carried from PR 7): the v2 config header plumbed
 * compile_cache_dir to the shim, but only Python/jax tenants armed on
 * it (JAX_COMPILATION_CACHE_DIR). This header is the Execute-path
 * client for everyone else: a tenant driving PJRT through the shim
 * without the Python runtime client gets the same one-compile-per-node
 * behavior via PJRT_Client_Compile interception (enforce.cc).
 *
 * The store protocol is byte-compatible with
 * vtpu_manager/compilecache/cache.py — same directory shape
 * (entries/ tmp/ lease/ quarantine/), same 24-byte checksummed entry
 * header (magic "VTCC", version, payload_len u64, fnv1a-64 u64), same
 * atomic write-tmp-fsync-rename landing, and the same born-flock'd
 * single-flight lease files ("pid@ts", liveness = the kernel-released
 * flock on the lease inode) — so the node janitor (LRU/quarantine/
 * stale-tmp reap) manages C++-written entries exactly like Python
 * ones, and the two sides' waiters exclude each other. Keys are
 * sha256 (like the Python side's content keys) over length-prefixed
 * program code/format/options, prefixed "shim-" — a distinct, non-
 * colliding namespace: the shim caches platform-serialized
 * executables, the Python side caches its own artifact shapes.
 *
 * Header-only so tests/test_config_abi.py's g++ probe rows compile the
 * EXACT client the shim ships and round-trip entries + leases against
 * the Python implementation.
 */
#ifndef VTPU_CACHE_CLIENT_H_
#define VTPU_CACHE_CLIENT_H_

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>
#include <utime.h>

#include <mutex>
#include <string>
#include <unordered_map>

namespace vtpu {

// ---------------------------------------------------------------------------
// sha256 (FIPS 180-4), compact: cache keys must be collision-safe —
// a weak hash colliding across programs would serve the WRONG
// executable to a tenant (verified-payload checksums only prove the
// entry matches itself).
// ---------------------------------------------------------------------------

class Sha256 {
 public:
  Sha256() { Reset(); }

  void Reset() {
    static const uint32_t kInit[8] = {
        0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
        0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
    memcpy(h_, kInit, sizeof(h_));
    len_ = 0;
    buf_used_ = 0;
  }

  void Update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    len_ += n;
    while (n > 0) {
      size_t take = 64 - buf_used_;
      if (take > n) take = n;
      memcpy(buf_ + buf_used_, p, take);
      buf_used_ += take;
      p += take;
      n -= take;
      if (buf_used_ == 64) {
        Block(buf_);
        buf_used_ = 0;
      }
    }
  }

  // 64 lowercase hex chars into out (must hold 65 bytes incl. NUL).
  void HexDigest(char* out) {
    uint64_t bits = len_ * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (buf_used_ != 56) Update(&zero, 1);
    uint8_t lenbuf[8];
    for (int i = 0; i < 8; i++)
      lenbuf[i] = (uint8_t)(bits >> (56 - 8 * i));
    Update(lenbuf, 8);
    for (int i = 0; i < 8; i++)
      snprintf(out + 8 * i, 9, "%08x", h_[i]);
  }

 private:
  static uint32_t Rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void Block(const uint8_t* p) {
    static const uint32_t kK[64] = {
        0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
        0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
        0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
        0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
        0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
        0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
        0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
        0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
        0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
        0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
        0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
        0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
        0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
             ((uint32_t)p[4 * i + 2] << 8) | (uint32_t)p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h_[0], b = h_[1], c = h_[2], d = h_[3];
    uint32_t e = h_[4], f = h_[5], g = h_[6], h = h_[7];
    for (int i = 0; i < 64; i++) {
      uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = h + s1 + ch + kK[i] + w[i];
      uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      h = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h_[0] += a; h_[1] += b; h_[2] += c; h_[3] += d;
    h_[4] += e; h_[5] += f; h_[6] += g; h_[7] += h;
  }

  uint32_t h_[8];
  uint64_t len_ = 0;
  uint8_t buf_[64];
  size_t buf_used_ = 0;
};

// ---------------------------------------------------------------------------
// Store client
// ---------------------------------------------------------------------------

constexpr uint32_t kCacheEntryMagic = 0x43435456;  // "VTCC" (cache.py MAGIC)
constexpr uint32_t kCacheEntryVersion = 1;
constexpr size_t kCacheEntryHeaderSize = 24;

inline uint64_t CacheFnv1a64(const uint8_t* data, size_t n) {
  uint64_t h = 0xCBF29CE484222325ull;
  for (size_t i = 0; i < n; i++) {
    h ^= data[i];
    h *= 0x100000001B3ull;
  }
  return h;
}

class CompileCacheClient {
 public:
  explicit CompileCacheClient(const char* root) {
    if (!root || !*root) return;
    root_ = root;
    // the plugin's Allocate created the tree; mkdir here only covers
    // a bare-process run pointed at a fresh dir (tests, probes)
    ok_ = EnsureDir(root_) && EnsureDir(root_ + "/entries") &&
          EnsureDir(root_ + "/tmp") && EnsureDir(root_ + "/lease") &&
          EnsureDir(root_ + "/quarantine");
    const char* stale = getenv("VTPU_CACHE_STALE_LEASE_S");
    if (stale) stale_lease_s_ = atof(stale);
    if (!(stale_lease_s_ > 0)) stale_lease_s_ = 300.0;
  }

  ~CompileCacheClient() {
    // close (not release): dropping the flocks mimics process death,
    // which is exactly what waiters are built to take over from
    std::lock_guard<std::mutex> g(leases_mu_);
    for (auto& kv : leases_) close(kv.second.fd);
  }

  bool ok() const { return ok_; }

  // "shim-" + sha256 over the length-prefixed compile inputs: the code
  // bytes, their declared format, and the serialized compile options
  // (sharding/replication change the produced executable).
  static std::string Key(const void* code, size_t code_size,
                         const char* format, size_t format_size,
                         const void* options, size_t options_size) {
    Sha256 sha;
    uint64_t lens[3] = {(uint64_t)code_size, (uint64_t)format_size,
                        (uint64_t)options_size};
    sha.Update(&lens[0], sizeof(lens[0]));
    if (code_size) sha.Update(code, code_size);
    sha.Update(&lens[1], sizeof(lens[1]));
    if (format_size) sha.Update(format, format_size);
    sha.Update(&lens[2], sizeof(lens[2]));
    if (options_size) sha.Update(options, options_size);
    char hex[65];
    sha.HexDigest(hex);
    return std::string("shim-") + hex;
  }

  // Verified read; corrupt entries are quarantined (rename wins for
  // exactly one racer, same as cache.py). A hit refreshes mtime (the
  // janitor's LRU signal).
  bool Get(const std::string& key, std::string* payload) {
    std::string path = EntryPath(key);
    std::string raw;
    if (!ReadFile(path, &raw)) return false;
    if (!Verify(raw, payload)) {
      Quarantine(key);
      return false;
    }
    utime(path.c_str(), nullptr);  // losing the refresh to a race is fine
    return true;
  }

  // Atomic landing: tmp (pid + random token in the name) + fsync +
  // rename. False = the payload did not land (callers serve their
  // in-memory copy uncached, the cache.py rule).
  bool Put(const std::string& key, const void* data, size_t len) {
    char token[32];
    snprintf(token, sizeof(token), "%d.%08x", (int)getpid(),
             (unsigned)(NowNsMono() & 0xFFFFFFFFu));
    std::string tmp = root_ + "/tmp/" + key + "." + token;
    int fd = open(tmp.c_str(), O_CREAT | O_EXCL | O_WRONLY, 0666);
    if (fd < 0) return false;
    uint8_t header[kCacheEntryHeaderSize];
    uint32_t magic = kCacheEntryMagic, version = kCacheEntryVersion;
    uint64_t len64 = len;
    uint64_t sum = CacheFnv1a64(static_cast<const uint8_t*>(data), len);
    memcpy(header, &magic, 4);
    memcpy(header + 4, &version, 4);
    memcpy(header + 8, &len64, 8);
    memcpy(header + 16, &sum, 8);
    bool ok = WriteAll(fd, header, sizeof(header)) &&
              WriteAll(fd, data, len) && fsync(fd) == 0;
    close(fd);
    if (ok) ok = rename(tmp.c_str(), EntryPath(key).c_str()) == 0;
    if (!ok) unlink(tmp.c_str());
    return ok;
  }

  // Single-flight population lease, the cache.py protocol: the lease
  // file is born already containing "pid@ts" AND already flock'd
  // (write-tmp, flock, link — no observer ever sees an empty or
  // unlocked lease), liveness is the kernel-released flock, stale/dead
  // holders are taken over after a verify-content-then-unlink guard.
  bool TryAcquireLease(const std::string& key) {
    std::string path = LeasePath(key);
    Hold hold;
    if (LinkLease(path, &hold)) {
      RememberHold(key, hold);
      return true;
    }
    std::string held;
    if (!ReadFile(path, &held)) return false;  // vanished: retry later
    if (!LeaseStale(path, held)) return false;
    std::string again;
    if (!ReadFile(path, &again) || again != held)
      return false;  // a fresh holder replaced it between read and unlink
    if (unlink(path.c_str()) != 0) return false;
    if (!LinkLease(path, &hold)) return false;  // another waiter won
    RememberHold(key, hold);
    return true;
  }

  void ReleaseLease(const std::string& key) {
    Hold hold;
    {
      // concurrent PJRT_Client_Compile calls share this client: the
      // map itself needs a lock (the flocks do not)
      std::lock_guard<std::mutex> g(leases_mu_);
      auto it = leases_.find(key);
      if (it == leases_.end()) return;
      hold = it->second;
      leases_.erase(it);
    }
    close(hold.fd);  // flock released with the OFD
    std::string path = LeasePath(key), current;
    // unlink only if still OUR exact content — a takeover's lease must
    // survive our late release (content equality, never pid equality)
    if (ReadFile(path, &current) && current == hold.payload)
      unlink(path.c_str());
  }

  // True while some other holder's lease looks live (the waiters' poll
  // predicate between Get() retries).
  bool LeaseHeldByOther(const std::string& key) {
    std::string path = LeasePath(key), held;
    {
      std::lock_guard<std::mutex> g(leases_mu_);
      if (leases_.count(key)) return false;
    }
    if (!ReadFile(path, &held)) return false;
    return !LeaseStale(path, held);
  }

  std::string EntryPath(const std::string& key) const {
    return root_ + "/entries/" + key;
  }

 private:
  struct Hold {
    int fd = -1;
    std::string payload;
  };

  void RememberHold(const std::string& key, const Hold& hold) {
    std::lock_guard<std::mutex> g(leases_mu_);
    leases_[key] = hold;
  }

  static uint64_t NowNsMono() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
  }

  static bool EnsureDir(const std::string& path) {
    if (mkdir(path.c_str(), 0777) == 0 || errno == EEXIST) return true;
    return false;
  }

  static bool ReadFile(const std::string& path, std::string* out) {
    int fd = open(path.c_str(), O_RDONLY);
    if (fd < 0) return false;
    out->clear();
    char buf[65536];
    for (;;) {
      ssize_t n = read(fd, buf, sizeof(buf));
      if (n < 0) {
        close(fd);
        return false;
      }
      if (n == 0) break;
      out->append(buf, (size_t)n);
    }
    close(fd);
    return true;
  }

  static bool WriteAll(int fd, const void* data, size_t len) {
    const char* p = static_cast<const char*>(data);
    while (len > 0) {
      ssize_t n = write(fd, p, len);
      if (n <= 0) return false;
      p += n;
      len -= (size_t)n;
    }
    return true;
  }

  static bool Verify(const std::string& raw, std::string* payload) {
    if (raw.size() < kCacheEntryHeaderSize) return false;
    uint32_t magic, version;
    uint64_t len64, sum;
    memcpy(&magic, raw.data(), 4);
    memcpy(&version, raw.data() + 4, 4);
    memcpy(&len64, raw.data() + 8, 8);
    memcpy(&sum, raw.data() + 16, 8);
    if (magic != kCacheEntryMagic || version != kCacheEntryVersion)
      return false;
    size_t n = raw.size() - kCacheEntryHeaderSize;
    if (n != len64) return false;
    const uint8_t* body =
        reinterpret_cast<const uint8_t*>(raw.data()) +
        kCacheEntryHeaderSize;
    if (CacheFnv1a64(body, n) != sum) return false;
    payload->assign(reinterpret_cast<const char*>(body), n);
    return true;
  }

  void Quarantine(const std::string& key) {
    char stamp[32];
    snprintf(stamp, sizeof(stamp), "%llu",
             (unsigned long long)NowNsMono());
    std::string dst = root_ + "/quarantine/" + key + "." + stamp;
    rename(EntryPath(key).c_str(), dst.c_str());  // one racer wins
  }

  std::string LeasePath(const std::string& key) const {
    return root_ + "/lease/" + key + ".lease";
  }

  bool LinkLease(const std::string& path, Hold* out) {
    char token[32];
    snprintf(token, sizeof(token), "%d.%08x", (int)getpid(),
             (unsigned)(NowNsMono() & 0xFFFFFFFFu));
    std::string tmp = path + "." + token + ".tmp";
    char payload[64];
    snprintf(payload, sizeof(payload), "%d@%.6f", (int)getpid(),
             (double)time(nullptr));
    int fd = open(tmp.c_str(), O_CREAT | O_EXCL | O_WRONLY, 0666);
    if (fd < 0) return false;
    bool linked = WriteAll(fd, payload, strlen(payload)) &&
                  flock(fd, LOCK_EX | LOCK_NB) == 0 &&
                  link(tmp.c_str(), path.c_str()) == 0;
    unlink(tmp.c_str());
    if (!linked) {
      close(fd);
      return false;
    }
    out->fd = fd;  // stays open: the flock IS the liveness
    out->payload = payload;
    return true;
  }

  bool LeaseStale(const std::string& path, const std::string& held) {
    // "pid@ts"; garbage parses as maximally stale (must be
    // takeover-able, never immortal)
    int pid = 0;
    double ts = 0.0;
    sscanf(held.c_str(), "%d@%lf", &pid, &ts);
    double age = (double)time(nullptr) - ts;
    if (age > stale_lease_s_ || age < -stale_lease_s_) return true;
    int fd = open(path.c_str(), O_RDONLY);
    if (fd >= 0) {
      bool grabbable = flock(fd, LOCK_EX | LOCK_NB) == 0;
      if (grabbable) flock(fd, LOCK_UN);
      close(fd);
      return grabbable;  // nobody holds the flock = holder died
    }
    // probe failed (vanished mid-check): same-namespace pid fallback
    return kill(pid, 0) != 0 && errno == ESRCH;
  }

  std::string root_;
  double stale_lease_s_ = 300.0;
  bool ok_ = false;
  std::mutex leases_mu_;
  std::unordered_map<std::string, Hold> leases_;
};

}  // namespace vtpu

#endif  // VTPU_CACHE_CLIENT_H_
