/* vtpu_quota.h — shim-side quota-market lease adoption (vtqm).
 *
 * The device plugin's quota-market manager lends a chip's measured-idle
 * headroom between co-tenants by rewriting each tenant's vtpu.config
 * (atomic tmp+rename, the file's own checksum guarding torn writes)
 * with a new per-device lease_core delta and a bumped header
 * quota_epoch. The shim cannot keep an mmap of the file — rename swaps
 * the inode — so *instant reclaim* is a re-read triggered from the
 * token-wait loop: every throttle quantum (~2 ms) the waiting thread
 * pays one stat(); only an inode/size/mtime change pays the full
 * read+verify. That bounds revoke-to-enforcement latency at one
 * throttle quantum + one config re-read, without any watcher thread in
 * the reclaim path.
 *
 * Header-only on purpose: enforce.cc, the g++ ABI-probe rows in
 * tests/test_config_abi.py, and library/tools/quota_reclaim_probe.cc
 * (the bench's real-latency measurement) all compile the same adoption
 * logic — the measured number and the shipped number cannot drift.
 */
#ifndef VTPU_QUOTA_H_
#define VTPU_QUOTA_H_

#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include "vtpu_config.h"

namespace vtpu {

// Effective TensorCore rate under a lease: the base grant plus the
// signed lease delta, clamped to a physical chip share. The market
// manager keeps per-chip sums <= 100 on the grant side; the clamp here
// is the defense against a torn ledger ever reaching enforcement.
inline int EffectiveCorePct(int base_core, int lease_core) {
  int v = base_core + lease_core;
  if (v < 0) v = 0;
  if (v > 100) v = 100;
  return v;
}

// Watches one vtpu.config path for quota-market generations. Check()
// is cheap enough for the token-wait loop: one stat() in the common
// case. A full read runs only when the inode/mtime/size moved, and the
// result is adopted only when it validates (magic/version/checksum/
// count) AND carries a different quota_epoch than the last adopted
// generation — a torn or stale rewrite is ignored, never enforced.
class QuotaReloader {
 public:
  explicit QuotaReloader(const char* path) {
    path_[0] = 0;
    if (path) snprintf(path_, sizeof(path_), "%s", path);
  }

  // Record the generation the shim already loaded (LoadConfig at
  // startup) so the first Check() does not re-adopt it.
  void Prime(const VtpuConfig& loaded) {
    last_epoch_ = loaded.quota_epoch;
    primed_ = true;
    struct stat st;
    if (path_[0] && stat(path_, &st) == 0) RememberStat(st);
  }

  // Returns true when a NEW valid lease generation was read into *out.
  bool Check(VtpuConfig* out) {
    if (path_[0] == 0) return false;
    struct stat st;
    if (stat(path_, &st) != 0) return false;
    if (SameStat(st)) return false;
    if ((size_t)st.st_size != sizeof(VtpuConfig)) {
      // mid-rewrite glimpse of a foreign file shape: remember nothing,
      // so the next tick re-stats (the rename lands a full-size file)
      return false;
    }
    VtpuConfig cfg;
    if (!ReadAndVerify(&cfg)) return false;
    RememberStat(st);
    if (primed_ && cfg.quota_epoch == last_epoch_) return false;
    last_epoch_ = cfg.quota_epoch;
    primed_ = true;
    *out = cfg;
    return true;
  }

  uint32_t epoch() const { return last_epoch_; }
  const char* path() const { return path_; }

 private:
  // mtime at NANOSECOND granularity: the size never changes and inode
  // numbers are recycled, so two rewrites inside one second could
  // otherwise look identical and a revoke would be silently skipped —
  // breaking the one-quantum reclaim bound the bench asserts
  bool SameStat(const struct stat& st) const {
    return seen_stat_ && st.st_ino == last_ino_ &&
           st.st_size == last_size_ &&
           st.st_mtim.tv_sec == last_mtime_sec_ &&
           st.st_mtim.tv_nsec == last_mtime_nsec_;
  }

  void RememberStat(const struct stat& st) {
    last_ino_ = st.st_ino;
    last_size_ = st.st_size;
    last_mtime_sec_ = st.st_mtim.tv_sec;
    last_mtime_nsec_ = st.st_mtim.tv_nsec;
    seen_stat_ = true;
  }

  bool ReadAndVerify(VtpuConfig* cfg) {
    int fd = open(path_, O_RDONLY);
    if (fd < 0) return false;
    size_t got = 0;
    char* dst = reinterpret_cast<char*>(cfg);
    while (got < sizeof(VtpuConfig)) {
      ssize_t n = read(fd, dst + got, sizeof(VtpuConfig) - got);
      if (n <= 0) {
        close(fd);
        return false;
      }
      got += (size_t)n;
    }
    close(fd);
    return cfg->magic == kConfigMagic && cfg->version == kConfigVersion &&
           cfg->checksum == Fnv1a(reinterpret_cast<const uint8_t*>(cfg),
                                  offsetof(VtpuConfig, checksum)) &&
           cfg->device_count >= 0 && cfg->device_count <= kMaxDeviceCount;
  }

  char path_[512];
  ino_t last_ino_ = 0;
  off_t last_size_ = 0;
  time_t last_mtime_sec_ = 0;
  long last_mtime_nsec_ = 0;
  uint32_t last_epoch_ = 0;
  bool seen_stat_ = false;
  bool primed_ = false;
};

}  // namespace vtpu

#endif  // VTPU_QUOTA_H_
