/* vtpu_telemetry.h — C++ side of the vttel step-ring ABI.
 *
 * Mirror of vtpu_manager/telemetry/stepring.py: a fixed-size mmap'd ring
 * of fixed-width step records under the per-container telemetry dir
 * (MANAGER_BASE_DIR/telemetry/step_telemetry.ring in-container). The
 * Python runtime client writes it for Python tenants; the shim's Execute
 * hook writes the identical layout for C++-driven tenants, and the node
 * monitor tails either indistinguishably. Layout changes are a two-step
 * edit: this header's static_asserts AND the committed abi_golden.json
 * (scripts/vtlint.py --update-abi-golden) both pin the Python module.
 *
 * Concurrency: per-record seqlock, same discipline as vtpu_config.h /
 * the tc_util feed — writer forces (seq | 1) odd before the payload and
 * bumps to even after; readers retry on odd or changed seq. Writer
 * exclusion is an open-time OFD write lock on the header range, never a
 * hot-path lock.
 */
#ifndef VTPU_TELEMETRY_H_
#define VTPU_TELEMETRY_H_

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

namespace vtpu {

constexpr uint32_t kStepRingMagic = 0x54535456;  // "VTST"
// v2 (vtovc): records grew a spill block — spilled_bytes gauge +
// spill/fill event deltas — the channel carrying the shim's host-tier
// activity to the collector's vtpu_node_spill_* series. Strict version
// check; rings are recreated per container and ship with the node.
// v3 (vtcomm): records grew a comm block — comm_time_ns (measured
// collective + transfer span time), bytes_transferred, and
// collective_count — the measured-communication channel feeding the
// vtuse comm-intensity ledger and the honest ICI-bucket currency.
// CommTelemetry off writes zeros in all three.
// v4 (vtslo): spill_fill_time_ns — measured wall time inside the
// host-tier demotion/promotion paths (TrySpillCold + FillSpilled), so
// the SLO attribution plane's spill-fill component is measured like
// the comm spans. An unarmed spill tier writes zero.
constexpr uint32_t kStepRingVersion = 4;
constexpr int kStepRingCapacity = 256;
constexpr int kStepTraceIdLen = 48;

// StepRecord.flags
constexpr uint32_t kStepFlagCompile = 0x1;  // step paid a compile
// vtheal: the step's Execute (or a transfer inside it) returned an
// error the shim recovered from. A new bit in the existing v4 flags
// field — no layout change, no version bump; readers that don't know
// the bit only test kStepFlagCompile. The health plane reads trailing
// streaks of it as dead-chip evidence.
constexpr uint32_t kStepFlagExecError = 0x2;

// Staleness budget of the measured-collective signal (mirror of
// stepring.COMM_SIGNAL_STALENESS_NS): the ICI token bucket charges the
// measured collective-time EMA only while the last measured collective
// is younger than this; otherwise it falls back to the exec-cost EMA —
// the exact pre-v3 currency, so CommTelemetry off is byte-identical.
constexpr uint64_t kCommSignalStalenessNs = 10ull * 1000 * 1000 * 1000;

// The ICI bucket's charge-selection rule (header-only so the
// test_config_abi g++ probe asserts it against the Python mirror
// stepring.comm_cost_us without the cmake build).
inline int64_t CommCostUs(int64_t comm_ema_us, uint64_t comm_age_ns,
                          int64_t exec_cost_us) {
  if (comm_ema_us > 0 && comm_age_ns <= kCommSignalStalenessNs)
    return comm_ema_us;
  return exec_cost_us;
}

struct StepRingHeader {
  uint32_t magic;
  uint32_t version;
  int32_t capacity;      // records in the ring (kStepRingCapacity)
  int32_t record_size;   // sizeof(StepRecord)
  int32_t writer_pid;
  int32_t pad_;
  uint64_t writes;       // total records ever published (ring head)
  char trace_id[kStepTraceIdLen];  // vtrace join key, NUL-terminated
};
static_assert(sizeof(StepRingHeader) == 80, "StepRingHeader ABI size");
static_assert(offsetof(StepRingHeader, writer_pid) == 16, "ABI");
static_assert(offsetof(StepRingHeader, writes) == 24, "ABI");
static_assert(offsetof(StepRingHeader, trace_id) == 32, "ABI");

struct StepRecord {
  uint64_t seq;          // per-record seqlock (odd = mid-write)
  uint64_t index;        // monotone step index (slot = index % capacity)
  uint64_t start_mono_ns;
  uint64_t duration_ns;
  uint64_t throttle_wait_ns;   // time stalled in the compute throttle
  uint64_t hbm_highwater_bytes;
  uint32_t flags;        // kStepFlag*
  int32_t pad_;
  // v2 spill block (vtovc; zeros when HBMOvercommit is off)
  uint64_t spilled_bytes;  // host-pool footprint at step end (gauge)
  uint32_t spill_events;   // HBM->host demotions since last record
  uint32_t fill_events;    // host->HBM promotions since last record
  // v3 comm block (vtcomm; zeros when CommTelemetry is off)
  uint64_t comm_time_ns;       // measured collective+transfer span time
  uint64_t bytes_transferred;  // bytes observed moving since last record
  uint32_t collective_count;   // multi-chip dispatches since last record
  uint32_t pad2_;
  // v4 (vtslo; zero when the spill tier never measured a span)
  uint64_t spill_fill_time_ns;  // host-tier spill+fill span time
};
static_assert(sizeof(StepRecord) == 104, "StepRecord ABI size");
static_assert(offsetof(StepRecord, index) == 8, "ABI");
static_assert(offsetof(StepRecord, duration_ns) == 24, "ABI");
static_assert(offsetof(StepRecord, throttle_wait_ns) == 32, "ABI");
static_assert(offsetof(StepRecord, hbm_highwater_bytes) == 40, "ABI");
static_assert(offsetof(StepRecord, flags) == 48, "ABI");
static_assert(offsetof(StepRecord, spilled_bytes) == 56, "ABI");
static_assert(offsetof(StepRecord, spill_events) == 64, "ABI");
static_assert(offsetof(StepRecord, fill_events) == 68, "ABI");
static_assert(offsetof(StepRecord, comm_time_ns) == 72, "ABI");
static_assert(offsetof(StepRecord, bytes_transferred) == 80, "ABI");
static_assert(offsetof(StepRecord, collective_count) == 88, "ABI");
static_assert(offsetof(StepRecord, spill_fill_time_ns) == 96, "ABI");

constexpr size_t kStepRingFileSize =
    sizeof(StepRingHeader) + kStepRingCapacity * sizeof(StepRecord);

// ---------------------------------------------------------------------------
// StepRingWriter — the shim-side mirror of stepring.StepRingWriter.
//
// Header-only on purpose: the Execute hook in enforce.cc and the
// g++-probe regression in tests/test_config_abi.py compile the SAME
// writer, so the bytes a C++ tenant's shim publishes are asserted
// byte-compatible with the Python reader without needing the cmake
// build. Protocol mirror of the Python writer, field for field:
// atomic create (tmp + rename) so a reader never maps a partial file,
// open-time OFD write lock on the header for cross-process writer
// exclusion (a live Python-side writer keeps the lock and this one
// yields — one winner per ring, the Python runtime client arms first
// for Python tenants), per-record seqlock (seq|1 odd before the
// payload, +1 even after), and the sequence continues across writer
// restarts so the reader's cursor stays monotone.
// ---------------------------------------------------------------------------

class StepRingWriter {
 public:
  explicit StepRingWriter(const char* path, const char* trace_id = nullptr) {
    if (!path || !*path) return;
    struct stat st;
    if (stat(path, &st) != 0 ||
        (size_t)st.st_size != kStepRingFileSize) {
      if (!CreateAtomically(path, trace_id)) return;
    }
    fd_ = open(path, O_RDWR | O_CLOEXEC);
    if (fd_ < 0) return;
    // writer exclusion across container restarts (and across the
    // language boundary): the kernel releases the lock on crash
    struct flock fl;
    memset(&fl, 0, sizeof(fl));
    fl.l_type = F_WRLCK;
    fl.l_whence = SEEK_SET;
    fl.l_start = 0;
    fl.l_len = (off_t)sizeof(StepRingHeader);
#ifdef F_OFD_SETLK
    int lock_cmd = F_OFD_SETLK;
#else
    int lock_cmd = F_SETLK;
#endif
    if (fcntl(fd_, lock_cmd, &fl) != 0) {
      Close();
      return;
    }
    void* mm = mmap(nullptr, kStepRingFileSize, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd_, 0);
    if (mm == MAP_FAILED) {
      Close();
      return;
    }
    mm_ = (uint8_t*)mm;
    StepRingHeader* h = Header();
    if (h->magic != kStepRingMagic || h->version != kStepRingVersion ||
        h->capacity != kStepRingCapacity ||
        h->record_size != (int32_t)sizeof(StepRecord)) {
      munmap(mm_, kStepRingFileSize);
      mm_ = nullptr;
      Close();
      return;
    }
    // a restarted writer continues the sequence: the reader's cursor
    // stays monotone across writer generations
    writes_ = __atomic_load_n(&h->writes, __ATOMIC_ACQUIRE);
    h->writer_pid = (int32_t)getpid();
    if (trace_id && *trace_id) {
      memset(h->trace_id, 0, kStepTraceIdLen);
      strncpy(h->trace_id, trace_id, kStepTraceIdLen - 1);
    }
  }

  ~StepRingWriter() {
    if (mm_) {
      munmap(mm_, kStepRingFileSize);
      mm_ = nullptr;
    }
    Close();  // the kernel drops the OFD lock with the fd
  }

  StepRingWriter(const StepRingWriter&) = delete;
  StepRingWriter& operator=(const StepRingWriter&) = delete;

  bool ok() const { return mm_ != nullptr; }
  uint64_t writes() const { return writes_; }

  // Publish one step record (the hot path: mmap stores only). Seqlock
  // bracket per the shared-mmap protocol — odd seq first, payload,
  // even seq last; `seq | 1` so a crashed writer's odd leftover can't
  // invert parity and let torn reads validate.
  void Record(uint64_t duration_ns, uint64_t throttle_wait_ns,
              uint64_t hbm_highwater_bytes, bool compiled,
              uint64_t start_mono_ns = 0, uint64_t spilled_bytes = 0,
              uint32_t spill_events = 0, uint32_t fill_events = 0,
              uint64_t comm_time_ns = 0, uint64_t bytes_transferred = 0,
              uint32_t collective_count = 0,
              uint64_t spill_fill_time_ns = 0,
              bool exec_error = false) {
    if (!mm_) return;
    if (start_mono_ns == 0) {
      struct timespec ts;
      clock_gettime(CLOCK_MONOTONIC, &ts);
      uint64_t now = (uint64_t)ts.tv_sec * 1000000000ull +
                     (uint64_t)ts.tv_nsec;
      start_mono_ns = now > duration_ns ? now - duration_ns : 0;
    }
    uint64_t index = writes_;
    StepRecord* rec = (StepRecord*)(mm_ + sizeof(StepRingHeader) +
                                    (index % kStepRingCapacity) *
                                        sizeof(StepRecord));
    uint64_t seq = __atomic_load_n(&rec->seq, __ATOMIC_RELAXED);
    uint64_t wseq = seq | 1;
    __atomic_store_n(&rec->seq, wseq, __ATOMIC_RELEASE);  // odd: writing
    rec->index = index;
    rec->start_mono_ns = start_mono_ns;
    rec->duration_ns = duration_ns;
    rec->throttle_wait_ns = throttle_wait_ns;
    rec->hbm_highwater_bytes = hbm_highwater_bytes;
    rec->flags = (compiled ? kStepFlagCompile : 0) |
                 (exec_error ? kStepFlagExecError : 0);
    rec->pad_ = 0;
    rec->spilled_bytes = spilled_bytes;
    rec->spill_events = spill_events;
    rec->fill_events = fill_events;
    rec->comm_time_ns = comm_time_ns;
    rec->bytes_transferred = bytes_transferred;
    rec->collective_count = collective_count;
    rec->pad2_ = 0;
    rec->spill_fill_time_ns = spill_fill_time_ns;
    __atomic_store_n(&rec->seq, wseq + 1, __ATOMIC_RELEASE);  // even
    writes_ = index + 1;
    __atomic_store_n(&Header()->writes, writes_, __ATOMIC_RELEASE);
  }

 private:
  StepRingHeader* Header() { return (StepRingHeader*)mm_; }

  void Close() {
    if (fd_ >= 0) {
      close(fd_);
      fd_ = -1;
    }
  }

  static bool CreateAtomically(const char* path, const char* trace_id) {
    // tmp + rename: a reader mmaping the final path must never observe
    // a partial file (the Python writer's contract)
    char tmp[4096];
    int n = snprintf(tmp, sizeof(tmp), "%s.tmp.%d", path, (int)getpid());
    if (n < 0 || (size_t)n >= sizeof(tmp)) return false;
    int fd = open(tmp, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
    if (fd < 0) return false;
    StepRingHeader h;
    memset(&h, 0, sizeof(h));
    h.magic = kStepRingMagic;
    h.version = kStepRingVersion;
    h.capacity = kStepRingCapacity;
    h.record_size = (int32_t)sizeof(StepRecord);
    h.writer_pid = (int32_t)getpid();
    if (trace_id && *trace_id)
      strncpy(h.trace_id, trace_id, kStepTraceIdLen - 1);
    bool ok = write(fd, &h, sizeof(h)) == (ssize_t)sizeof(h) &&
              ftruncate(fd, (off_t)kStepRingFileSize) == 0;
    close(fd);
    if (!ok || rename(tmp, path) != 0) {
      unlink(tmp);
      return false;
    }
    return true;
  }

  int fd_ = -1;
  uint8_t* mm_ = nullptr;
  uint64_t writes_ = 0;
};

}  // namespace vtpu

#endif  // VTPU_TELEMETRY_H_
