/* vtpu_telemetry.h — C++ side of the vttel step-ring ABI.
 *
 * Mirror of vtpu_manager/telemetry/stepring.py: a fixed-size mmap'd ring
 * of fixed-width step records under the per-container telemetry dir
 * (MANAGER_BASE_DIR/telemetry/step_telemetry.ring in-container). The
 * Python runtime client writes it for Python tenants; the shim's Execute
 * hook writes the identical layout for C++-driven tenants, and the node
 * monitor tails either indistinguishably. Layout changes are a two-step
 * edit: this header's static_asserts AND the committed abi_golden.json
 * (scripts/vtlint.py --update-abi-golden) both pin the Python module.
 *
 * Concurrency: per-record seqlock, same discipline as vtpu_config.h /
 * the tc_util feed — writer forces (seq | 1) odd before the payload and
 * bumps to even after; readers retry on odd or changed seq. Writer
 * exclusion is an open-time OFD write lock on the header range, never a
 * hot-path lock.
 */
#ifndef VTPU_TELEMETRY_H_
#define VTPU_TELEMETRY_H_

#include <cstddef>
#include <cstdint>

namespace vtpu {

constexpr uint32_t kStepRingMagic = 0x54535456;  // "VTST"
constexpr uint32_t kStepRingVersion = 1;
constexpr int kStepRingCapacity = 256;
constexpr int kStepTraceIdLen = 48;

// StepRecord.flags
constexpr uint32_t kStepFlagCompile = 0x1;  // step paid a compile

struct StepRingHeader {
  uint32_t magic;
  uint32_t version;
  int32_t capacity;      // records in the ring (kStepRingCapacity)
  int32_t record_size;   // sizeof(StepRecord)
  int32_t writer_pid;
  int32_t pad_;
  uint64_t writes;       // total records ever published (ring head)
  char trace_id[kStepTraceIdLen];  // vtrace join key, NUL-terminated
};
static_assert(sizeof(StepRingHeader) == 80, "StepRingHeader ABI size");
static_assert(offsetof(StepRingHeader, writer_pid) == 16, "ABI");
static_assert(offsetof(StepRingHeader, writes) == 24, "ABI");
static_assert(offsetof(StepRingHeader, trace_id) == 32, "ABI");

struct StepRecord {
  uint64_t seq;          // per-record seqlock (odd = mid-write)
  uint64_t index;        // monotone step index (slot = index % capacity)
  uint64_t start_mono_ns;
  uint64_t duration_ns;
  uint64_t throttle_wait_ns;   // time stalled in the compute throttle
  uint64_t hbm_highwater_bytes;
  uint32_t flags;        // kStepFlag*
  int32_t pad_;
};
static_assert(sizeof(StepRecord) == 56, "StepRecord ABI size");
static_assert(offsetof(StepRecord, index) == 8, "ABI");
static_assert(offsetof(StepRecord, duration_ns) == 24, "ABI");
static_assert(offsetof(StepRecord, throttle_wait_ns) == 32, "ABI");
static_assert(offsetof(StepRecord, hbm_highwater_bytes) == 40, "ABI");
static_assert(offsetof(StepRecord, flags) == 48, "ABI");

constexpr size_t kStepRingFileSize =
    sizeof(StepRingHeader) + kStepRingCapacity * sizeof(StepRecord);

}  // namespace vtpu

#endif  // VTPU_TELEMETRY_H_
