/* shim.h — internal state of libvtpu-control.so, the PJRT interceptor.
 *
 * TPU-native re-design of the reference's LD_PRELOAD CUDA/NVML hook library
 * (reference: library/include/hook.h, library/src/loader.c, cuda_hook.c).
 * Where CUDA needs dlsym shadowing + cuGetProcAddress route tables
 * (loader.c:1780, cuda_hook.c:2408), PJRT gives one sanctioned seam: the
 * plugin's exported GetPjrtApi() returns a table of function pointers. The
 * shim exports GetPjrtApi, dlopens the real libtpu, copies its table, and
 * substitutes wrappers for the entries that matter:
 *
 *   - PJRT_Client_BufferFromHostBuffer / PJRT_Buffer_Destroy /
 *     PJRT_LoadedExecutable_Execute outputs -> HBM accounting + cap OOM
 *   - PJRT_Device_MemoryStats                -> capped view faking
 *   - PJRT_LoadedExecutable_Execute          -> TensorCore-% throttling
 *   - PJRT_Error_Destroy/Message/GetCode     -> sentinel errors (the shim
 *     must mint OOM errors the caller frees via the same API)
 *
 * Enforcement parameters come from the mmap'd vtpu.config written by the
 * device plugin, or are synthesized from env vars when the file is absent
 * (reference: load_controller_configuration loader.c:2483,
 * init_g_vgpu_config_by_env loader.c:2357).
 */
#ifndef VTPU_SHIM_H_
#define VTPU_SHIM_H_

#include <atomic>
#include <cstdint>
#include <mutex>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "vtpu_config.h"
#include "xla/pjrt/c/pjrt_c_api.h"

namespace vtpu {

// ---------------------------------------------------------------------------
// Logging (reference hook.h:443-454: leveled stderr logger, LOGGER_LEVEL)
// ---------------------------------------------------------------------------

enum LogLevel { kLogError = 0, kLogWarn = 1, kLogInfo = 2, kLogDebug = 3 };
extern int g_log_level;
void LogF(LogLevel level, const char* fmt, ...);
#define VTPU_LOG(level, ...)                         \
  do {                                               \
    if ((level) <= ::vtpu::g_log_level) ::vtpu::LogF(level, __VA_ARGS__); \
  } while (0)

// ---------------------------------------------------------------------------
// Sampled metrics counters (reference metrics.c: power-of-two sampling)
// ---------------------------------------------------------------------------

struct Counter {
  const char* name;
  std::atomic<uint64_t> count{0};
  void Bump();  // logs at powers of two
};

struct Metrics {
  Counter oom_rejected{"oom_rejected"};
  Counter mem_charged{"mem_charged"};
  // vtovc spill tier: cold-buffer demotions to the host pool, refills
  // on next touch, and physical-exhaustion rejections the spill arm
  // could NOT absorb (no cold candidates / budget exhausted)
  Counter spills{"spills"};
  Counter fills{"fills"};
  Counter spill_rejected{"spill_rejected"};
  Counter throttle_waits{"throttle_waits"};
  Counter gap_throttles{"gap_throttles"};
  Counter watcher_ticks{"watcher_ticks"};
  Counter watcher_external{"watcher_external"};
  Counter watcher_fallback{"watcher_self_estimate"};
  Counter execs{"execs"};
  Counter exec_done{"exec_done"};
  Counter aimd_md_events{"aimd_md_events"};
  // vtqm: adopted quota-market lease generations (config re-reads that
  // actually changed the enforced rates)
  Counter quota_reloads{"quota_reloads"};
  // vtici: multi-chip (collective-heavy) submissions that blocked in
  // the ICI link-share token bucket (ici_link_pct shaping)
  Counter ici_throttle_waits{"ici_throttle_waits"};
  // vtcc: Execute-path compile-cache client outcomes (non-Python
  // tenants arming off the config header's compile_cache_dir)
  Counter compile_cache_hits{"compile_cache_hits"};
  Counter compile_cache_misses{"compile_cache_misses"};
};
extern Metrics g_metrics;

// ---------------------------------------------------------------------------
// Per-device enforcement state
// ---------------------------------------------------------------------------

// Cacheline-isolated hot state (reference dev_hot_t, cuda_hook.c:106-119).
struct alignas(128) DeviceHot {
  std::atomic<int64_t> used_bytes{0};      // this process's HBM on the chip
  std::atomic<int64_t> peak_bytes{0};
  std::atomic<int64_t> tokens_us{0};       // busy-microsecond budget
  std::atomic<int64_t> grant_us{0};        // current per-window grant
  std::atomic<uint64_t> last_submit_ns{0};
  std::atomic<uint64_t> busy_ns_window{0};   // self-measured busy time
  std::atomic<int64_t> precharged_us{0};     // submit-time token deductions
  std::atomic<int64_t> submits_window{0};    // Execute submissions this tick
  std::atomic<int64_t> blind_cost_us{0};     // feed-derived per-submission
                                             // cost when self-blind
  std::atomic<bool> blind{true};             // self-observation starved
                                             // (default: unproven)
  std::atomic<int64_t> inflight{0};
  std::atomic<int> up_limit{0};            // balance mode elastic target (%)
  std::atomic<bool> throttled_since_watch{false};
  std::atomic<int> vmem_idx{-1};           // cached own vmem-ledger slot
  std::atomic<uint64_t> vmem_retry_ns{0};  // ledger-full claim backoff
  // vtovc: this process's live host-pool bytes for the chip (published
  // to the vmem entry's spilled field, bounded by spill_budget_bytes)
  std::atomic<int64_t> spilled_bytes{0};
  // vtici ICI link-share bucket (armed when ici_link_pct in (0,100)):
  // link-time microsecond budget refilled at ici_link_pct% of wall
  // time, charged only by multi-chip dispatch — the collective-heavy
  // pattern whose traffic occupies ICI links. Separate from tokens_us
  // on purpose: a tenant may be under its core share yet over its
  // link share (and vice versa).
  std::atomic<int64_t> ici_tokens_us{0};
  std::atomic<uint64_t> ici_last_refill_ns{0};
  // vtcomm honest ICI currency (armed by VTPU_COMM_TELEMETRY): EMA of
  // this slot's MEASURED multi-chip (collective) spans + the wall
  // stamp of the newest sample. While fresh (CommCostUs), the ICI
  // bucket charges this instead of the exec-cost EMA — the exec EMA
  // prices the whole program, this prices the dispatch shape that
  // actually occupies links. Unarmed, both stay 0 and the bucket's
  // currency is byte-identical to pre-v3.
  std::atomic<int64_t> comm_cost_us{0};
  std::atomic<uint64_t> comm_last_ns{0};
  // Observation-overhead calibration: host-observed completion spans carry
  // a fixed per-op transport+observation latency (remote PJRT tunnels add
  // ~ms of RTT to every span). An idle-time probe (min of an H2D and a D2H
  // round trip ≈ zero device work) measures it; isolated spans are
  // discounted by the min-filtered estimate (a latency FLOOR — downward
  // moves apply immediately, upward only drifts) so low-quota tenants are
  // not charged for transport time the chip never saw.
  std::atomic<int64_t> obs_overhead_us{0};
  std::atomic<int> obs_samples{0};
  // Discount actually applied to the previous span (0 when it was
  // classified overlapped): the observed idle gap underestimates true idle
  // by exactly the previous END's inflation, and the discount we charged
  // off that span is our estimate of that inflation — feeding it back is
  // exact where the old gap+excess(gap) proxy over-inflated after a
  // back-to-back span (advisor r2: bounded over-discount, slope×max-excess).
  std::atomic<int64_t> last_discount_us{0};
};
static_assert(sizeof(DeviceHot) % 128 == 0, "cacheline isolation");

struct ShimState {
  const PJRT_Api* real_api = nullptr;
  PJRT_Api wrapped_api;           // copy with substituted entries
  VtpuConfig config{};            // loaded or env-synthesized
  bool enforce = false;           // config present and not disabled
  int device_count = 0;
  DeviceHot hot[kMaxDeviceCount];
  // PJRT local device ordinal -> slot in config.devices (-1 = unmanaged)
  int slot_by_ordinal[kMaxDeviceCount];
  // buffer -> tracking record for destroy-time credit. The vtovc spill
  // tier extends the record with an LRU key (last Execute-input touch)
  // and, for buffers whose creation shape was observed, the dims/type
  // needed to re-materialize them from a host copy — only those are
  // spill candidates (a buffer we could not recreate must never be
  // demoted).
  struct BufRec {
    int slot = -1;
    int64_t bytes = 0;
    uint64_t last_touch_ns = 0;          // LRU by last-Execute touch
    bool spillable = false;
    std::vector<int64_t> dims;
    PJRT_Buffer_Type type = PJRT_Buffer_Type_INVALID;
  };
  std::mutex buffers_mu;
  std::unordered_map<PJRT_Buffer*, BufRec> buffers;
  // vtovc spill tier (armed when VTPU_SPILL_POOL_DIR is injected AND a
  // device's virtual_hbm_bytes exceeds its physical capacity):
  // `spilled` holds demoted buffers — original handle -> host copy —
  // whose HBM was freed via PJRT_Buffer_Delete; `spill_fwd` maps a
  // demoted-then-refilled original to its live replacement so Execute
  // argument lists (and D2H readbacks) are transparently rewritten.
  // Both under spill_mu (never taken inside buffers_mu).
  struct SpillRec {
    int slot = -1;
    int64_t bytes = 0;
    void* host = nullptr;                // malloc'd host-pool block
    std::vector<int64_t> dims;
    PJRT_Buffer_Type type = PJRT_Buffer_Type_INVALID;
  };
  std::mutex spill_mu;
  std::unordered_map<PJRT_Buffer*, SpillRec> spilled;
  std::unordered_map<PJRT_Buffer*, PJRT_Buffer*> spill_fwd;
  // async H2D transfer managers: bytes are reserved when the manager is
  // created (CreateBuffersForAsyncHostToDevice); each buffer's share moves
  // to `buffers` on RetrieveBuffer, and unretrieved shares are credited
  // back when the manager is destroyed.
  struct TmRec {
    int slot = -1;
    std::vector<int64_t> bytes;
    std::vector<char> retrieved;
  };
  std::mutex tms_mu;
  std::unordered_map<PJRT_AsyncHostToDeviceTransferManager*, TmRec> tms;
  // executable -> EMA cost in device-busy microseconds + static facts;
  // both evicted on PJRT_LoadedExecutable_Destroy (pointer reuse must not
  // serve a new executable the old one's cost/gate data)
  std::mutex cost_mu;
  std::unordered_map<PJRT_LoadedExecutable*, double> exec_cost_us;
  // vtcomm: executables ever launched multi-chip (the collective-heavy
  // dispatch shape); their measured spans feed the per-slot comm EMA.
  // Evicted with exec_cost_us on LoadedExecutable_Destroy.
  std::unordered_set<PJRT_LoadedExecutable*> multichip_exes;
  struct ExecFactsEntry {
    size_t num_outputs = 0;
    int64_t gate_bytes = 0;
  };
  std::unordered_map<PJRT_LoadedExecutable*, ExecFactsEntry> exec_facts;
  // tc_util external feed (mapped readonly if present)
  const TcUtilFile* tc_file = nullptr;
  // v2 feed's calibration block (daemon-published excess table); read
  // each watcher tick so live recalibrations reach running shims
  const TcCalibration* tc_cal = nullptr;
  // Handles captured opportunistically from wrapped calls so the
  // observation-overhead probe can issue its own (real-API) operations.
  std::atomic<PJRT_Client*> probe_client{nullptr};
  std::atomic<PJRT_Device*> probe_device[kMaxDeviceCount]{};
};

ShimState& State();

// loader.cc
const PJRT_Api* RealApi();
bool LoadConfig();                    // vtpu.config mmap or env synthesis
void StartWatcherOnce();
int SlotForDevice(PJRT_Device* device);      // -1 if unmanaged
const VtpuDevice* DeviceCfg(int slot);

// error.cc — sentinel PJRT_Error minting
PJRT_Error* MakeError(PJRT_Error_Code code, const char* fmt, ...);
bool IsOurError(const PJRT_Error* err);
void WrapErrorEntries(PJRT_Api* api);
// Destroy an error returned by a forwarded real-API call (hot paths must
// not leak the heap object); returns true if there was an error.
bool ConsumeError(PJRT_Error* err);

// enforce.cc — memory + compute hooks
void WrapEnforcementEntries(PJRT_Api* api);
struct LedgerBytes {
  int64_t siblings;  // our tenant's other processes (share our cap)
  int64_t others;    // other tenants (count against physical HBM only)
};
LedgerBytes ScanLedgerBytes(int slot);
int64_t OtherProcsBytes(int slot);    // vmem-ledger view of co-tenants
void RecordOwnBytes(int slot);        // publish to the ledger

// throttle (in enforce.cc)
void RateLimit(int slot, int64_t cost_us);
void OnExecuteDone(int slot, PJRT_LoadedExecutable* exe, uint64_t start_ns,
                   uint64_t end_ns, bool measured = true);

uint64_t NowNs();

}  // namespace vtpu

#endif  // VTPU_SHIM_H_
