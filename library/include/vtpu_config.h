/* vtpu_config.h — C++ side of the L3 binary ABI.
 *
 * Mirror of vtpu_manager/config/vtpu_config.py (the Python writer) and
 * tc_watcher.py / vmem.py. The reference keeps the same contract between Go
 * and C (reference: pkg/config/vgpu/vgpu_config.go:19-57 <-> hook.h:198-226)
 * and asserts it with layout tests; tests/test_config_abi.py compiles this
 * header and compares offsets with the Python structs.
 *
 * Layout rules: little-endian, explicit padding, 8-byte alignment,
 * NUL-terminated fixed strings, FNV-1a footer checksum.
 */
#ifndef VTPU_CONFIG_H_
#define VTPU_CONFIG_H_

#include <cstddef>
#include <cstdint>
#include <cstdlib>

namespace vtpu {

constexpr uint32_t kConfigMagic = 0x55505456;  // "VTPU"
// v2: header grew compile_cache_dir[kCacheDirLen] (vtcc); strict
// version check — plugin and shim ship together per node.
// v3 (vtqm): header grew workload_class + quota_epoch (the quota-market
// lease generation — the shim's token-wait loop re-reads the config
// when the on-disk epoch moves, bounding revoke-to-enforcement at one
// throttle quantum + one re-read); the device pad became lease_core
// (signed borrowed/lent core-% delta).
// v4 (vtovc): the device struct grew virtual_hbm_bytes (scheduler-
// admitted VIRTUAL chip capacity; > real_memory arms the spill tier)
// and spill_budget_bytes (node host-RAM budget bounding Σ spilled in
// the vmem ledger). Gate off writes zeros — v3 semantics byte-for-byte.
// v5 (vtici): the device struct grew ici_link_pct (the tenant's ICI
// link-bandwidth share for collective-heavy — multi-chip — dispatch;
// the shim shapes it with a dedicated token bucket) + explicit pad.
// 0 = unshaped; gate off writes zeros — v4 semantics byte-for-byte.
// v6 (vtpilot): header grew migration_freeze (i32 bool — the
// autopilot's freeze request: the shim parks dispatch at the
// token-wait entry and drains in-flight Executes while set, with a
// bounded fail-open so a dead controller never parks a tenant
// forever) + freeze_epoch (u32, bumped on every freeze/unfreeze
// transition; rides the quota_epoch adoption channel so a parked
// shim sees the flag within one throttle quantum). Gate off writes
// zeros in both — v5 semantics byte-for-byte.
constexpr uint32_t kConfigVersion = 6;
constexpr int kMaxDeviceCount = 64;
constexpr int kUuidLen = 64;
constexpr int kNameLen = 64;
constexpr int kPodUidLen = 48;
constexpr int kCacheDirLen = 64;

// Workload classes (vtqm, webhook-stamped).
enum WorkloadClass : int32_t {
  kWorkloadNone = 0,        // unclassified: never lends, never borrows
  kWorkloadLatency = 1,     // latency-critical serving (borrower side)
  kWorkloadThroughput = 2,  // throughput training (lender side)
};

enum CoreLimit : int32_t {
  kCoreLimitNone = 0,
  kCoreLimitHard = 1,  // fixed policy: clamp at hard_core
  kCoreLimitSoft = 2,  // balance policy: elastic hard_core..soft_core
};

// Compatibility-mode bitmask (reference hook.h:386-392).
enum CompatMode : int32_t {
  kCompatHost = 0x01,
  kCompatCgroup = 0x02,
  kCompatClient = 0x04,
  kCompatOpenKernel = 0x08,
};

struct VtpuDevice {
  char uuid[kUuidLen];
  uint64_t total_memory;    // HBM cap bytes (inflated when oversold)
  uint64_t real_memory;     // physical HBM bytes
  int32_t hard_core;        // percent
  int32_t soft_core;        // percent (balance ceiling)
  int32_t core_limit;       // CoreLimit
  int32_t memory_limit;     // bool
  int32_t memory_oversold;  // bool
  int32_t host_index;
  int32_t mesh_x;
  int32_t mesh_y;
  int32_t mesh_z;
  // vtqm: signed quota-lease core-% delta (>0 borrowed, <0 lent; the
  // v2 pad — 0 means no lease). Effective rate =
  // clamp(hard_core + lease_core, 0, 100).
  int32_t lease_core;
  // vtovc (v4; both 0 when HBMOvercommit is off): the chip's VIRTUAL
  // capacity the scheduler admitted against — when > real_memory the
  // physical-exhaustion check spills cold buffers to the host pool
  // instead of hard-failing — and the node's host-RAM spill budget
  // (bound on Σ spilled bytes across tenants, vmem-ledger accounted).
  uint64_t virtual_hbm_bytes;
  uint64_t spill_budget_bytes;
  // vtici (v5; 0 when ICILinkAware is off): percentage of the node's
  // ICI link bandwidth this tenant's multi-chip dispatch may consume.
  // 0 or >= 100 = unshaped; the ICI token bucket arms only in (0,100).
  int32_t ici_link_pct;
  uint32_t ici_pad_;
};
static_assert(sizeof(VtpuDevice) == 144, "VtpuDevice ABI size");
static_assert(offsetof(VtpuDevice, total_memory) == 64, "ABI");
static_assert(offsetof(VtpuDevice, hard_core) == 80, "ABI");
static_assert(offsetof(VtpuDevice, mesh_x) == 104, "ABI");
static_assert(offsetof(VtpuDevice, lease_core) == 116, "ABI");
static_assert(offsetof(VtpuDevice, virtual_hbm_bytes) == 120, "ABI");
static_assert(offsetof(VtpuDevice, spill_budget_bytes) == 128, "ABI");
static_assert(offsetof(VtpuDevice, ici_link_pct) == 136, "ABI");

struct VtpuConfig {
  uint32_t magic;
  uint32_t version;
  char pod_uid[kPodUidLen];
  char pod_name[kNameLen];
  char pod_namespace[kNameLen];
  char container_name[kNameLen];
  int32_t device_count;
  int32_t compat_mode;
  // vtcc: in-container node-shared compile cache mount; empty string =
  // CompileCache off for this container (the shim arms only when set)
  char compile_cache_dir[kCacheDirLen];
  int32_t workload_class;  // WorkloadClass (vtqm; 0 when gate off)
  // vtqm lease generation: bumped by the market manager on every
  // grant/revoke written into this config. The shim compares the
  // on-disk value against the loaded one in its token-wait loop.
  uint32_t quota_epoch;
  // vtpilot (v6; both 0 when SLOAutopilot is off): the autopilot's
  // freeze request. Non-zero migration_freeze parks dispatch at the
  // token-wait entry and drains in-flight Executes; freeze_epoch
  // bumps on every freeze/unfreeze transition and is adopted through
  // the same epoch re-read loop as quota_epoch.
  int32_t migration_freeze;
  uint32_t freeze_epoch;
  VtpuDevice devices[kMaxDeviceCount];
  uint32_t checksum;  // FNV-1a over all preceding bytes
  uint32_t pad_;
};
static_assert(offsetof(VtpuConfig, device_count) == 248, "ABI");
static_assert(offsetof(VtpuConfig, compile_cache_dir) == 256, "ABI");
static_assert(offsetof(VtpuConfig, workload_class) == 320, "ABI");
static_assert(offsetof(VtpuConfig, quota_epoch) == 324, "ABI");
static_assert(offsetof(VtpuConfig, migration_freeze) == 328, "ABI");
static_assert(offsetof(VtpuConfig, freeze_epoch) == 332, "ABI");
static_assert(offsetof(VtpuConfig, devices) == 336, "ABI");
static_assert(sizeof(VtpuConfig) == 336 + 64 * 144 + 8, "VtpuConfig ABI");

inline uint64_t Fnv1a64(const char* data) {
  uint64_t h = 0xCBF29CE484222325ull;
  for (const char* p = data; *p; ++p) {
    h ^= (uint64_t)(unsigned char)*p;
    h *= 0x100000001B3ull;
  }
  return h;
}

inline uint32_t Fnv1a(const uint8_t* data, size_t len) {
  uint32_t h = 0x811C9DC5u;
  for (size_t i = 0; i < len; ++i) {
    h ^= data[i];
    h *= 0x01000193u;
  }
  return h;
}

// ---------------------------------------------------------------------------
// tc_util.config (node watcher feed; seqlock per record)
// ---------------------------------------------------------------------------

constexpr uint32_t kTcUtilMagic = 0x55544356;  // "VCTU"
constexpr int kMaxProcs = 32;

struct TcProcUtil {
  int32_t pid;
  int32_t util;          // percent of the chip
  uint64_t mem_used;     // bytes
  uint64_t owner_token;  // namespace-independent tenant identity
};
static_assert(sizeof(TcProcUtil) == 24, "ABI");

struct TcDeviceRecord {
  uint64_t seq;           // seqlock: odd while writing
  uint64_t timestamp_ns;  // writer CLOCK_MONOTONIC
  int32_t device_util;    // chip duty-cycle percent
  int32_t proc_count;
  TcProcUtil procs[kMaxProcs];
};
static_assert(sizeof(TcDeviceRecord) == 24 + 32 * 24, "ABI");

struct TcUtilFile {
  uint32_t magic;
  uint32_t version;
  int32_t device_count;
  int32_t pad_;
  TcDeviceRecord records[kMaxDeviceCount];
};
static_assert(offsetof(TcUtilFile, records) == 16, "ABI");
static_assert(sizeof(TcUtilFile) == 16 + 64 * (24 + 32 * 24), "ABI");

// v2 appends one transport-calibration block after the records: the node
// daemon's measured span-inflation excess table (obs_calibrate.py),
// live-updatable so running shims follow transport regime changes that
// env-injected tables (frozen at container start) cannot. One block per
// host — the transport is per-host, not per-chip. Same seqlock
// discipline as the device records.
constexpr uint32_t kTcUtilVersion2 = 2;
constexpr int kMaxExcessPoints = 8;

struct TcCalibration {
  uint64_t seq;           // seqlock: odd while writing
  uint64_t timestamp_ns;  // writer CLOCK_MONOTONIC at calibration time
  int32_t n_points;
  int32_t pad_;
  int64_t gap_us[kMaxExcessPoints];
  int64_t excess_us[kMaxExcessPoints];
};
static_assert(sizeof(TcCalibration) == 24 + 2 * 8 * 8, "ABI");

// ---------------------------------------------------------------------------
// vmem_node.config (cross-process memory ledger)
// ---------------------------------------------------------------------------

constexpr uint32_t kVmemMagic = 0x4D454D56;  // "VMEM"
// v3 (vtovc): entries grew a trailing spilled u64 — the tenant's live
// host-pool footprint. Resident (`bytes`) and spilled are disjoint: the
// alloc-path cap check sums resident only, the node spill budget bounds
// Σ spilled, and a dead+stale entry's reap reclaims both at once.
constexpr uint32_t kVmemVersion = 3;
constexpr int kVmemMaxEntries = 1024;

struct VmemEntry {
  int32_t pid;  // 0 = free slot (pid is namespace-local; identity below)
  int32_t host_index;
  uint64_t bytes;
  uint64_t last_update_ns;
  uint64_t owner_token;  // namespace-independent tenant identity
  uint64_t activity;     // monotonic submit counter; the node watcher
                         // apportions chip duty-cycle by per-tick deltas
  uint64_t spilled;      // v3: live host-RAM spill-pool bytes
};
static_assert(sizeof(VmemEntry) == 48, "ABI");
static_assert(offsetof(VmemEntry, spilled) == 40, "ABI");

struct VmemFile {
  uint32_t magic;
  uint32_t version;
  int32_t max_entries;
  int32_t pad_;
  VmemEntry entries[kVmemMaxEntries];
};
static_assert(sizeof(VmemFile) == 16 + 1024 * 48, "ABI");

// Dead-entry staleness window — the SHARED clamp contract with Python's
// vmem._stale_reap_ns (VTPU_VMEM_STALE_S): unparsable/<=0/NaN fall back
// to 120 s, huge values clamp to 1e10 s BEFORE the fp->int conversion
// (overflow there is UB). Header-inline so enforce.cc and the g++-probe
// parity row in tests/test_config_abi.py compile the SAME function —
// the v3 spilled field makes divergent reaping load-bearing: a side
// that reaps earlier would free spill budget the other still charges.
inline uint64_t VmemStaleReapNsFromEnv(const char* v) {
  double s = v ? atof(v) : 120.0;
  if (!(s > 0)) s = 120.0;  // catches 0, negatives, NaN and garbage
  if (s > 1e10) s = 1e10;   // ~317 years: effectively never, still finite
  return (uint64_t)(s * 1e9);
}

// vtovc Execute-output shape capture (vtovc item (b)) — the SHARED
// spill-recipe rule with Python's overcommit.spill mirror, header-
// inline so enforce.cc and the g++-probe parity row compile the SAME
// functions. A captured (dims, element-type) pair is only a safe
// re-materialization recipe when the LOGICAL size it implies equals
// the buffer's on-device size: a padded/tiled layout spilled as a
// flat host copy would refill into a differently-sized buffer, and a
// zero-element or overflowing shape is no recipe at all.
inline int64_t SpillLogicalBytes(const int64_t* dims, size_t num_dims,
                                 int64_t elem_bytes) {
  if (elem_bytes <= 0) return 0;
  const int64_t kCap = 9000000000000000000LL;  // overflow guard
  int64_t elems = 1;
  for (size_t i = 0; i < num_dims; i++) {
    int64_t d = dims ? dims[i] : 0;
    if (d <= 0) return 0;          // zero/negative dim: no recipe
    if (elems > kCap / d) return 0;
    elems *= d;
  }
  if (elems > kCap / elem_bytes) return 0;
  return elems * elem_bytes;
}

inline bool SpillShapeCaptureOk(int64_t logical_bytes,
                                int64_t on_device_bytes) {
  return logical_bytes > 0 && logical_bytes == on_device_bytes;
}

// ---------------------------------------------------------------------------
// pids.config (CLIENT compat mode: registry-attested container pid set)
// ---------------------------------------------------------------------------

constexpr uint32_t kPidsMagic = 0x53444950;  // "PIDS"

struct PidsFileHeader {
  uint32_t magic;
  uint32_t version;
  int32_t count;
  int32_t pad_;
  // followed by count little-endian int32 pids
};
static_assert(sizeof(PidsFileHeader) == 16, "ABI");

}  // namespace vtpu

#endif  // VTPU_CONFIG_H_
