# Empty dependencies file for vtpu-control.
# This may be replaced when dependencies are built.
