
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/library/src/enforce.cc" "CMakeFiles/vtpu-control.dir/src/enforce.cc.o" "gcc" "CMakeFiles/vtpu-control.dir/src/enforce.cc.o.d"
  "/root/repo/library/src/error.cc" "CMakeFiles/vtpu-control.dir/src/error.cc.o" "gcc" "CMakeFiles/vtpu-control.dir/src/error.cc.o.d"
  "/root/repo/library/src/loader.cc" "CMakeFiles/vtpu-control.dir/src/loader.cc.o" "gcc" "CMakeFiles/vtpu-control.dir/src/loader.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
