CMakeFiles/vtpu-control.dir/src/error.cc.o: \
 /root/repo/library/src/error.cc /usr/include/stdc-predef.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stdarg.h /usr/include/stdio.h \
 /usr/include/x86_64-linux-gnu/bits/libc-header-start.h \
 /usr/include/features.h /usr/include/features-time64.h \
 /usr/include/x86_64-linux-gnu/bits/wordsize.h \
 /usr/include/x86_64-linux-gnu/bits/timesize.h \
 /usr/include/x86_64-linux-gnu/sys/cdefs.h \
 /usr/include/x86_64-linux-gnu/bits/long-double.h \
 /usr/include/x86_64-linux-gnu/gnu/stubs.h \
 /usr/include/x86_64-linux-gnu/gnu/stubs-64.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stddef.h \
 /usr/include/x86_64-linux-gnu/bits/types.h \
 /usr/include/x86_64-linux-gnu/bits/typesizes.h \
 /usr/include/x86_64-linux-gnu/bits/time64.h \
 /usr/include/x86_64-linux-gnu/bits/types/__fpos_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__mbstate_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__fpos64_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__FILE.h \
 /usr/include/x86_64-linux-gnu/bits/types/FILE.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_FILE.h \
 /usr/include/x86_64-linux-gnu/bits/types/cookie_io_functions_t.h \
 /usr/include/x86_64-linux-gnu/bits/stdio_lim.h \
 /usr/include/x86_64-linux-gnu/bits/floatn.h \
 /usr/include/x86_64-linux-gnu/bits/floatn-common.h \
 /usr/include/x86_64-linux-gnu/bits/stdio.h /usr/include/string.h \
 /usr/include/x86_64-linux-gnu/bits/types/locale_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__locale_t.h \
 /usr/include/strings.h /root/repo/library/include/shim.h \
 /usr/include/c++/12/atomic /usr/include/c++/12/bits/atomic_base.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/c++config.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/os_defines.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/cpu_defines.h \
 /usr/include/c++/12/pstl/pstl_config.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stdint.h /usr/include/stdint.h \
 /usr/include/x86_64-linux-gnu/bits/wchar.h \
 /usr/include/x86_64-linux-gnu/bits/stdint-intn.h \
 /usr/include/x86_64-linux-gnu/bits/stdint-uintn.h \
 /usr/include/c++/12/bits/atomic_lockfree_defines.h \
 /usr/include/c++/12/bits/move.h /usr/include/c++/12/type_traits \
 /usr/include/c++/12/cstdint /usr/include/c++/12/mutex \
 /usr/include/c++/12/tuple /usr/include/c++/12/bits/stl_pair.h \
 /usr/include/c++/12/bits/utility.h \
 /usr/include/c++/12/bits/uses_allocator.h \
 /usr/include/c++/12/bits/invoke.h /usr/include/c++/12/exception \
 /usr/include/c++/12/bits/exception.h \
 /usr/include/c++/12/bits/exception_ptr.h \
 /usr/include/c++/12/bits/exception_defines.h \
 /usr/include/c++/12/bits/cxxabi_init_exception.h \
 /usr/include/c++/12/typeinfo /usr/include/c++/12/bits/hash_bytes.h \
 /usr/include/c++/12/new /usr/include/c++/12/bits/nested_exception.h \
 /usr/include/c++/12/system_error \
 /usr/include/x86_64-linux-gnu/c++/12/bits/error_constants.h \
 /usr/include/c++/12/cerrno /usr/include/errno.h \
 /usr/include/x86_64-linux-gnu/bits/errno.h /usr/include/linux/errno.h \
 /usr/include/x86_64-linux-gnu/asm/errno.h \
 /usr/include/asm-generic/errno.h /usr/include/asm-generic/errno-base.h \
 /usr/include/x86_64-linux-gnu/bits/types/error_t.h \
 /usr/include/c++/12/iosfwd /usr/include/c++/12/bits/stringfwd.h \
 /usr/include/c++/12/bits/memoryfwd.h /usr/include/c++/12/bits/postypes.h \
 /usr/include/c++/12/cwchar /usr/include/wchar.h \
 /usr/include/x86_64-linux-gnu/bits/types/wint_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/mbstate_t.h \
 /usr/include/c++/12/stdexcept /usr/include/c++/12/string \
 /usr/include/c++/12/bits/char_traits.h \
 /usr/include/c++/12/bits/allocator.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/c++allocator.h \
 /usr/include/c++/12/bits/new_allocator.h \
 /usr/include/c++/12/bits/functexcept.h \
 /usr/include/c++/12/bits/cpp_type_traits.h \
 /usr/include/c++/12/bits/localefwd.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/c++locale.h \
 /usr/include/c++/12/clocale /usr/include/locale.h \
 /usr/include/x86_64-linux-gnu/bits/locale.h /usr/include/c++/12/cctype \
 /usr/include/ctype.h /usr/include/x86_64-linux-gnu/bits/endian.h \
 /usr/include/x86_64-linux-gnu/bits/endianness.h \
 /usr/include/c++/12/bits/ostream_insert.h \
 /usr/include/c++/12/bits/cxxabi_forced.h \
 /usr/include/c++/12/bits/stl_iterator_base_types.h \
 /usr/include/c++/12/bits/stl_iterator_base_funcs.h \
 /usr/include/c++/12/bits/concept_check.h \
 /usr/include/c++/12/debug/assertions.h \
 /usr/include/c++/12/bits/stl_iterator.h \
 /usr/include/c++/12/ext/type_traits.h \
 /usr/include/c++/12/bits/ptr_traits.h \
 /usr/include/c++/12/bits/stl_function.h \
 /usr/include/c++/12/backward/binders.h \
 /usr/include/c++/12/ext/numeric_traits.h \
 /usr/include/c++/12/bits/stl_algobase.h \
 /usr/include/c++/12/debug/debug.h \
 /usr/include/c++/12/bits/predefined_ops.h \
 /usr/include/c++/12/bits/refwrap.h \
 /usr/include/c++/12/bits/range_access.h \
 /usr/include/c++/12/initializer_list \
 /usr/include/c++/12/bits/basic_string.h \
 /usr/include/c++/12/ext/alloc_traits.h \
 /usr/include/c++/12/bits/alloc_traits.h \
 /usr/include/c++/12/bits/stl_construct.h /usr/include/c++/12/string_view \
 /usr/include/c++/12/bits/functional_hash.h \
 /usr/include/c++/12/bits/string_view.tcc \
 /usr/include/c++/12/ext/string_conversions.h /usr/include/c++/12/cstdlib \
 /usr/include/stdlib.h /usr/include/x86_64-linux-gnu/bits/waitflags.h \
 /usr/include/x86_64-linux-gnu/bits/waitstatus.h \
 /usr/include/x86_64-linux-gnu/sys/types.h \
 /usr/include/x86_64-linux-gnu/bits/types/clock_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/clockid_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/time_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/timer_t.h /usr/include/endian.h \
 /usr/include/x86_64-linux-gnu/bits/byteswap.h \
 /usr/include/x86_64-linux-gnu/bits/uintn-identity.h \
 /usr/include/x86_64-linux-gnu/sys/select.h \
 /usr/include/x86_64-linux-gnu/bits/select.h \
 /usr/include/x86_64-linux-gnu/bits/types/sigset_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__sigset_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_timeval.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_timespec.h \
 /usr/include/x86_64-linux-gnu/bits/pthreadtypes.h \
 /usr/include/x86_64-linux-gnu/bits/thread-shared-types.h \
 /usr/include/x86_64-linux-gnu/bits/pthreadtypes-arch.h \
 /usr/include/x86_64-linux-gnu/bits/atomic_wide_counter.h \
 /usr/include/x86_64-linux-gnu/bits/struct_mutex.h \
 /usr/include/x86_64-linux-gnu/bits/struct_rwlock.h /usr/include/alloca.h \
 /usr/include/x86_64-linux-gnu/bits/stdlib-bsearch.h \
 /usr/include/x86_64-linux-gnu/bits/stdlib-float.h \
 /usr/include/c++/12/bits/std_abs.h /usr/include/c++/12/cstdio \
 /usr/include/c++/12/bits/charconv.h \
 /usr/include/c++/12/bits/basic_string.tcc \
 /usr/include/c++/12/bits/chrono.h /usr/include/c++/12/ratio \
 /usr/include/c++/12/limits /usr/include/c++/12/ctime /usr/include/time.h \
 /usr/include/x86_64-linux-gnu/bits/time.h \
 /usr/include/x86_64-linux-gnu/bits/timex.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_tm.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_itimerspec.h \
 /usr/include/c++/12/bits/parse_numbers.h \
 /usr/include/c++/12/bits/std_mutex.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/gthr.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/gthr-default.h \
 /usr/include/pthread.h /usr/include/sched.h \
 /usr/include/x86_64-linux-gnu/bits/sched.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_sched_param.h \
 /usr/include/x86_64-linux-gnu/bits/cpu-set.h \
 /usr/include/x86_64-linux-gnu/bits/setjmp.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct___jmp_buf_tag.h \
 /usr/include/x86_64-linux-gnu/bits/pthread_stack_min-dynamic.h \
 /usr/include/c++/12/bits/unique_lock.h \
 /usr/include/c++/12/ext/atomicity.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/atomic_word.h \
 /usr/include/x86_64-linux-gnu/sys/single_threaded.h \
 /usr/include/c++/12/unordered_map \
 /usr/include/c++/12/ext/aligned_buffer.h \
 /usr/include/c++/12/bits/hashtable.h \
 /usr/include/c++/12/bits/hashtable_policy.h \
 /usr/include/c++/12/bits/enable_special_members.h \
 /usr/include/c++/12/bits/node_handle.h \
 /usr/include/c++/12/bits/unordered_map.h \
 /usr/include/c++/12/bits/erase_if.h \
 /root/repo/library/include/vtpu_config.h /usr/include/c++/12/cstddef \
 /opt/venv/lib/python3.12/site-packages/tensorflow/include/xla/pjrt/c/pjrt_c_api.h \
 /usr/include/assert.h /usr/lib/gcc/x86_64-linux-gnu/12/include/stdbool.h
