file(REMOVE_RECURSE
  "CMakeFiles/vtpu-control.dir/src/enforce.cc.o"
  "CMakeFiles/vtpu-control.dir/src/enforce.cc.o.d"
  "CMakeFiles/vtpu-control.dir/src/error.cc.o"
  "CMakeFiles/vtpu-control.dir/src/error.cc.o.d"
  "CMakeFiles/vtpu-control.dir/src/loader.cc.o"
  "CMakeFiles/vtpu-control.dir/src/loader.cc.o.d"
  "libvtpu-control.pdb"
  "libvtpu-control.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/vtpu-control.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
