CMakeFiles/shim_test.dir/test/shim_test.cc.o: \
 /root/repo/library/test/shim_test.cc /usr/include/stdc-predef.h \
 /usr/include/dlfcn.h /usr/include/features.h \
 /usr/include/features-time64.h \
 /usr/include/x86_64-linux-gnu/bits/wordsize.h \
 /usr/include/x86_64-linux-gnu/bits/timesize.h \
 /usr/include/x86_64-linux-gnu/sys/cdefs.h \
 /usr/include/x86_64-linux-gnu/bits/long-double.h \
 /usr/include/x86_64-linux-gnu/gnu/stubs.h \
 /usr/include/x86_64-linux-gnu/gnu/stubs-64.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stddef.h \
 /usr/include/x86_64-linux-gnu/bits/dlfcn.h \
 /usr/include/x86_64-linux-gnu/bits/dl_find_object.h /usr/include/stdio.h \
 /usr/include/x86_64-linux-gnu/bits/libc-header-start.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stdarg.h \
 /usr/include/x86_64-linux-gnu/bits/types.h \
 /usr/include/x86_64-linux-gnu/bits/typesizes.h \
 /usr/include/x86_64-linux-gnu/bits/time64.h \
 /usr/include/x86_64-linux-gnu/bits/types/__fpos_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__mbstate_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__fpos64_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__FILE.h \
 /usr/include/x86_64-linux-gnu/bits/types/FILE.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_FILE.h \
 /usr/include/x86_64-linux-gnu/bits/types/cookie_io_functions_t.h \
 /usr/include/x86_64-linux-gnu/bits/stdio_lim.h \
 /usr/include/x86_64-linux-gnu/bits/floatn.h \
 /usr/include/x86_64-linux-gnu/bits/floatn-common.h \
 /usr/include/x86_64-linux-gnu/bits/stdio.h /usr/include/c++/12/stdlib.h \
 /usr/include/c++/12/cstdlib \
 /usr/include/x86_64-linux-gnu/c++/12/bits/c++config.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/os_defines.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/cpu_defines.h \
 /usr/include/c++/12/pstl/pstl_config.h /usr/include/stdlib.h \
 /usr/include/x86_64-linux-gnu/bits/waitflags.h \
 /usr/include/x86_64-linux-gnu/bits/waitstatus.h \
 /usr/include/x86_64-linux-gnu/bits/types/locale_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__locale_t.h \
 /usr/include/x86_64-linux-gnu/sys/types.h \
 /usr/include/x86_64-linux-gnu/bits/types/clock_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/clockid_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/time_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/timer_t.h \
 /usr/include/x86_64-linux-gnu/bits/stdint-intn.h /usr/include/endian.h \
 /usr/include/x86_64-linux-gnu/bits/endian.h \
 /usr/include/x86_64-linux-gnu/bits/endianness.h \
 /usr/include/x86_64-linux-gnu/bits/byteswap.h \
 /usr/include/x86_64-linux-gnu/bits/uintn-identity.h \
 /usr/include/x86_64-linux-gnu/sys/select.h \
 /usr/include/x86_64-linux-gnu/bits/select.h \
 /usr/include/x86_64-linux-gnu/bits/types/sigset_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__sigset_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_timeval.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_timespec.h \
 /usr/include/x86_64-linux-gnu/bits/pthreadtypes.h \
 /usr/include/x86_64-linux-gnu/bits/thread-shared-types.h \
 /usr/include/x86_64-linux-gnu/bits/pthreadtypes-arch.h \
 /usr/include/x86_64-linux-gnu/bits/atomic_wide_counter.h \
 /usr/include/x86_64-linux-gnu/bits/struct_mutex.h \
 /usr/include/x86_64-linux-gnu/bits/struct_rwlock.h /usr/include/alloca.h \
 /usr/include/x86_64-linux-gnu/bits/stdlib-bsearch.h \
 /usr/include/x86_64-linux-gnu/bits/stdlib-float.h \
 /usr/include/c++/12/bits/std_abs.h /usr/include/string.h \
 /usr/include/strings.h /usr/include/time.h \
 /usr/include/x86_64-linux-gnu/bits/time.h \
 /usr/include/x86_64-linux-gnu/bits/timex.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_tm.h \
 /usr/include/x86_64-linux-gnu/bits/types/struct_itimerspec.h \
 /opt/venv/lib/python3.12/site-packages/tensorflow/include/xla/pjrt/c/pjrt_c_api.h \
 /usr/include/assert.h /usr/lib/gcc/x86_64-linux-gnu/12/include/stdbool.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stdint.h /usr/include/stdint.h \
 /usr/include/x86_64-linux-gnu/bits/wchar.h \
 /usr/include/x86_64-linux-gnu/bits/stdint-uintn.h
