file(REMOVE_RECURSE
  "CMakeFiles/shim_test.dir/test/shim_test.cc.o"
  "CMakeFiles/shim_test.dir/test/shim_test.cc.o.d"
  "shim_test"
  "shim_test.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/shim_test.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
