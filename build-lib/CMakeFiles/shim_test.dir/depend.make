# Empty dependencies file for shim_test.
# This may be replaced when dependencies are built.
