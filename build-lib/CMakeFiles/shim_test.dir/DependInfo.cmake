
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/library/test/shim_test.cc" "CMakeFiles/shim_test.dir/test/shim_test.cc.o" "gcc" "CMakeFiles/shim_test.dir/test/shim_test.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
