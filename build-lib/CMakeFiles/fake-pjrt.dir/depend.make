# Empty dependencies file for fake-pjrt.
# This may be replaced when dependencies are built.
