
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/library/test/fake_pjrt_plugin.cc" "CMakeFiles/fake-pjrt.dir/test/fake_pjrt_plugin.cc.o" "gcc" "CMakeFiles/fake-pjrt.dir/test/fake_pjrt_plugin.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
