# CMAKE generated file: DO NOT EDIT!
# Timestamp file for compiler generated dependencies management for fake-pjrt.
