file(REMOVE_RECURSE
  "CMakeFiles/fake-pjrt.dir/test/fake_pjrt_plugin.cc.o"
  "CMakeFiles/fake-pjrt.dir/test/fake_pjrt_plugin.cc.o.d"
  "libfake-pjrt.pdb"
  "libfake-pjrt.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/fake-pjrt.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
