"""Living under an HBM cap with JAX host offload (the oversold story).

The reference's memory-oversold mode leans on CUDA UVA: oversubscribed
tenants spill to host RAM transparently. TPUs have no UVA — the
TPU-native equivalent is EXPLICIT host offload through JAX's memory
kinds: park tensors in `pinned_host` memory and stream them into HBM
when used. The vtpu shim cooperates by design: host memory spaces are
never charged against the HBM cap (enforce.cc SlotForMemory skips
memories whose kind contains "host"), so an oversold tenant can hold a
model larger than its cap as long as the RESIDENT working set fits.

Pattern shown here: layer-streamed inference. All layer weights live in
pinned_host; each step, one layer at a time moves to device, is applied,
and its device copy is dropped — peak HBM is one layer + activations,
not the whole model.

Run (any backend; on a vtpu tenant the cap applies automatically):
    python examples/host_offload_demo.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# honor an explicit CPU request even under an ambient tunnel registration
# (a wedged tunnel would otherwise hang the demo)
from vtpu_manager.util.jaxplatform import honor_cpu_request  # noqa: E402

honor_cpu_request()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import SingleDeviceSharding


def offload_params(params: list[jax.Array],
                   device: jax.Device) -> list[jax.Array]:
    """Move every layer's weights to the host memory space (uncharged by
    the vtpu HBM cap)."""
    host = SingleDeviceSharding(device, memory_kind="pinned_host")
    return [jax.device_put(p, host) for p in params]


def streamed_forward(params_host: list[jax.Array], x: jax.Array,
                     device: jax.Device) -> jax.Array:
    """Apply layers one at a time, fetching each from host memory just
    before use. Device residency: one layer + the activation."""
    dev = SingleDeviceSharding(device, memory_kind="device")
    for w in params_host:
        w_dev = jax.device_put(w, dev)      # H2D: charged against the cap
        x = jnp.tanh(x @ w_dev)
        del w_dev                           # drop before the next fetch
    return x


def main() -> None:
    device = jax.devices()[0]
    kinds = [m.kind for m in device.addressable_memories()]
    if "pinned_host" not in kinds:
        print(f"backend exposes no pinned_host memory ({kinds}); "
              "host offload unavailable")
        return
    layers, width = 8, 1024
    keys = jax.random.split(jax.random.PRNGKey(0), layers)
    params = [jax.random.normal(k, (width, width), jnp.bfloat16) * 0.1
              for k in keys]
    params_host = offload_params(params, device)
    bytes_per_layer = width * width * 2
    print(f"model: {layers} layers x {bytes_per_layer/2**20:.0f} MiB "
          f"held in {params_host[0].sharding.memory_kind}; device peak "
          f"~{2*bytes_per_layer/2**20:.0f} MiB instead of "
          f"{layers*bytes_per_layer/2**20:.0f} MiB")
    x = jax.random.normal(jax.random.PRNGKey(1), (256, width), jnp.bfloat16)
    y = streamed_forward(params_host, x, device)
    print("forward ok:", y.shape, float(jnp.abs(y).mean()))


if __name__ == "__main__":
    main()
