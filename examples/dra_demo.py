#!/usr/bin/env python3
"""End-to-end DRA-mode demo: claims, prepare, CDI, NRI — no cluster.

Walks the DRA flow the way a cluster would drive it: a fake 2-chip node
publishes its ResourceSlice (fractional slots over shared counters) → a
ResourceClaim is "allocated" (as the scheduler's DRA allocator would) →
the kubelet plugin prepares it over REAL gRPC (unix socket) → the CDI
spec + binary vtpu.config land on disk → the NRI runtime hook (REAL
ttrpc over a mux-framed socket) validates the container and injects the
config mount, and rejects a spoofing container → unprepare cleans up.

    python examples/dra_demo.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import grpc

from vtpu_manager.client.fake import FakeKubeClient
from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.device.types import fake_chip
from vtpu_manager.kubeletplugin import cdi, nri_transport as nt
from vtpu_manager.kubeletplugin.allocatable import build_resource_slice
from vtpu_manager.kubeletplugin.api import dra_pb2 as pb
from vtpu_manager.kubeletplugin.api import nri_pb2
from vtpu_manager.kubeletplugin.device_state import DeviceState
from vtpu_manager.kubeletplugin.driver import ClaimSource, DraDriver
from vtpu_manager.kubeletplugin.nri import RuntimeHook
from vtpu_manager.kubeletplugin.registration import publish_resource_slice
from vtpu_manager.util import consts, ttrpc


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="vtpu-dra-demo-")
    try:
        return run(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run(tmp: str) -> int:
    client = FakeKubeClient()
    chips = [fake_chip(0), fake_chip(1)]

    print("== 1. node publishes its ResourceSlice")
    rs = build_resource_slice("demo-node", chips)
    publish_resource_slice(client, rs)
    print(f"   {len(rs['spec']['devices'])} devices "
          f"({len(rs['spec']['sharedCounters'])} shared counter sets); "
          f"first: {rs['spec']['devices'][0]['name']}")

    print("== 2. a ResourceClaim is allocated (50% cores / 2GiB of chip 0)")
    claim = {
        "metadata": {"uid": "claim-demo", "name": "tpu", "namespace": "ml"},
        "status": {
            "reservedFor": [{"resource": "pods", "name": "train",
                             "uid": "pod-demo"}],
            "allocation": {"devices": {
                "results": [{"request": "tpu",
                             "driver": consts.DRA_DRIVER_NAME,
                             "pool": "demo-node", "device": "vtpu-0"}],
                "config": [{"requests": ["tpu"], "opaque": {
                    "driver": consts.DRA_DRIVER_NAME,
                    "parameters": {"cores": 50, "memoryMiB": 2048}}}],
            }},
        },
    }
    source = ClaimSource()
    source.local["claim-demo"] = claim

    print("== 3. kubelet prepares the claim over gRPC")
    state = DeviceState("demo-node", chips, base_dir=f"{tmp}/mgr",
                        cdi_dir=f"{tmp}/cdi")
    driver = DraDriver("demo-node", chips, source, state=state,
                       plugin_dir=f"{tmp}/plugin")
    driver.serve()
    with grpc.insecure_channel(f"unix://{driver.socket_path}") as chan:
        prep = chan.unary_unary(
            "/v1beta1dra.DRAPlugin/NodePrepareResources",
            request_serializer=pb.NodePrepareResourcesRequest.
            SerializeToString,
            response_deserializer=pb.NodePrepareResourcesResponse.
            FromString)
        resp = prep(pb.NodePrepareResourcesRequest(claims=[
            pb.Claim(uid="claim-demo", name="tpu", namespace="ml")]),
            timeout=10)
    entry = resp.claims["claim-demo"]
    assert not entry.error, entry.error
    print(f"   CDI devices: {list(entry.devices[0].cdi_device_ids)}")
    spec = json.load(open(cdi.spec_path("claim-demo", f"{tmp}/cdi")))
    env = spec["devices"][0]["containerEdits"]["env"]
    print(f"   CDI env: {[e for e in env if 'LIMIT' in e or 'CLAIM' in e]}")
    cfg = vc.read_config(f"{tmp}/mgr/claim_claim-demo/config/vtpu.config")
    print(f"   vtpu.config: core={cfg.devices[0].hard_core}% "
          f"mem={cfg.devices[0].total_memory >> 20}MiB")

    print("== 4. NRI hook validates at container create (real ttrpc)")
    plugin = nt.NriPlugin(RuntimeHook(state),
                          claim_uids_for_pod=driver.claim_uids_for_pod)
    sock = f"{tmp}/nri.sock"
    runtime_srv = ttrpc.TtrpcServer(sock, {
        (nt.RUNTIME_SERVICE, "RegisterPlugin"):
            lambda raw: nri_pb2.Empty().SerializeToString()}, mux=True)
    session = plugin.run(sock)
    runtime = runtime_srv.wait_for_connection()
    raw = runtime.call(nt.PLUGIN_SERVICE, "CreateContainer",
                       nri_pb2.CreateContainerRequest(
                           pod=nri_pb2.PodSandbox(uid="pod-demo"),
                           container=nri_pb2.Container(
                               name="main",
                               env=["VTPU_CLAIM_UID=claim-demo"]),
                       ).SerializeToString())
    adj = nri_pb2.CreateContainerResponse.FromString(raw).adjust
    print(f"   injected mount -> {adj.mounts[0].destination} "
          f"env {[e.key for e in adj.env]}")
    try:
        runtime.call(nt.PLUGIN_SERVICE, "CreateContainer",
                     nri_pb2.CreateContainerRequest(
                         pod=nri_pb2.PodSandbox(uid="pod-evil"),
                         container=nri_pb2.Container(
                             name="evil",
                             env=["VTPU_CLAIM_UID=claim-demo"]),
                     ).SerializeToString())
        print("   !! spoof was NOT rejected")
        return 1
    except ttrpc.TtrpcError as e:
        print(f"   spoof rejected: {e.message}")
    session.close()
    runtime_srv.stop()

    print("== 5. multi-container claim: two requests, two containers")
    multi = {
        "metadata": {"uid": "claim-mc", "name": "shared", "namespace": "ml"},
        "status": {"allocation": {"devices": {
            "results": [
                {"request": "train", "driver": consts.DRA_DRIVER_NAME,
                 "pool": "node-demo", "device": "vtpu-0"},
                {"request": "eval", "driver": consts.DRA_DRIVER_NAME,
                 "pool": "node-demo", "device": "vtpu-1"},
            ],
            "config": [
                {"requests": ["train"], "opaque": {
                    "driver": consts.DRA_DRIVER_NAME,
                    "parameters": {"cores": 60, "memoryMiB": 4096}}},
                {"requests": ["eval"], "opaque": {
                    "driver": consts.DRA_DRIVER_NAME,
                    "parameters": {"cores": 30, "memoryMiB": 2048}}},
            ]}}},
    }
    source.local["claim-mc"] = multi
    with grpc.insecure_channel(f"unix://{driver.socket_path}") as chan:
        prep = chan.unary_unary(
            "/v1beta1dra.DRAPlugin/NodePrepareResources",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=(
                pb.NodePrepareResourcesResponse.FromString))
        resp = prep(pb.NodePrepareResourcesRequest(claims=[
            pb.Claim(uid="claim-mc", name="shared", namespace="ml")]),
            timeout=10)
    entry = resp.claims["claim-mc"]
    assert not entry.error, entry.error
    for dev in entry.devices:
        if dev.cdi_device_ids:
            print(f"   request {list(dev.requests)} -> "
                  f"{list(dev.cdi_device_ids)}")
    t_cfg = vc.read_config(
        f"{tmp}/mgr/claim_claim-mc/config_train/vtpu.config")
    e_cfg = vc.read_config(
        f"{tmp}/mgr/claim_claim-mc/config_eval/vtpu.config")
    print(f"   trainer sees chip {t_cfg.devices[0].host_index} at "
          f"{t_cfg.devices[0].hard_core}%; evaluator sees chip "
          f"{e_cfg.devices[0].host_index} at {e_cfg.devices[0].hard_core}%")
    state.unprepare_claim("claim-mc")

    print("== 6. unprepare cleans up")
    state.unprepare_claim("claim-demo")
    driver.stop()
    assert state.prepared_uids() == set()
    assert not os.path.exists(cdi.spec_path("claim-demo", f"{tmp}/cdi"))
    print("== DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
