#!/usr/bin/env python3
"""End-to-end local demo: the whole control plane on one machine, no
cluster, no TPU.

Walks BASELINE config[0]'s shape: a fake 2-chip node registers → the
scheduler extender filters + binds a 10%-core/1GiB pod → the kubelet
(simulated over real gRPC) allocates → the binary vtpu.config lands on
disk exactly as a tenant shim would mmap it → the node-state tool dumps
it.

    python examples/local_demo.py
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import grpc

from vtpu_manager.client.fake import FakeKubeClient
from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.config.node_config import NodeConfig
from vtpu_manager.device.claims import PodDeviceClaims
from vtpu_manager.deviceplugin.api import deviceplugin_pb2 as pb
from vtpu_manager.deviceplugin.base import PluginServer
from vtpu_manager.deviceplugin.vnum import VnumPlugin, device_id
from vtpu_manager.manager.device_manager import DeviceManager
from vtpu_manager.scheduler.bind import BindPredicate
from vtpu_manager.scheduler.filter import FilterPredicate
from vtpu_manager.tpu.discovery import FakeBackend
from vtpu_manager.util import consts


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="vtpu-demo-")
    base_dir = os.path.join(workdir, "manager")
    sock_dir = os.path.join(workdir, "kubelet")
    client = FakeKubeClient()

    print("== 1. node agent: discover chips, register the node")
    manager = DeviceManager(
        "demo-node", client,
        node_config=NodeConfig(device_split_count=10),
        backends=[FakeBackend(n_chips=2, mesh_shape=(1, 2))])
    manager.init_devices()
    client.add_node({"metadata": {"name": "demo-node", "annotations": {}}})
    manager.register_node()
    print(f"   chips: {[c.uuid for c in manager.chips]}")

    print("== 2. tenant pod: 1 vTPU, 10% cores, 1 GiB HBM")
    pod = {
        "metadata": {"name": "mnist", "namespace": "demo",
                     "uid": "uid-mnist", "annotations": {}},
        "spec": {"containers": [{"name": "train", "resources": {"limits": {
            consts.vtpu_number_resource(): 1,
            consts.vtpu_cores_resource(): 10,
            consts.vtpu_memory_resource(): 1024}}}]},
        "status": {"phase": "Pending"},
    }
    client.add_pod(pod)

    print("== 3. scheduler extender: filter -> pre-allocate -> bind")
    fres = FilterPredicate(client).filter({"Pod": pod})
    assert fres.node_names == ["demo-node"], fres.error
    bres = BindPredicate(client).bind({"PodName": "mnist",
                                       "PodNamespace": "demo",
                                       "Node": "demo-node"})
    assert not bres.error, bres.error
    anns = client.get_pod("demo", "mnist")["metadata"]["annotations"]
    claim = PodDeviceClaims.decode(
        anns[consts.pre_allocated_annotation()]).all_claims()[0]
    print(f"   committed: chip {claim.uuid} ({claim.cores}% cores, "
          f"{claim.memory >> 20} MiB)")

    print("== 4. kubelet allocates over the device-plugin gRPC socket")
    plugin = VnumPlugin(manager, client, "demo-node", base_dir=base_dir,
                        node_config=NodeConfig())
    server = PluginServer(plugin, plugin_dir=sock_dir)
    server.serve()
    try:
        with grpc.insecure_channel(f"unix://{server.socket_path}") as chan:
            alloc = chan.unary_unary(
                "/v1beta1.DevicePlugin/Allocate",
                request_serializer=pb.AllocateRequest.SerializeToString,
                response_deserializer=pb.AllocateResponse.FromString)(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(
                        devicesIDs=[device_id(claim.uuid, 0)])]),
                timeout=10)
    finally:
        server.stop()
    cresp = alloc.container_responses[0]
    enforce_envs = {k: v for k, v in sorted(cresp.envs.items())
                    if k.startswith("VTPU_") or k.startswith("TPU_")}
    print("   container env:", enforce_envs)

    print("== 5. the binary config a tenant shim would mmap")
    cfg_mount = [m for m in cresp.mounts
                 if m.container_path.endswith("/config")][0]
    cfg = vc.read_config(os.path.join(cfg_mount.host_path, "vtpu.config"))
    dev = cfg.devices[0]
    print(f"   {cfg.pod_namespace}/{cfg.pod_name}: device {dev.uuid} "
          f"cap={dev.total_memory >> 20}MiB cores={dev.hard_core}% "
          f"limit={dev.core_limit}")

    print("== 6. node-state inspection tool")
    subprocess.run([sys.executable,
                    os.path.join(os.path.dirname(__file__), "..",
                                 "library", "tools", "vtpu_inspect.py"),
                    "--base", base_dir, "--vmem", "/nonexistent",
                    "--tc", "/nonexistent"], check=True)

    status = client.get_pod("demo", "mnist")["metadata"]["annotations"][
        consts.allocation_status_annotation()]
    print(f"== DONE: pod allocation status = {status!r}")
    shutil.rmtree(workdir, ignore_errors=True)
    return 0 if status == consts.ALLOC_STATUS_SUCCEED else 1


if __name__ == "__main__":
    sys.exit(main())
