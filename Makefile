# vtpu-manager top-level build entry (reference: Makefile + versions.mk —
# redesigned for the Python/C++ split: cmake builds the PJRT shim, pytest is
# the suite, helm renders the chart; no Go toolchain).

include $(CURDIR)/versions.mk

SHELL = /usr/bin/env bash -o pipefail
.SHELLFLAGS = -ec

BUILD_DIR ?= build-lib
PYTEST ?= python -m pytest
CONTAINER_TOOL ?= docker

.PHONY: all
all: build lint

##@ General

.PHONY: help
help: ## Show this help
	@awk 'BEGIN {FS = ":.*##"} /^[a-zA-Z_0-9-]+:.*?##/ \
	  {printf "  \033[36m%-18s\033[0m %s\n", $$1, $$2} /^##@/ \
	  {printf "\n\033[1m%s\033[0m\n", substr($$0, 5)}' $(MAKEFILE_LIST)

##@ Build

.PHONY: build
build: ## Build the PJRT enforcement shim + test harness (cmake)
	cmake -S library -B $(BUILD_DIR) -DVTPU_BUILD_TESTS=ON \
	  -DCMAKE_BUILD_TYPE=Release
	cmake --build $(BUILD_DIR)

.PHONY: protos
protos: ## Regenerate *_pb2.py from the in-repo .proto sources
	cd vtpu_manager/kubeletplugin/api && \
	  protoc -I. --python_out=. nri.proto ttrpc.proto dra.proto \
	  pluginregistration.proto
	cd vtpu_manager/deviceplugin/api && \
	  protoc -I. --python_out=. deviceplugin.proto podresources.proto

.PHONY: clean
clean: ## Remove build artifacts
	rm -rf $(BUILD_DIR)

##@ Test

.PHONY: lint
lint: ## Project-native static analysis (vtlint, incl. the C++ shim pass) + ruff baseline when available
	python scripts/vtlint.py vtpu_manager/ cmd/
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check .; \
	else \
	  echo "ruff not installed; vtlint-only (baseline config in pyproject.toml)"; \
	fi

.PHONY: lint-golden
lint-golden: ## Regenerate the golden ABI layout (the explicit bump for intentional layout changes)
	python scripts/vtlint.py --update-abi-golden

.PHONY: test
test: build ## Full hermetic suite (pytest; includes the C harness via fixtures)
	$(PYTEST) tests/ -x -q

.PHONY: test-trace
test-trace: ## vtrace subsystem alone (recorder, assembly, hermetic e2e)
	$(PYTEST) tests/test_trace.py -q

.PHONY: test-snapshot
test-snapshot: ## Scheduler snapshot alone (fake watch, incremental apply, 410 relist, gate parity)
	$(PYTEST) tests/test_snapshot.py -q

.PHONY: test-chaos
test-chaos: ## Seeded chaos suite: failpoints at every site over the e2e path (CHAOS_SEED=n reproduces one seed)
	$(PYTEST) tests/test_chaos.py tests/test_resilience.py -q

.PHONY: test-telemetry
test-telemetry: ## vttel suite: step ring ABI + torture, aggregation, pressure hint, hermetic e2e
	$(PYTEST) tests/test_telemetry.py -q

.PHONY: test-ha
test-ha: ## vtha suite: shard leases/fencing units + the multi-scheduler chaos topology (CHAOS_SEED=n CHAOS_TOPOLOGY=multi reproduces one seed)
	$(PYTEST) tests/test_ha.py -q
	CHAOS_TOPOLOGY=multi $(PYTEST) tests/test_chaos.py -q -k multi_scheduler

.PHONY: test-compilecache
test-compilecache: ## vtcc suite: content addressing, single-flight torture, LRU/quarantine chaos, anti-storm parity in both scheduler modes
	$(PYTEST) tests/test_compilecache.py -q

.PHONY: test-utilization
test-utilization: ## vtuse suite: ledger EWMA/burstiness/staleness math, budgeted fold bound, gate-off contract, rollup chaos, vtpu-smi e2e
	$(PYTEST) tests/test_utilization.py -q

.PHONY: test-explain
test-explain: ## vtexplain suite: ring bounds/drops, gate-off contracts, reason-code matrix, score-reproduction e2e, doctor verdicts, victim-ordering satellite, chaos
	$(PYTEST) tests/test_explain.py -q

.PHONY: test-quotamarket
test-quotamarket: ## vtqm suite: class stamping, lease ledger, market policy + conservation invariant, headroom score term both modes, replay/smi CLIs, 24-seed reclaim-under-crash chaos (CHAOS_SEED=n reproduces one seed)
	$(PYTEST) tests/test_quota.py -q

.PHONY: test-clustercache
test-clustercache: ## vtcs suite: advertisement codec, peer fetch ladder + torn-fetch chaos, warm-preference parity in both scheduler modes, victim-cost ordering
	$(PYTEST) tests/test_clustercache.py -q

.PHONY: bench-clustercache
bench-clustercache: ## vtcs headline bench: M-node fleet cold start — one compile fleet-wide, cold-node TTFS at warm-node order (asserted; writes BENCH_VTCS_r12.json)
	python scripts/bench_clustercache.py

.PHONY: bench-compilecache
bench-compilecache: ## vtcc headline bench: N-replica gang cold start, cache off/cold/warm (1 compile + N-1 hits asserted)
	python scripts/bench_compilecache.py

.PHONY: bench-quotamarket
bench-quotamarket: ## vtqm headline bench: bursty inference + steady training co-location, market off/on (burst p99 >=2x, training >=95% retained, reclaim bound asserted; writes BENCH_VTQM_r10.json)
	python scripts/bench_quotamarket.py

.PHONY: test-ici
test-ici: ## vtici suite: link-graph torus properties, contention vs brute force, link-aware placement parity both modes, codec staleness matrix, publisher chaos, v5 stamp matrix, class-mix term, ad-cap review
	$(PYTEST) tests/test_ici.py -q

.PHONY: bench-ici
bench-ici: ## vtici headline bench: co-resident communicator boxes, capacity-only vs link-aware placement — worst-link contention + modeled all-reduce step time reduction, gate-off parity (asserted; writes BENCH_VTICI_r13.json)
	python scripts/bench_ici.py

.PHONY: test-comm
test-comm: ## vtcomm suite: v3 comm-block ledger fold, publisher preference chain + fallback audit, gate-off byte-contracts, torn-fold chaos, borrowed-vs-used replay check, fleet overcommit view
	$(PYTEST) tests/test_comm.py -q

.PHONY: bench-comm
bench-comm: ## vtcomm headline bench: measured comm-intensity MAE vs ground truth beats the duty chain and the 1.6x model, measured-fed steering both scheduler modes (asserted; writes BENCH_VTCOMM_r14.json)
	python scripts/bench_comm.py

.PHONY: test-slo
test-slo: ## vtslo suite: attribution arithmetic, ring v4 roundtrip/skip, detector+cause matrix, history spools, stalecodec consolidation, gate-off contracts, /slo + --why-slow e2e, grant-step feedback
	$(PYTEST) tests/test_slo.py -q

.PHONY: bench-slo
bench-slo: ## vtslo headline bench: four injected causes (quota revoke, spill thrash, ICI contention, cold compile) each attributed to its plane with zero cross-attribution (asserted; writes BENCH_VTSLO_r15.json)
	python scripts/bench_slo.py

.PHONY: test-overcommit
test-overcommit: ## vtovc suite: ratio codec + policy percentiles, virtual admission parity both modes, spill pool chaos (torn copy / budget / crashed-spiller reap), gate-off byte-contracts
	$(PYTEST) tests/test_overcommit.py -q

.PHONY: bench-overcommit
bench-overcommit: ## vtovc headline bench: pods-per-chip density gate off/on (>=1.5x at bounded p99 step-time regression, thrash backoff asserted; writes BENCH_VTOVC_r11.json)
	python scripts/bench_overcommit.py

.PHONY: test-autopilot
test-autopilot: ## vtpilot suite: election+fencing, hysteresis/cooldown/rate-limit guards, the three remediations through real channels, gang-migration e2e, crash-mid-migration reap convergence, gate-off byte-contracts both modes, one-cluster-scanner election
	$(PYTEST) tests/test_autopilot.py -q

.PHONY: bench-autopilot
bench-autopilot: ## vtpilot headline bench: PR-15's four injected causes re-run with the autopilot on — >=3/4 remediated within K windows, zero steady-state actions, zero flapping, crash-mid-migration convergence (asserted; writes BENCH_VTAP_r17.json)
	python scripts/bench_autopilot.py

.PHONY: test-abi-san
test-abi-san: ## ABI probe suite rebuilt with ASan+UBSan (skips clean when g++/libasan absent)
	VTPU_ABI_SAN=1 $(PYTEST) tests/test_config_abi.py -q

.PHONY: test-scale
test-scale: ## vtscale suite: fence epoch codec, plan object, bind waves, rolling reshard, cross-shard spill, webhook HA, gate-off byte-contracts
	$(PYTEST) tests/test_scale.py -q

.PHONY: bench-scale
bench-scale: ## vtscale headline bench: 50k nodes/100k pods, pipelined binds >=5x serial over a simulated RTT, placement parity, rolling-reshard chaos (asserted; writes BENCH_VTSCALE_r18.json). bench-scale-quick is the CI smoke.
	python scripts/bench_scale.py

.PHONY: bench-scale-quick
bench-scale-quick: ## vtscale bench at smoke scale (no artifact written)
	python scripts/bench_scale.py --quick

.PHONY: test-frag
test-frag: ## vtfrag suite: codec staleness matrix, score vs select_submesh, TTL/snapshot tap parity, gate-off byte-contracts, forecaster-vs-FilterPredicate agreement, publisher, history ring, elected scan lease
	$(PYTEST) tests/test_frag.py -q

.PHONY: bench-frag
bench-frag: ## vtfrag headline bench: packed->checkered churn holds free capacity flat while the score crosses the alarm bar; doctor == scheduler for every gang class in both modes; gate-off identity (asserted; writes BENCH_VTFRAG_r20.json)
	python scripts/bench_frag.py

.PHONY: verify
verify: lint test test-trace test-snapshot test-chaos test-telemetry test-ha test-compilecache test-clustercache test-utilization test-explain test-quotamarket test-overcommit test-ici test-comm test-slo test-autopilot test-scale test-frag test-abi-san bench-overcommit bench-clustercache bench-ici bench-comm bench-slo bench-autopilot bench-scale-quick bench-frag ## Default verify flow: static analysis, the suite, vtrace e2e, snapshot suite, chaos invariants, vttel e2e, vtha leases+multi-scheduler chaos, vtcc cache suite, vtcs fleet-seeding suite + bench, vtuse ledger suite, vtexplain audit suite, vtqm market suite, vtovc overcommit suite + density bench, vtici link-plane suite + bench, vtcomm comm-plane suite + bench, vtslo attribution suite + bench, vtpilot autopilot suite + bench, vtscale suite + smoke bench, vtfrag observatory suite + bench, sanitized ABI probes

.PHONY: test-shim
test-shim: build ## C harness alone against the fake PJRT plugin
	SHIM_PATH=$(CURDIR)/$(BUILD_DIR)/libvtpu-control.so \
	VTPU_REAL_TPU_LIBRARY_PATH=$(CURDIR)/$(BUILD_DIR)/libfake-pjrt.so \
	VTPU_MEM_LIMIT_0=1048576 VTPU_CORE_LIMIT_0=50 \
	VTPU_CONFIG_PATH=/nonexistent VTPU_LOCK_DIR=/tmp/.vtpu_make_locks \
	VTPU_TC_UTIL_PATH=/nonexistent VTPU_VMEM_PATH=/nonexistent \
	$(BUILD_DIR)/shim_test

.PHONY: test-perf
test-perf: ## Opt-in perf matrix + sustained harness (VTPU_PERF=1)
	VTPU_PERF=1 VTPU_PERF_SUSTAINED=1 VTPU_SUSTAINED_PODS=5000 \
	$(PYTEST) tests/test_filter_perf.py -q -s

.PHONY: bench
bench: build ## The driver benchmark (one JSON line; TPU when healthy)
	python bench.py

.PHONY: capture
capture: build ## Full real-TPU capture matrix (resumable, MFU-first)
	python scripts/capture_hw.py

.PHONY: watch-tpu
watch-tpu: ## Background tunnel watcher: probes health, fires the capture on recovery
	nohup python scripts/tpu_watch.py >> tpu_watch.out 2>&1 & \
	  echo "watcher started (log: tpu_watch.out, probes: TPU_PROBE_LOG_r*.jsonl)"

##@ Deploy

.PHONY: chart
chart: ## Render the Helm chart to stdout (helm, or the certified subset renderer)
	@if command -v helm >/dev/null 2>&1; then \
	  helm template vtpu-manager charts/vtpu-manager; \
	else \
	  python scripts/render_chart.py; \
	fi

.PHONY: images
images: ## Build container images (device plugin stack + DRA driver)
	$(CONTAINER_TOOL) build -t $(IMG) -f Dockerfile .
	$(CONTAINER_TOOL) build -t $(DRA_IMG) -f Dockerfile.dra .

.PHONY: install
install: ## Apply the non-chart manifests to the current cluster context
	kubectl apply -f deploy/vtpu-manager.yaml
	kubectl apply -f deploy/vtpu-dra-driver.yaml

.PHONY: uninstall
uninstall: ## Delete the non-chart manifests
	kubectl delete --ignore-not-found -f deploy/vtpu-dra-driver.yaml
	kubectl delete --ignore-not-found -f deploy/vtpu-manager.yaml

.PHONY: version
version: ## Print build metadata
	@echo "version=$(VERSION) commit=$(GIT_COMMIT) branch=$(GIT_BRANCH) date=$(BUILD_DATE)"
