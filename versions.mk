# Version pins for vtpu-manager builds (reference: versions.mk).

VERSION ?= $(shell git describe --tags --always --dirty 2>/dev/null || echo dev)
GIT_COMMIT ?= $(shell git rev-parse HEAD 2>/dev/null || echo unknown)
GIT_BRANCH ?= $(shell git rev-parse --abbrev-ref HEAD 2>/dev/null || echo unknown)
BUILD_DATE ?= $(shell date -u +%Y-%m-%dT%H:%M:%SZ)

TAG ?= latest
REGISTRY ?= vtpu-manager
IMG = $(REGISTRY)/vtpu-manager:$(TAG)
DRA_IMG = $(REGISTRY)/vtpu-manager-dra:$(TAG)
