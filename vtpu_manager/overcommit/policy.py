"""vtovc policy engine: per-class safe oversubscription ratios.

The node-side answer to "how much virtual HBM can this node safely
advertise, per workload class?" — computed from vtuse's measured
ground truth, never from declared caps:

- per tenant, the step-ring **HBM high-water** is the working-set
  envelope (the high-water IS the burst envelope — the same reasoning
  the headroom ledger applies to HBM reclaim);
- per class, the p95 of ``highwater / allocated`` across the class's
  tenants (with a safety factor) is the fraction of declared HBM the
  class demonstrably touches — the inverse is the raw safe ratio;
- the class's **minimum tenant confidence** gates the whole claim:
  ratio = 1 + (raw - 1) × conf, so a class whose samples are going
  stale decays linearly back to 1.0 and a never-sampled class IS 1.0
  (no signal means no oversubscription — the headroom discipline,
  because the scheduler will ADMIT against this number).

Latency-critical tenants get a tighter safety factor than throughput
ones: an underestimated working set costs a serving tenant a spill
stall on its critical path, while a training step merely slows.

The publisher rides the device-plugin daemon (the node-annotation
owner, same shape as Pressure/HeadroomPublisher) and folds the node's
live spill signal (step-ring spill/fill deltas + the vmem ledger's
host-pool footprint) into the same annotation, so the scheduler's
thrash-backoff reads one codec.
"""

from __future__ import annotations

import logging
import threading
import time

from vtpu_manager.overcommit.ratio import MAX_RATIO, NodeOvercommit
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

# working-set percentile across a class's tenants: the envelope the
# ratio must cover (p95 — one outlier tenant caps the class, a tail
# beyond that is what the spill tier exists for)
HIGHWATER_PERCENTILE = 0.95

# safety headroom multiplied onto the measured envelope fraction
# before inversion — latency-critical working sets get more slack
SAFETY_FACTOR = {"lat": 1.5, "thr": 1.2, "def": 1.35}

# a class's envelope fraction is floored here before inversion: even a
# provably tiny working set never advertises more than MAX_RATIO
MIN_ENVELOPE_FRACTION = 1.0 / MAX_RATIO

# minimum evidence before any oversubscription: below this many
# distinct sampled tenants in a class the ratio stays 1.0 (one tenant's
# high-water says nothing about the mix the virtual capacity will admit)
MIN_CLASS_TENANTS = 2


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list (no numpy in the
    node daemon's hot loop)."""
    if not sorted_vals:
        return 1.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class OvercommitPolicy:
    """Fold one node's vtuse ledger into a NodeOvercommit rollup."""

    def __init__(self, ledger, max_ratio: float = MAX_RATIO):
        self.ledger = ledger
        self.max_ratio = max_ratio

    def compute(self, now_wall: float | None = None) -> NodeOvercommit:
        now_wall = time.time() if now_wall is None else now_wall
        samples = self.ledger.hbm_fraction_samples(now_wall)
        ratios: dict[str, float] = {}
        for key in ("lat", "thr", "def"):
            ratios[key] = self._class_ratio(key, samples.get(key, []))
        spill_frac, spilled_bytes = \
            self.ledger.node_spill_signal(now_wall)
        return NodeOvercommit(ratios=ratios, spill_frac=spill_frac,
                              spilled_bytes=spilled_bytes, ts=now_wall)

    def _class_ratio(self, key: str,
                     samples: list[tuple[float, float]]) -> float:
        """One class's safe ratio from its (fraction, confidence)
        samples. Confidence gating is the MIN across the class — the
        stalest tenant's decay bounds the whole claim, because the
        admitted mix includes tenants like it."""
        live = [(f, c) for f, c in samples if c > 0.0]
        if len(live) < MIN_CLASS_TENANTS:
            return 1.0
        fracs = sorted(min(max(f, 0.0), 1.0) for f, _ in live)
        envelope = _percentile(fracs, HIGHWATER_PERCENTILE) \
            * SAFETY_FACTOR[key]
        envelope = max(envelope, MIN_ENVELOPE_FRACTION)
        raw = min(1.0 / envelope, self.max_ratio)
        conf = min(c for _, c in live)
        return round(1.0 + (raw - 1.0) * conf, 2)


class OvercommitPublisher:
    """Daemon loop: compute the policy, patch the node annotation.

    Device-plugin side behind the HBMOvercommit gate — the exact shape
    of Pressure/HeadroomPublisher (per-tick failure tolerance; the
    codec's timestamp ages a silent publisher out to ratio 1.0 on the
    scheduler side, which is the safe direction)."""

    def __init__(self, client, node_name: str, policy: OvercommitPolicy,
                 retry_policy=None, interval_s: float = 15.0,
                 fold: bool = True):
        from vtpu_manager.resilience.policy import RetryPolicy
        self.client = client
        self.node_name = node_name
        self.policy = policy
        self.retry_policy = retry_policy or RetryPolicy(max_attempts=3,
                                                        deadline_s=10.0)
        self.interval_s = interval_s
        # False when another daemon loop (e.g. a shared ledger's owner)
        # already folds: two folders would race one cursor state
        self.fold = fold
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def publish_once(self) -> NodeOvercommit:
        if self.fold:
            self.policy.ledger.fold()
        oc = self.policy.compute()
        self.retry_policy.run(
            lambda: self.client.patch_node_annotations(
                self.node_name,
                {consts.node_overcommit_annotation(): oc.encode()}),
            op="overcommit.policy_patch")
        return oc

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.publish_once()
                except Exception:  # noqa: BLE001 — advisory signal; the
                    # annotation timestamp decays a silent failure to
                    # ratio 1.0 (the safe direction)
                    log.warning("overcommit policy publish failed",
                                exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtovc-policy")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
