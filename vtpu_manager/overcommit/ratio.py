"""Node-overcommit annotation: vtovc's feedback edge into the scheduler.

Same codec family as the vttel pressure and vtuse headroom annotations —
parse-cheap on purpose (the snapshot path decodes it per node event, the
TTL path per candidate), staleness explicit by timestamp:

    "<class>:<ratio>;...|<spill_frac>:<spilled_bytes>@<wall_ts>"

one ``;``-separated segment per workload class (``lat`` / ``thr`` /
``def`` for unclassified), ratios as decimals >= 1.0, then the node's
measured spill activity: ``spill_frac`` is the fraction of recent steps
that paid a spill or fill (the thrash signal), ``spilled_bytes`` the
live host-pool footprint. A publisher that goes dark decays to ratio
1.0 and zero spill signal — an oversubscription claim that outlives its
publisher is worse than no claim, because the scheduler would admit
pods against capacity nobody is measuring anymore.

Two consumers, two disciplines:

- **ratio_for_class** feeds ADMISSION (virtual capacity). Staleness is
  judged at parse time AND re-judged at use time (the pressure-penalty
  rule: the snapshot caches the parsed object and a dead publisher
  emits no further events);
- **spill_penalty** feeds SCORING — a soft penalty in the same currency
  as the pressure term (reorders fits, never vetoes one), so a node
  actively servicing spills repels new pods before it thrashes harder.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from vtpu_manager.util import consts, stalecodec

# a policy rollup older than this reads as no-signal => ratio 1.0
# (publisher cadence is seconds; the pressure/headroom constant family)
MAX_OVERCOMMIT_AGE_S = 120.0
FUTURE_SKEW_TOLERANCE_S = stalecodec.FUTURE_SKEW_TOLERANCE_S

# hard bound on any published ratio: even a unanimous working-set
# signal never oversells a chip more than 4x (the bench's density
# headline needs 1.5-2x; 4x is the runaway backstop)
MAX_RATIO = 4.0

# scoring weight for the spill-rate penalty: a node where every recent
# step paid a spill/fill loses this many points — the same currency as
# the vttel pressure penalty (reorders fits, never vetoes; strictly
# below the +100 gang bonus so gang locality still wins)
SPILL_SCORE_WEIGHT = 50.0

# wire keys per workload class; "def" covers unclassified tenants
CLASS_KEYS = {
    consts.WORKLOAD_CLASS_LATENCY_CRITICAL: "lat",
    consts.WORKLOAD_CLASS_THROUGHPUT: "thr",
    "": "def",
}


@dataclass(frozen=True)
class NodeOvercommit:
    """Decoded node-overcommit policy rollup."""

    ratios: dict[str, float] = field(default_factory=dict)  # key -> ratio
    spill_frac: float = 0.0        # fraction of recent steps spilling
    spilled_bytes: int = 0         # live host-pool footprint
    ts: float = 0.0

    def encode(self) -> str:
        body = ";".join(f"{k}:{r:.2f}"
                        for k, r in sorted(self.ratios.items()))
        return stalecodec.stamp(
            f"{body}|{self.spill_frac:.4f}:{self.spilled_bytes}",
            self.ts)

    def max_ratio(self) -> float:
        return max(self.ratios.values(), default=1.0)


def parse_overcommit(raw: str | None, now: float | None = None,
                     max_age_s: float = MAX_OVERCOMMIT_AGE_S
                     ) -> NodeOvercommit | None:
    """Decode the annotation; None when absent, malformed, or stale —
    every bad shape degrades to no-signal (ratio 1.0 everywhere), never
    to a wrong oversubscription claim."""
    split = stalecodec.split_stamp(raw)
    if split is None:
        return None
    body, ts = split
    if not stalecodec.is_fresh(ts, now, max_age_s):
        return None
    classes, sep, spill_raw = body.rpartition("|")
    if not sep:
        return None
    frac_raw, _, bytes_raw = spill_raw.partition(":")
    try:
        spill_frac = float(frac_raw)
        spilled_bytes = int(bytes_raw)
    except (TypeError, ValueError):
        return None
    if not math.isfinite(spill_frac):
        return None
    ratios: dict[str, float] = {}
    for seg in classes.split(";"):
        if not seg:
            continue
        key, _, ratio_raw = seg.partition(":")
        try:
            ratio = float(ratio_raw)
        except (TypeError, ValueError):
            return None
        if not math.isfinite(ratio):
            # NaN parses but poisons every capacity product downstream
            return None
        ratios[key] = min(max(ratio, 1.0), MAX_RATIO)
    return NodeOvercommit(ratios=ratios,
                          spill_frac=min(max(spill_frac, 0.0), 1.0),
                          spilled_bytes=max(spilled_bytes, 0), ts=ts)


def _fresh(oc: "NodeOvercommit | None", now: float | None) -> bool:
    if oc is None:
        return False
    return stalecodec.is_fresh(oc.ts, now, MAX_OVERCOMMIT_AGE_S)


def ratio_for_class(oc: "NodeOvercommit | None", workload_class: str,
                    now: float | None = None) -> float:
    """The admission ratio for one pod's workload class. Staleness is
    re-judged HERE, not only at parse time — the snapshot path caches
    the parsed rollup on the NodeEntry and a dead publisher emits no
    further node events, so a use-time check is what decays the claim
    to 1.0 instead of admitting against phantom capacity forever."""
    if not _fresh(oc, now):
        return 1.0
    key = CLASS_KEYS.get(workload_class, "def")
    ratio = oc.ratios.get(key)
    if ratio is None:
        ratio = oc.ratios.get("def", 1.0)
    return min(max(ratio, 1.0), MAX_RATIO)


def spill_penalty(oc: "NodeOvercommit | None",
                  now: float | None = None) -> float:
    """Score points to subtract for a node's live spill activity — the
    thrash-backoff term. Soft like the pressure penalty: a thrashing
    node with the only free chips still schedules. Stale signal = 0.0
    (the byte-identical pre-vtovc score)."""
    if not _fresh(oc, now):
        return 0.0
    return SPILL_SCORE_WEIGHT * oc.spill_frac


# ---------------------------------------------------------------------------
# Virtual-registry scaling: the one place virtual capacity enters the
# scheduler's accounting. Both data paths admit with the SAME scaled
# registry (fast_free_totals pre-gate and the allocator's per-chip
# placement both read ChipSpec.memory), so the virtual/physical split
# cannot drift between the gate and the allocation.
# ---------------------------------------------------------------------------

def virtual_registry(registry, ratio: float):
    """A view of ``registry`` with every healthy chip's HBM scaled by
    ``ratio``. Ratio <= 1.0 returns the registry itself (the gate-off /
    no-signal identity — zero allocations, byte-identical objects).

    Scaled copies are memoized ON the registry object (the same idiom
    as its healthy_totals memo): registries are decode-cached and
    shared across passes, ratios are quantized to 2 decimals by the
    codec, so a node's steady ratio costs one copy, not one per pass.
    ChipSpec is frozen — copies never alias the originals' capacity.
    """
    if registry is None or ratio <= 1.0:
        return registry
    ratio = round(min(ratio, MAX_RATIO), 2)
    cache = getattr(registry, "_virtual_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(registry, "_virtual_cache", cache)
    scaled = cache.get(ratio)
    if scaled is not None:
        return scaled
    import dataclasses

    from vtpu_manager.device.types import NodeDeviceRegistry
    scaled = NodeDeviceRegistry(
        chips=[dataclasses.replace(c, memory=int(c.memory * ratio))
               for c in registry.chips],
        mesh=registry.mesh, mesh_domain=registry.mesh_domain)
    cache[ratio] = scaled
    return scaled
