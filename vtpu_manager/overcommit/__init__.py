"""vtovc — HBM oversubscription with a host-spill tier (HBMOvercommit).

The overcommit plane, the TPU analogue of the reference's UVA-oversold
mode: schedule against *virtual* HBM larger than physical, let the shim
demote cold buffers to a host-RAM pool when physical runs out, and back
the scheduler off nodes that are actively thrashing.

- :mod:`ratio` — the node-overcommit annotation codec (per-class safe
  ratios + spill-rate, staleness-stamped) and the virtual-registry
  scaling both scheduler paths admit against;
- :mod:`policy` — the node-side policy engine computing safe ratios
  from vtuse's step-ring HBM high-water percentiles, plus the publisher
  daemon;
- :mod:`spill` — the host-RAM spill pool: LRU demotion, per-node
  budget accounted in the vmem ledger, crash reaping, and the node
  invariants the chaos harness asserts.
"""

from vtpu_manager.overcommit.ratio import (NodeOvercommit,  # noqa: F401
                                           SPILL_SCORE_WEIGHT,
                                           parse_overcommit,
                                           ratio_for_class,
                                           spill_penalty,
                                           virtual_registry)
from vtpu_manager.overcommit.policy import (OvercommitPolicy,  # noqa: F401
                                            OvercommitPublisher)
from vtpu_manager.overcommit.spill import (SpillBudgetError,  # noqa: F401
                                           SpillPool,
                                           assert_node_invariants)
