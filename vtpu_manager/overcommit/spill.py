"""Host-RAM spill pool: the vtovc demotion tier (Python side).

The contract mirror of the C++ shim's spill arm (enforce.cc), the same
way ``config/vmem.py`` mirrors the ledger the shim mmaps: one node-
shared pool directory holds each tenant's demoted buffers as files,
the vmem ledger's per-entry ``spilled`` field accounts every byte, and
the per-node spill budget bounds the sum. The chaos harness and the
density bench drive THIS implementation; real tenants go through the
shim, which follows the identical protocol on the identical files.

Protocol (crash-ordered so a torn spill can never corrupt accounting):

1. budget check under the pool lock (Σ spilled + incoming <= budget,
   re-read from the ledger — the pre-write invariant guard);
2. payload lands in ``<name>.tmp`` and is fsynced, then atomically
   renamed to the pool file. A crash mid-copy (the ``spill.copy``
   failpoint's partial-write) leaves only a ``.tmp`` orphan: the pool
   file namespace and the ledger are untouched, and the reaper deletes
   the orphan;
3. only after the rename does the ledger's spilled counter move — the
   file IS the commit point, exactly like vtpu.config's tmp+rename.

Fill reverses the order: ledger first (the budget frees optimistically;
a crash between ledger and unlink leaves an orphan file the reaper
reconciles), then the file is read and removed.

Pool files are self-describing (``<token>-<pid>-<chip>-<buf>.spill``)
so the reaper can attribute every byte without a sidecar index: a dead
owner's files are deleted and the vmem ledger's own dead+stale reap
clears the accounting row — the two converge without coordination.
"""

from __future__ import annotations

import logging
import os
import time

from vtpu_manager.config import vmem as vmem_mod
from vtpu_manager.resilience import failpoints
from vtpu_manager.util import consts
from vtpu_manager.util.flock import FileLock

log = logging.getLogger(__name__)

SPILL_SUFFIX = ".spill"


class SpillBudgetError(RuntimeError):
    """The node's host-RAM spill budget cannot absorb this demotion."""


# ---------------------------------------------------------------------------
# Execute-output shape capture (vtovc item (b)): the Python mirror of
# vtpu_config.h SpillLogicalBytes / SpillShapeCaptureOk — the rule
# deciding whether an observed (dims, element-type) pair is a SAFE
# spill-recipe. Asserted identical cross-language by the g++ probe in
# tests/test_config_abi.py; the density bench classifies its simulated
# activation buffers with this exact predicate.
# ---------------------------------------------------------------------------

_SPILL_BYTES_CAP = 9_000_000_000_000_000_000


def spill_logical_bytes(dims, elem_bytes: int) -> int:
    """Logical byte size a (dims, element-size) recipe implies; 0 when
    the shape is no recipe at all (zero/negative dim, non-positive
    element size, or int64 overflow) — mirror of the C++ helper."""
    if elem_bytes <= 0:
        return 0
    elems = 1
    for d in dims or ():
        d = int(d)
        if d <= 0:
            return 0
        if elems > _SPILL_BYTES_CAP // d:
            return 0
        elems *= d
    if elems > _SPILL_BYTES_CAP // elem_bytes:
        return 0
    return elems * elem_bytes


def spill_shape_capture_ok(logical_bytes: int,
                           on_device_bytes: int) -> bool:
    """Whether the captured shape may mark a buffer SPILLABLE: only
    when the logical size equals the on-device size — a padded/tiled
    layout spilled as a flat host copy would refill wrong."""
    return logical_bytes > 0 and logical_bytes == on_device_bytes


def _pool_name(token: int, pid: int, host_index: int, buf_id: str) -> str:
    return f"{token:016x}-{pid}-{host_index}-{buf_id}{SPILL_SUFFIX}"


def _parse_pool_name(name: str) -> tuple[int, int, int, str] | None:
    if not name.endswith(SPILL_SUFFIX):
        return None
    parts = name[: -len(SPILL_SUFFIX)].split("-", 3)
    if len(parts) != 4:
        return None
    try:
        return (int(parts[0], 16), int(parts[1]), int(parts[2]), parts[3])
    except ValueError:
        return None


class SpillPool:
    """One tenant-process's handle on the node-shared spill pool."""

    def __init__(self, pool_dir: str = consts.SPILL_DIR,
                 budget_bytes: int = 0,
                 ledger: "vmem_mod.VmemLedger | None" = None,
                 owner_token: int | None = None,
                 pid: int | None = None):
        self.pool_dir = pool_dir
        self.budget_bytes = budget_bytes
        self.ledger = ledger
        self.owner_token = owner_token if owner_token is not None \
            else vmem_mod.owner_token_from_env()
        self.pid = pid if pid is not None else os.getpid()
        os.makedirs(pool_dir, exist_ok=True)
        # budget admission is cross-process: two spillers must not both
        # pass the same last slice of budget (the pre-write guard)
        self._lock = FileLock(os.path.join(pool_dir, ".budget.lock"))
        # this process's live spilled bytes per chip (the ledger mirror)
        self._spilled: dict[int, int] = {}
        self.spill_events = 0
        self.fill_events = 0

    # -- demotion ------------------------------------------------------------

    def spill(self, host_index: int, buf_id: str, payload: bytes) -> int:
        """Demote one buffer to the host pool. Returns bytes moved.
        Raises SpillBudgetError when the node budget cannot absorb it —
        the caller's allocation then fails exactly as it would have
        pre-vtovc (the spill arm only ever converts failures into
        successes, never successes into failures)."""
        nbytes = len(payload)
        path = os.path.join(self.pool_dir, _pool_name(
            self.owner_token, self.pid, host_index, buf_id))
        with self._lock:
            # pre-write invariant guard: Σ spilled (cluster-truth from
            # the ledger, else local) + incoming must fit the budget
            failpoints.fire("spill.budget", buf=buf_id,
                            host_index=host_index)
            spilled_now = (self.ledger.node_spilled_total()
                           if self.ledger is not None
                           else sum(self._spilled.values()))
            if self.budget_bytes and \
                    spilled_now + nbytes > self.budget_bytes:
                raise SpillBudgetError(
                    f"spill budget exhausted: {spilled_now}B live + "
                    f"{nbytes}B > {self.budget_bytes}B")
            tmp = f"{path}.tmp.{self.pid}"
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                # the copy crash window: partial-write tears the TMP
                # file (never the pool file), then simulated death —
                # the ledger below is unreached, so accounting is clean
                failpoints.fire("spill.copy", buf=buf_id, path=tmp,
                                host_index=host_index)
                os.fsync(f.fileno())
            os.rename(tmp, path)      # the commit point
            self._spilled[host_index] = \
                self._spilled.get(host_index, 0) + nbytes
            self.spill_events += 1
            if self.ledger is not None:
                self.ledger.record_spilled(
                    self.pid, host_index,
                    self._spilled[host_index],
                    owner_token=self.owner_token)
        return nbytes

    # -- promotion -----------------------------------------------------------

    def fill(self, host_index: int, buf_id: str) -> bytes | None:
        """Promote one buffer back out of the host pool; None when the
        pool holds no such buffer (already filled, or reaped)."""
        path = os.path.join(self.pool_dir, _pool_name(
            self.owner_token, self.pid, host_index, buf_id))
        with self._lock:
            try:
                with open(path, "rb") as f:
                    payload = f.read()
            except OSError:
                return None
            self._spilled[host_index] = max(
                0, self._spilled.get(host_index, 0) - len(payload))
            self.fill_events += 1
            if self.ledger is not None:
                self.ledger.record_spilled(
                    self.pid, host_index,
                    self._spilled[host_index],
                    owner_token=self.owner_token)
            try:
                os.unlink(path)
            except OSError:
                # orphan: the reaper reconciles (accounting already
                # settled — an orphan only wastes host RAM, never
                # budget, and never resurrects as a double fill
                # because this process's _spilled no longer covers it)
                log.warning("spill pool file %s not removed", path)
        return payload

    def spilled_bytes(self, host_index: int | None = None) -> int:
        if host_index is None:
            return sum(self._spilled.values())
        return self._spilled.get(host_index, 0)

    # -- LRU victim selection ------------------------------------------------

    @staticmethod
    def choose_victims(candidates: list[tuple[str, int, int]],
                       need_bytes: int) -> list[str]:
        """Coldest-first victim set covering ``need_bytes``.
        ``candidates`` are (buf_id, bytes, last_touch_ns) of RESIDENT
        buffers — the same LRU-by-last-Execute-touch order the shim
        applies to its tracked buffers. Returns [] when the candidates
        cannot cover the need (the caller then fails the allocation;
        a partial eviction would thrash without helping)."""
        if need_bytes <= 0:
            return []
        total = sum(b for _, b, _ in candidates)
        if total < need_bytes:
            return []
        victims: list[str] = []
        covered = 0
        for buf_id, nbytes, _touch in sorted(candidates,
                                             key=lambda c: (c[2], c[0])):
            victims.append(buf_id)
            covered += nbytes
            if covered >= need_bytes:
                break
        return victims


# ---------------------------------------------------------------------------
# Reaping + invariants (the chaos harness's contract surface)
# ---------------------------------------------------------------------------

def reap_pool(pool_dir: str = consts.SPILL_DIR,
              stale_s: float | None = None) -> int:
    """Delete pool files whose owner is dead (plus torn ``.tmp``
    orphans past the staleness window). The vmem ledger reaps the
    matching accounting rows by its own dead+stale rule, so bytes and
    budget converge from either side after a crash. Returns files
    removed. Runs in the node daemon (the vmem-reaper's cadence)."""
    if stale_s is None:
        stale_s = vmem_mod._stale_reap_ns() / 1e9
    removed = 0
    try:
        names = os.listdir(pool_dir)
    except OSError:
        return 0
    now = time.time()
    for name in names:
        path = os.path.join(pool_dir, name)
        if ".tmp." in name:
            # a torn spill's leftover: the rename never happened, no
            # accounting references it — age it out conservatively
            try:
                if now - os.path.getmtime(path) > stale_s:
                    os.unlink(path)
                    removed += 1
            except OSError:
                continue
            continue
        parsed = _parse_pool_name(name)
        if parsed is None:
            continue
        _token, pid, _hidx, _buf = parsed
        try:
            dead = not vmem_mod._pid_alive(pid)
            stale = now - os.path.getmtime(path) > stale_s
        except OSError:
            continue
        if dead and stale:
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                log.warning("could not reap spill file %s", path)
    return removed


def pool_totals(pool_dir: str = consts.SPILL_DIR) -> tuple[int, int]:
    """(files, bytes) currently in the pool — the rollup/vtpu-smi view
    and the reconciliation side of the ledger's spilled sum."""
    files = total = 0
    try:
        names = os.listdir(pool_dir)
    except OSError:
        return 0, 0
    for name in names:
        if _parse_pool_name(name) is None:
            continue
        try:
            total += os.path.getsize(os.path.join(pool_dir, name))
            files += 1
        except OSError:
            continue
    return files, total


def assert_node_invariants(ledger: "vmem_mod.VmemLedger",
                           chip_capacity: dict[int, int],
                           budget_bytes: int) -> None:
    """The per-node safety contract, checked pre-write by spill() and
    at every chaos round: Σ resident physical HBM per chip <= chip
    capacity, and Σ spilled bytes <= the node spill budget. Raises
    AssertionError with the offending sums."""
    for host_index, capacity in chip_capacity.items():
        resident = ledger.device_total(host_index)
        assert resident <= capacity, (
            f"chip {host_index}: resident {resident}B > physical "
            f"{capacity}B — the spill tier failed to keep residency "
            f"under the physical cap")
    if budget_bytes:
        spilled = ledger.node_spilled_total()
        assert spilled <= budget_bytes, (
            f"node spill pool {spilled}B > budget {budget_bytes}B")
