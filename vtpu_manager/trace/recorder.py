"""vtrace span recorder: bounded ring + per-process JSONL spool.

Hot allocation paths (the scheduler filter holds the serial section, the
plugin's Allocate holds kubelet's attention) must never pay disk I/O to
be observed, so recording is two-phase, following the node's existing
shared-state idioms:

- ``record()`` appends to a bounded in-memory ring under a plain
  ``threading.Lock`` held only for the append (lock-cheap, the
  seqlock-writer discipline: no I/O, no allocation storms under the
  lock), and at the half-full threshold merely WAKES the flusher — it
  never performs I/O itself, so a hung disk cannot stall a filter pass
  or an Allocate from inside a span exit. A full ring DROPS the span
  and counts it — backpressure must never reach the instrumented path.
- ``flush()`` (driven by the background flusher thread the module
  ``configure()`` starts, and atexit) snapshots-and-clears the ring
  under that same short lock, then appends JSONL to the per-process
  spool file under a ``FileLock`` (the flock discipline every
  cross-process file on the node uses), so concurrent flushers and the
  monitor's readers never interleave a torn line. Cumulative drop
  counts ride along as ``meta`` records so the monitor can export
  ``vtpu_trace_spool_dropped_total`` without asking the process.

One recorder per process (module singleton in ``vtpu_manager.trace``);
spool files are ``<service>.<pid>.jsonl`` under the trace dir, so
restarts and multi-process nodes never contend for a file.

Retention: a spool reaching ``max_spool_bytes`` is rotated to a single
``<service>.<pid>.prev.jsonl`` generation (still read by assembly), so
one process is bounded at ~2x the cap; spools whose process is long
gone are reaped by ``reap_stale_spools`` (the monitor calls it before
each read).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from vtpu_manager.resilience import failpoints
from vtpu_manager.trace.context import TraceContext
from vtpu_manager.util.flock import FileLock

SPOOL_SUFFIX = ".jsonl"
DEFAULT_CAPACITY = 512
DEFAULT_MAX_SPOOL_BYTES = 16 * 2**20
DEFAULT_FLUSH_INTERVAL_S = 1.0
# a spool untouched this long belongs to a dead process (live recorders
# re-stamp their meta line at least every flush interval while tracing)
DEFAULT_SPOOL_TTL_S = 24 * 3600.0


@dataclass
class Span:
    """One timed stage of a pod's allocation path."""

    stage: str                 # e.g. "scheduler.filter"
    trace_id: str = ""
    pod_uid: str = ""
    service: str = ""          # emitting process ("scheduler", "plugin"...)
    start_s: float = 0.0       # wall clock (cross-process join axis)
    dur_s: float = 0.0
    attrs: dict = field(default_factory=dict)

    def to_wire(self) -> dict:
        out = {"kind": "span", "stage": self.stage, "trace": self.trace_id,
               "pod": self.pod_uid, "service": self.service,
               "start": round(self.start_s, 6), "dur": round(self.dur_s, 6)}
        if self.attrs:
            out["attrs"] = self.attrs
        return out

    @classmethod
    def from_wire(cls, doc: dict) -> "Span":
        return cls(stage=str(doc.get("stage", "")),
                   trace_id=str(doc.get("trace", "")),
                   pod_uid=str(doc.get("pod", "")),
                   service=str(doc.get("service", "")),
                   start_s=float(doc.get("start", 0.0)),
                   dur_s=float(doc.get("dur", 0.0)),
                   attrs=dict(doc.get("attrs") or {}))


class SpanRecorder:
    def __init__(self, service: str, spool_dir: str,
                 capacity: int = DEFAULT_CAPACITY,
                 flush_at: int | None = None,
                 max_spool_bytes: int = DEFAULT_MAX_SPOOL_BYTES):
        self.service = service
        self.spool_dir = spool_dir
        self.capacity = max(1, capacity)
        self.max_spool_bytes = max_spool_bytes
        self.spool_path = os.path.join(
            spool_dir, f"{service}.{os.getpid()}{SPOOL_SUFFIX}")
        self._lock = threading.Lock()
        self._buf: list[Span] = []
        self._dropped = 0
        self._flushed_drops = -1   # last drop count written to the spool
        # wake the flusher when the ring is half full so a burst inside
        # one long filter pass doesn't hit the drop path before the
        # interval tick; > capacity disables the early wake (ring tests)
        self._flush_at = flush_at if flush_at is not None \
            else max(1, self.capacity // 2)
        self._wake = threading.Event()
        self._stop = False

    # -- hot path ------------------------------------------------------------

    def record(self, span: Span) -> bool:
        """Append to the ring; False (and a drop count) when full. Never
        performs I/O — a full-enough ring only wakes the flusher."""
        span.service = span.service or self.service
        with self._lock:
            if len(self._buf) >= self.capacity:
                self._dropped += 1
                return False
            self._buf.append(span)
            pending = len(self._buf)
        if pending >= self._flush_at:
            self._wake.set()
        return True

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    # -- spool ---------------------------------------------------------------

    def flush(self) -> int:
        """Drain the ring to the spool. Returns spans written. The ring
        lock covers only the snapshot; the file I/O runs under the spool
        flock alone (never nested — the recorder promises the hot path
        the ring lock is always short)."""
        with self._lock:
            spans = self._buf
            self._buf = []
            drops = self._dropped
        if not spans and drops == self._flushed_drops:
            return 0
        lines = [json.dumps(s.to_wire(), separators=(",", ":"))
                 for s in spans]
        lines.append(json.dumps(
            {"kind": "meta", "service": self.service, "pid": os.getpid(),
             "drops": drops, "ts": round(time.time(), 3)},
            separators=(",", ":")))
        try:
            # arm with exc=OSError to drive the spans-become-drops path
            failpoints.fire("trace.spool_flush", path=self.spool_path)
            os.makedirs(self.spool_dir, exist_ok=True)
            with FileLock(f"{self.spool_path}.flock"):
                self._rotate_if_large()
                with open(self.spool_path, "a") as f:
                    f.write("\n".join(lines) + "\n")
        except OSError:
            # spool unavailable (disk full, dir unwritable): the spans
            # are lost — count them as drops so the loss is visible in
            # vtpu_trace_spool_dropped_total rather than silent
            with self._lock:
                self._dropped += len(spans)
            return 0
        self._flushed_drops = drops
        return len(spans)

    def _rotate_if_large(self) -> None:
        """Bound this process's spool at ~2x max_spool_bytes: the current
        file rotates to one .prev generation (named *.jsonl so assembly
        still reads it) which the next rotation overwrites. Caller holds
        the spool flock."""
        try:
            size = os.path.getsize(self.spool_path)
        except OSError:
            return
        if size < self.max_spool_bytes:
            return
        prev = self.spool_path[:-len(SPOOL_SUFFIX)] + f".prev{SPOOL_SUFFIX}"
        os.replace(self.spool_path, prev)

    # -- flusher thread (started by vtpu_manager.trace.configure) ------------

    def run_flusher(self,
                    interval_s: float = DEFAULT_FLUSH_INTERVAL_S) -> None:
        """Flush loop: every ``interval_s``, or immediately when record()
        wakes us at the ring threshold. All spool I/O happens here (and
        at atexit) — never on an instrumented thread."""
        while not self._stop:
            self._wake.wait(interval_s)
            self._wake.clear()
            self.flush()

    def stop_flusher(self) -> None:
        self._stop = True
        self._wake.set()


def reap_stale_spools(spool_dir: str,
                      max_age_s: float = DEFAULT_SPOOL_TTL_S) -> int:
    """Delete spools (and their flocks) untouched for ``max_age_s`` —
    leftovers of dead processes. Called by the monitor before reads;
    returns files removed. Live spools are safe: their recorder re-stamps
    mtime on every flush."""
    removed = 0
    if not os.path.isdir(spool_dir):
        return removed
    cutoff = time.time() - max_age_s
    for name in os.listdir(spool_dir):
        if not (name.endswith(SPOOL_SUFFIX)
                or name.endswith(f"{SPOOL_SUFFIX}.flock")):
            continue
        path = os.path.join(spool_dir, name)
        try:
            if os.path.getmtime(path) < cutoff:
                os.unlink(path)
                removed += 1
        except OSError:
            continue
    return removed
