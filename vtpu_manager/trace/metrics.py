"""vtrace Prometheus rendering: spool spans -> per-stage histograms.

The monitor (cmd/device_monitor.py) appends this to its /metrics output:
aggregate visibility rides the existing scrape path while the full
per-pod timelines stay behind /traces. Rendered fresh per scrape from
the node's spools — the monitor holds no trace state, matching how the
collector reads the tc/vmem feeds.

``vtpu_trace_spool_dropped_total`` is the subsystem's own health signal:
nonzero means the ring backpressured and timelines have holes — raise
the flush cadence or lower the sampling rate before trusting latencies.
"""

from __future__ import annotations

from vtpu_manager.trace.assemble import read_spools, stage_durations
from vtpu_manager.trace.recorder import Span

# admission/bind stages sit in the low milliseconds; shim startup can
# reach seconds — one bucket ladder covers both ends
BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

HIST_NAME = "vtpu_trace_stage_duration_seconds"
DROP_NAME = "vtpu_trace_spool_dropped_total"


def _fmt(value: float) -> str:
    return f"{value:g}"


def render_spans(spans: list[Span],
                 drops: dict[tuple[str, int], int]) -> str:
    lines = [
        f"# HELP {HIST_NAME} Duration of each vtrace allocation-path "
        f"stage, from the node's span spools",
        f"# TYPE {HIST_NAME} histogram",
    ]
    for stage, durs in sorted(stage_durations(spans).items()):
        cumulative = 0
        for le in BUCKETS_S:
            cumulative = sum(1 for d in durs if d <= le)
            lines.append(f'{HIST_NAME}_bucket{{stage="{stage}",'
                         f'le="{_fmt(le)}"}} {cumulative}')
        lines.append(f'{HIST_NAME}_bucket{{stage="{stage}",le="+Inf"}} '
                     f'{len(durs)}')
        lines.append(f'{HIST_NAME}_sum{{stage="{stage}"}} '
                     f'{_fmt(round(sum(durs), 6))}')
        lines.append(f'{HIST_NAME}_count{{stage="{stage}"}} {len(durs)}')
    lines += [
        f"# HELP {DROP_NAME} Spans dropped by each process's bounded "
        f"ring (nonzero = timelines have holes)",
        f"# TYPE {DROP_NAME} counter",
    ]
    by_service: dict[str, int] = {}
    for (service, _pid), count in drops.items():
        by_service[service] = by_service.get(service, 0) + count
    for service in sorted(by_service):
        lines.append(f'{DROP_NAME}{{service="{service}"}} '
                     f'{by_service[service]}')
    return "\n".join(lines) + "\n"


def render_trace_metrics(spool_dir: str) -> str:
    """One-call render for the monitor's scrape path."""
    spans, drops = read_spools(spool_dir)
    return render_spans(spans, drops)
