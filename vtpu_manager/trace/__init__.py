"""vtrace: end-to-end allocation-path tracing across the six binaries.

Answers the question no aggregate gauge can: *where did this pod's
admission-to-running latency go* — admission mutate, filter scoring, gang
resolution, bind patch, device-plugin Allocate + config generation, DRA
prepare/CDI, registry registration, shim startup — across process
boundaries, joined by a trace id minted at admission (annotation-
propagated, env-injected into containers) or by pod uid where
annotations can't reach (DRA claims, the registry socket).

Gated behind the ``Tracing`` feature gate, default off. This module is
the zero-overhead seam: until ``configure()`` runs, every entry point
returns a constant after one ``is None`` check — no clock reads, no
allocation, no recorder. With tracing on but a pod unsampled, ``span()``
returns the shared null span the same way, so the sampling knob bounds
the cost at any admission rate.

Usage (instrumented sites)::

    ctx = trace.context_for_pod(pod)          # None when off/untraced
    with trace.span(ctx, "scheduler.filter", nodes=len(nodes)):
        ...

Spools are per-process JSONL files (recorder.py); ``scripts/vtrace.py``
and the monitor's ``/traces`` endpoint assemble them into per-pod
timelines (assemble.py) and Prometheus histograms (metrics.py).
"""

from __future__ import annotations

import atexit
import threading
import time

from vtpu_manager.trace import context as _context
from vtpu_manager.trace.context import TraceContext
from vtpu_manager.trace.recorder import (DEFAULT_CAPACITY,
                                         DEFAULT_FLUSH_INTERVAL_S, Span,
                                         SpanRecorder)
from vtpu_manager.util import consts

__all__ = ["TraceContext", "Span", "SpanRecorder", "configure", "reset",
           "is_enabled", "sampling_rate", "recorder", "flush",
           "mint_for_pod", "context_for_pod", "context_for_claim",
           "context_for_uid", "context_from_env", "span", "event",
           "annotation_values"]


class _Config:
    __slots__ = ("service", "rate", "recorder")

    def __init__(self, service: str, rate: float, rec: SpanRecorder):
        self.service = service
        self.rate = rate
        self.recorder = rec


_cfg: _Config | None = None
_atexit_registered = False


def configure(service: str, spool_dir: str | None = None,
              sampling_rate: float = 1.0,
              capacity: int = DEFAULT_CAPACITY,
              flush_interval_s: float = DEFAULT_FLUSH_INTERVAL_S) -> None:
    """Enable tracing for this process (binaries call this when the
    Tracing gate is on). Starts the background flusher — ALL spool I/O
    runs on that daemon thread (plus atexit), never on an instrumented
    thread. Idempotent-by-replacement: reconfiguring swaps recorder and
    flusher (tests); the final flush is registered once."""
    global _cfg, _atexit_registered
    if _cfg is not None:
        _cfg.recorder.stop_flusher()
    rate = min(1.0, max(0.0, sampling_rate))
    rec = SpanRecorder(service, spool_dir or consts.TRACE_DIR,
                       capacity=capacity)
    _cfg = _Config(service, rate, rec)
    threading.Thread(target=rec.run_flusher, args=(flush_interval_s,),
                     daemon=True, name="vtrace-flush").start()
    if not _atexit_registered:
        atexit.register(flush)
        _atexit_registered = True


def reset() -> None:
    """Disable tracing (tests; restores the zero-overhead path)."""
    global _cfg
    if _cfg is not None:
        _cfg.recorder.stop_flusher()
    _cfg = None


def is_enabled() -> bool:
    return _cfg is not None


def sampling_rate() -> float:
    return _cfg.rate if _cfg is not None else 0.0


def recorder() -> SpanRecorder | None:
    return _cfg.recorder if _cfg is not None else None


def flush() -> int:
    return _cfg.recorder.flush() if _cfg is not None else 0


# -- context factories (all return None when tracing is off) ----------------

def mint_for_pod(pod: dict) -> TraceContext | None:
    """Admission-time mint (webhook mutate). Returns a context even for
    unsampled pods — the decision must still propagate so downstream
    stages skip coherently instead of re-deciding."""
    if _cfg is None:
        return None
    return _context.mint(pod, _cfg.rate)


def context_for_pod(pod: dict) -> TraceContext | None:
    if _cfg is None:
        return None
    return _context.from_pod(pod)


def context_for_claim(claim: dict) -> TraceContext | None:
    if _cfg is None:
        return None
    return _context.for_claim(claim, _cfg.rate)


def context_for_uid(pod_uid: str) -> TraceContext | None:
    if _cfg is None:
        return None
    return _context.for_uid(pod_uid, _cfg.rate)


def context_from_env(environ: dict | None = None) -> TraceContext | None:
    if _cfg is None:
        return None
    return _context.from_env(environ)


def annotation_values(ctx: TraceContext) -> dict[str, str]:
    """The annotations that propagate a context between binaries."""
    return {consts.trace_id_annotation(): ctx.trace_id,
            consts.trace_sampled_annotation():
                "true" if ctx.sampled else "false"}


# -- span emission ----------------------------------------------------------

class _NullSpan:
    """Shared no-op context manager for the off/unsampled path."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    __slots__ = ("_cfg", "_ctx", "_stage", "_attrs", "_start", "_t0")

    def __init__(self, cfg: _Config, ctx: TraceContext, stage: str,
                 attrs: dict):
        self._cfg = cfg
        self._ctx = ctx
        self._stage = stage
        self._attrs = attrs

    def __enter__(self) -> TraceContext:
        self._start = time.time()
        self._t0 = time.perf_counter()
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        attrs = self._attrs
        if exc_type is not None:
            # a failed stage is exactly the span an operator hunts for
            attrs = dict(attrs, error=exc_type.__name__)
        self._cfg.recorder.record(Span(
            stage=self._stage, trace_id=self._ctx.trace_id,
            pod_uid=self._ctx.pod_uid, service=self._cfg.service,
            start_s=self._start, dur_s=dur, attrs=attrs))
        return False


def span(ctx: TraceContext | None, stage: str, **attrs):
    """Timed span context manager. The off path is one attribute load
    and two ``is``/truth checks — no object construction."""
    cfg = _cfg
    if cfg is None or ctx is None or not ctx.sampled:
        return _NULL_SPAN
    return _LiveSpan(cfg, ctx, stage, attrs)


def event(ctx: TraceContext | None, stage: str, **attrs) -> None:
    """Zero-duration marker (e.g. shim first-execute)."""
    cfg = _cfg
    if cfg is None or ctx is None or not ctx.sampled:
        return
    cfg.recorder.record(Span(
        stage=stage, trace_id=ctx.trace_id, pod_uid=ctx.pod_uid,
        service=cfg.service, start_s=time.time(), dur_s=0.0, attrs=attrs))
