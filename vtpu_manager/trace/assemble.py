"""vtrace timeline assembly: spools -> per-pod allocation timelines.

Each process spools its own spans (recorder.py); nothing at record time
pays for cross-process correlation. Assembly is the read-side join, run
by the monitor's ``/traces`` endpoint and the vtrace CLI:

- spans carrying a trace id join by trace id (webhook/scheduler/plugin —
  the annotation-propagated stages);
- spans carrying only a pod uid (DRA prepare, registry registration)
  join through the uid<->trace mapping the annotated spans establish;
- the result is one timeline per pod: spans ordered by wall-clock start,
  with the canonical stage order breaking ties so e.g. a same-millisecond
  filter and gang span render in causal order.

Wall-clock start times are the cross-process axis (processes on one node
share a clock to well under the millisecond latencies measured here);
durations are perf_counter deltas and immune to clock steps.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from vtpu_manager.trace.recorder import SPOOL_SUFFIX, Span

# Canonical allocation-path order: admission -> scheduling -> node
# preparation -> tenant startup. Used for tie-breaking and for naming
# the expected next hop when the CLI flags a gap.
STAGE_ORDER = (
    "webhook.mutate",
    "scheduler.filter",
    "scheduler.gang",
    "scheduler.preempt",
    "scheduler.bind",
    "plugin.allocate",
    "plugin.config",
    "dra.prepare",
    "dra.cdi",
    "registry.register",
    "shim.install",
    "shim.register",
    "shim.first_execute",
)

_STAGE_RANK = {s: i for i, s in enumerate(STAGE_ORDER)}


@dataclass
class Timeline:
    pod_uid: str = ""
    trace_id: str = ""
    spans: list[Span] = field(default_factory=list)

    def key(self) -> str:
        return self.pod_uid or self.trace_id

    def sort(self) -> None:
        self.spans.sort(key=lambda s: (s.start_s,
                                       _STAGE_RANK.get(s.stage, 99)))

    def total_s(self) -> float:
        """First-start to last-end across the assembled path."""
        if not self.spans:
            return 0.0
        start = min(s.start_s for s in self.spans)
        end = max(s.start_s + s.dur_s for s in self.spans)
        return end - start

    def stages(self) -> set[str]:
        return {s.stage for s in self.spans}

    def to_wire(self) -> dict:
        return {"pod_uid": self.pod_uid, "trace_id": self.trace_id,
                "total_s": round(self.total_s(), 6),
                "spans": [s.to_wire() for s in self.spans]}


def read_spools(spool_dir: str) -> tuple[list[Span],
                                         dict[tuple[str, int], int]]:
    """(spans, cumulative drops per (service, pid)). Unparseable lines
    (a spooling process killed mid-write before the flock protocol was
    in force, operator edits) are skipped, not fatal — the read side
    must degrade to a partial timeline, never to no timeline."""
    spans: list[Span] = []
    drops: dict[tuple[str, int], int] = {}
    if not os.path.isdir(spool_dir):
        return spans, drops
    for name in sorted(os.listdir(spool_dir)):
        if not name.endswith(SPOOL_SUFFIX):
            continue
        try:
            with open(os.path.join(spool_dir, name)) as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if not isinstance(doc, dict):
                continue
            if doc.get("kind") == "meta":
                key = (str(doc.get("service", "")),
                       int(doc.get("pid", 0) or 0))
                # drops are cumulative per process: keep the newest count
                drops[key] = max(drops.get(key, 0),
                                 int(doc.get("drops", 0) or 0))
            elif doc.get("kind") == "span":
                spans.append(Span.from_wire(doc))
    return spans, drops


def assemble(spans: list[Span]) -> dict[str, Timeline]:
    """Join spans into per-pod timelines, keyed by pod uid (or trace id
    for spans whose pod uid never became known)."""
    uid_by_trace: dict[str, str] = {}
    trace_by_uid: dict[str, str] = {}
    for s in spans:
        if s.trace_id and s.pod_uid:
            uid_by_trace.setdefault(s.trace_id, s.pod_uid)
            trace_by_uid.setdefault(s.pod_uid, s.trace_id)
    out: dict[str, Timeline] = {}
    for s in spans:
        uid = s.pod_uid or uid_by_trace.get(s.trace_id, "")
        key = uid or s.trace_id
        if not key:
            continue
        tl = out.get(key)
        if tl is None:
            tl = out[key] = Timeline(pod_uid=uid)
        tl.trace_id = (tl.trace_id or s.trace_id
                       or trace_by_uid.get(uid, ""))
        tl.pod_uid = tl.pod_uid or uid
        tl.spans.append(s)
    for tl in out.values():
        tl.sort()
    return out


def find_timeline(timelines: dict[str, Timeline],
                  key: str) -> Timeline | None:
    """Lookup by assembly key (pod uid) OR by trace id — an operator may
    hold either (the uid from kubectl, the trace id from an annotation
    or a spool line), and a timeline with any uid-bearing span is keyed
    by the uid even when the caller has the trace id."""
    tl = timelines.get(key)
    if tl is not None:
        return tl
    for tl in timelines.values():
        if tl.trace_id == key:
            return tl
    return None


def critical_path(tl: Timeline) -> list[dict]:
    """Per-stage rows with offsets and inter-stage gaps: where the
    admission-to-running time actually went. The gap before a stage is
    time attributed to NO instrumented stage (queueing, kubelet work,
    watch lag) — often the real finding."""
    rows: list[dict] = []
    if not tl.spans:
        return rows
    origin = min(s.start_s for s in tl.spans)
    prev_end = origin
    for s in tl.spans:
        rows.append({
            "stage": s.stage,
            "service": s.service,
            "offset_s": round(s.start_s - origin, 6),
            "dur_s": round(s.dur_s, 6),
            "gap_s": round(max(0.0, s.start_s - prev_end), 6),
            "attrs": s.attrs,
        })
        prev_end = max(prev_end, s.start_s + s.dur_s)
    return rows


def stage_durations(spans: list[Span]) -> dict[str, list[float]]:
    out: dict[str, list[float]] = {}
    for s in spans:
        out.setdefault(s.stage, []).append(s.dur_s)
    return out


def _median(values: list[float]) -> float:
    ordered = sorted(values)
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def outliers(spans: list[Span], factor: float = 3.0,
             floor_s: float = 0.001) -> list[dict]:
    """Spans whose duration exceeds ``factor``x their stage's median
    (and an absolute floor, so microsecond jitter on fast stages never
    alarms). The per-stage population is the fleet baseline; a single
    sample can't be its own outlier."""
    by_stage = stage_durations(spans)
    medians = {stage: _median(durs) for stage, durs in by_stage.items()
               if len(durs) >= 2}
    out = []
    for s in spans:
        med = medians.get(s.stage)
        if med is None:
            continue
        if s.dur_s >= floor_s and s.dur_s > factor * med:
            out.append({"stage": s.stage, "pod_uid": s.pod_uid,
                        "trace_id": s.trace_id,
                        "dur_s": round(s.dur_s, 6),
                        "median_s": round(med, 6),
                        "factor": round(s.dur_s / med, 1) if med else 0.0})
    out.sort(key=lambda r: r["dur_s"], reverse=True)
    return out
