"""vtrace trace context: identity + sampling decision, minted once.

The context is created at admission (webhook mutate) and crosses every
process boundary the allocation path crosses, using the channels the
framework already has: pod annotations between the control-plane binaries
(the same channel pre-allocation uses) and container env vars into the
tenant (the same channel the enforcement limits use). DRA claims and the
registry socket don't carry annotations — those stages join the timeline
by pod/claim uid instead (assemble.py joins on either key).

The sampling decision is made ONCE, at mint time, and propagated as an
annotation: downstream stages must all record or all skip, or a timeline
assembles with holes that read as latency. Sampling is deterministic in
the trace id (fnv64 bucket), so a given pod's fate is reproducible and a
fleet-wide rate needs no coordination.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from vtpu_manager.config.vmem import fnv64
from vtpu_manager.util import consts

_SAMPLE_BUCKETS = 1 << 20


@dataclass(frozen=True)
class TraceContext:
    trace_id: str
    pod_uid: str = ""
    sampled: bool = True


def _sample(trace_id: str, rate: float) -> bool:
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (fnv64(trace_id) % _SAMPLE_BUCKETS) < int(rate * _SAMPLE_BUCKETS)


def mint(pod: dict, rate: float = 1.0) -> TraceContext:
    """New context for a pod at admission. The trace id is derived from
    the pod uid when the API server already assigned one (CREATE
    admission usually has it), else random — either way unique per
    admission attempt is not required, unique per pod is."""
    meta = pod.get("metadata") or {}
    uid = meta.get("uid", "")
    trace_id = uid or os.urandom(8).hex()
    return TraceContext(trace_id=trace_id, pod_uid=uid,
                        sampled=_sample(trace_id, rate))


def from_pod(pod: dict) -> TraceContext | None:
    """Context a prior stage propagated via annotations; None when the
    pod was never admitted under tracing (no annotation = no trace)."""
    meta = pod.get("metadata") or {}
    anns = meta.get("annotations") or {}
    trace_id = anns.get(consts.trace_id_annotation())
    if not trace_id:
        return None
    sampled = anns.get(consts.trace_sampled_annotation(), "true") == "true"
    return TraceContext(trace_id=trace_id, pod_uid=meta.get("uid", ""),
                        sampled=sampled)


def from_env(environ: dict | None = None) -> TraceContext | None:
    """Context injected into a tenant container (Allocate env vars)."""
    env = os.environ if environ is None else environ
    trace_id = env.get(consts.ENV_TRACE_ID, "")
    if not trace_id:
        return None
    return TraceContext(
        trace_id=trace_id,
        pod_uid=env.get(consts.ENV_POD_UID, ""),
        sampled=env.get(consts.ENV_TRACE_SAMPLED, "true") == "true")


def for_claim(claim: dict, rate: float = 1.0) -> TraceContext | None:
    """Context for a DRA claim: claims carry no trace annotation, so the
    span joins the pod's timeline by uid — the first reservedFor pod (the
    normal single-consumer case) or, failing that, the claim uid.

    The sampling decision is RECOMPUTED from the uid: sampling is a
    deterministic function of the trace id, and the admission mint uses
    the pod uid as the trace id whenever one exists (the normal case),
    so uid-joined stages reach the same verdict as the webhook without
    any propagated bit — keeping the all-record-or-all-skip invariant
    (and the spool-volume bound) intact at the stages annotations can't
    reach. Only a pod admitted before the API server assigned a uid
    (random trace id) can diverge, and then only toward a missing
    dra/registry span, never an orphan timeline."""
    meta = claim.get("metadata") or {}
    reserved = ((claim.get("status") or {}).get("reservedFor")) or []
    pod_uid = ""
    for ref in reserved:
        if ref.get("resource", "pods") == "pods" and ref.get("uid"):
            pod_uid = ref["uid"]
            break
    uid = meta.get("uid", "")
    if not pod_uid and not uid:
        return None
    join_uid = pod_uid or uid
    return TraceContext(trace_id="", pod_uid=join_uid,
                        sampled=_sample(join_uid, rate))


def for_uid(pod_uid: str, rate: float = 1.0) -> TraceContext | None:
    """Context for a stage that only knows the pod uid (registry
    registration): joins by uid, no trace id of its own; sampling
    recomputed from the uid (see for_claim)."""
    if not pod_uid:
        return None
    return TraceContext(trace_id="", pod_uid=pod_uid,
                        sampled=_sample(pod_uid, rate))
