"""``step_telemetry.ring`` shm ABI: per-container step-telemetry ring.

vttel's L3 contract: a fixed-size mmap'd ring of fixed-width step
records, one per tenant container, living under the container config dir
(``<base>/<uid>_<cont>/telemetry/step_telemetry.ring`` on the host,
mounted read-write at ``MANAGER_BASE_DIR/telemetry`` in-container). The
tenant's step loop (runtime/client.py) is the writer; the node monitor
(metrics/collector.py) tails each ring by sequence cursor and folds the
deltas into per-pod Prometheus histograms. The C++ shim reads/writes the
same layout via library/include/vtpu_telemetry.h (static-asserted
mirror), so the record a Python trainer writes and the record the shim's
Execute hook would write are indistinguishable to the reader.

Concurrency: same discipline as the tc_util feed (config/tc_watcher.py)
— each record carries its own **seqlock** (writer forces ``seq | 1`` odd
before the payload, bumps to even after; readers retry on odd/changed
seq). The writer is single-per-ring by construction (the ring is private
to one container) and enforced across container restarts by one OFD
write lock on the header taken at *open* time — the hot path itself
takes no locks and does no I/O beyond the mmap stores.

Ring semantics: slot = index % capacity, oldest records overwritten.
The header's ``writes`` counter tells the reader where the head is; a
reader that fell more than ``capacity`` behind counts the overwritten
records as drops (exported as the ring-overwrite counter) instead of
serving torn or stale data — every validated record also self-identifies
(``record.index`` must equal the index the reader asked for), so a slot
overwritten mid-read can never be attributed to the wrong step.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from dataclasses import dataclass

from vtpu_manager.util.flock import byte_range_write_lock

MAGIC = 0x54535456          # "VTST" little-endian
# v2 (vtovc): the record grew a spill block — spilled_bytes (the
# tenant's host-pool footprint at step end, a gauge) and
# spill/fill_events (tier transitions since the previous record) — the
# channel that carries the shim's spill activity to the collector's
# vtpu_node_spill_* series and the scheduler's spill-rate pressure
# input. Strict version check, the config-ABI rule: rings are recreated
# per container and plugin + shim + monitor ship together per node.
# v3 (vtcomm): a comm block — comm_time_ns (measured collective +
# transfer span time inside the step), bytes_transferred (bytes
# observed moving: H2D/D2H transfers plus multi-chip collective
# payloads) and collective_count (multi-chip dispatches) — the channel
# that makes communication a MEASURED per-step quantity (the vtuse
# comm-intensity feed and the honest ICI-bucket currency both read
# it). CommTelemetry off writes zeros in all three: the v3 wire
# carries nothing beyond zeroed pad, the gate-off contract.
# v4 (vtslo): spill_fill_time_ns — the wall time the step spent inside
# the shim's host-tier demotions (TrySpillCold) and promotions
# (FillSpilled), accumulated per record exactly like the comm spans —
# so the SLO attribution plane's spill-fill component is MEASURED, not
# inferred from event counts. An unarmed spill tier (HBMOvercommit
# off) never measures one and the field stays zero — the same
# zeros-on-the-wire contract the v2 spill block and v3 comm block keep
# when their planes are off.
VERSION = 4
RING_CAPACITY = 256          # records; ~memory of the last 256 steps
TRACE_ID_LEN = 48            # same bound as vtpu_config's pod_uid

# Staleness budget of the shim's measured-collective signal (mirrored
# by vtpu_telemetry.h kCommSignalStalenessNs + CommCostUs): the ICI
# token bucket charges the measured collective-time EMA only while the
# last measured collective is younger than this; older (or absent —
# CommTelemetry off never measures one) falls back to the exec-cost
# EMA, the exact pre-v3 currency.
COMM_SIGNAL_STALENESS_NS = 10_000_000_000


def comm_cost_us(comm_ema_us: int, comm_age_ns: int,
                 exec_cost_us: int) -> int:
    """Python mirror of vtpu_telemetry.h CommCostUs — the ICI bucket's
    charge-selection rule, asserted identical cross-language by the
    test_config_abi g++ probe."""
    if comm_ema_us > 0 and 0 <= comm_age_ns <= COMM_SIGNAL_STALENESS_NS:
        return comm_ema_us
    return exec_cost_us

# header: magic u32, version u32, capacity i32, record_size i32,
# writer_pid i32, pad i32, writes u64 (total records ever published),
# trace_id[48] (vtrace join key; one per ring — a ring is one tenant
# process's step stream)
_HEADER_FMT = "<IIiiiiQ48s"
HEADER_SIZE = struct.calcsize(_HEADER_FMT)
assert HEADER_SIZE == 80

# record: seq u64 (per-record seqlock), index u64, start_mono_ns u64,
# duration_ns u64, throttle_wait_ns u64, hbm_highwater_bytes u64,
# flags u32, pad u32, spilled_bytes u64, spill_events u32,
# fill_events u32 (v2 spill block, vtovc), comm_time_ns u64,
# bytes_transferred u64, collective_count u32, pad2 u32 (v3 comm
# block, vtcomm; zeros when CommTelemetry is off),
# spill_fill_time_ns u64 (v4, vtslo; zeros when the spill tier never
# measured a demotion/promotion span)
_RECORD_FMT = "<QQQQQQIiQIIQQIIQ"
RECORD_SIZE = struct.calcsize(_RECORD_FMT)
assert RECORD_SIZE == 104

FILE_SIZE = HEADER_SIZE + RING_CAPACITY * RECORD_SIZE

FLAG_COMPILE = 0x1           # step paid a compile / first-execute
# vtheal: the step's Execute (or a transfer inside it) returned an
# error the shim/runtime recovered from. A NEW BIT in the existing v4
# flags field — no layout change, no version bump: v4 readers that
# don't know the bit see it as reserved-zero semantics (they only test
# FLAG_COMPILE), and the health plane's signals.py reads trailing
# streaks of it as dead-chip evidence (one errored step is a retry;
# a streak is a chip that stopped executing).
FLAG_EXEC_ERROR = 0x2

_WRITES_OFFSET = 24          # header offset of the u64 writes counter
_TRACE_ID_OFFSET = 32


def record_offset(slot: int) -> int:
    return HEADER_SIZE + slot * RECORD_SIZE


@dataclass
class StepRecord:
    index: int
    start_mono_ns: int
    duration_ns: int
    throttle_wait_ns: int = 0
    hbm_highwater_bytes: int = 0
    flags: int = 0
    spilled_bytes: int = 0       # host-pool footprint at step end (gauge)
    spill_events: int = 0        # HBM→host demotions since last record
    fill_events: int = 0         # host→HBM promotions since last record
    comm_time_ns: int = 0        # measured collective+transfer span time
    bytes_transferred: int = 0   # bytes observed moving since last record
    collective_count: int = 0    # multi-chip dispatches since last record
    spill_fill_time_ns: int = 0  # measured host-tier spill+fill span time

    @property
    def compiled(self) -> bool:
        return bool(self.flags & FLAG_COMPILE)

    @property
    def exec_error(self) -> bool:
        return bool(self.flags & FLAG_EXEC_ERROR)


class StepRingWriter:
    """Tenant-side writer. Construction does the one-time work (file
    create, mmap, writer-exclusion lock); ``record()`` is the hot path —
    mmap stores only, no locks, no syscalls."""

    def __init__(self, path: str, trace_id: str = "",
                 lock_timeout_s: float = 2.0):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if not os.path.exists(path) or os.path.getsize(path) != FILE_SIZE:
            # atomic create (tmp + rename): a reader mmaping the final
            # path must never observe a partial file
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(struct.pack(
                    _HEADER_FMT, MAGIC, VERSION, RING_CAPACITY,
                    RECORD_SIZE, os.getpid(), 0, 0,
                    trace_id.encode()[:TRACE_ID_LEN]))
                f.write(b"\0" * (FILE_SIZE - HEADER_SIZE))
            os.rename(tmp, path)
        self._fd = os.open(path, os.O_RDWR)
        try:
            # writer exclusion across container restarts: held for the
            # ring's lifetime (the kernel releases it on crash), taken
            # once here — never on the step path. Short timeout: a held
            # lock means another LIVE writer owns this ring, and waiting
            # the full lock budget would stall tenant startup
            self._lock_ctx = byte_range_write_lock(self._fd, 0, HEADER_SIZE,
                                                   timeout_s=lock_timeout_s)
            self._lock_ctx.__enter__()
            self._mm = mmap.mmap(self._fd, FILE_SIZE)
        except (ValueError, OSError):
            os.close(self._fd)
            self._fd = None
            raise
        magic, version, cap, rec_size, _, _, writes, _ = struct.unpack_from(
            _HEADER_FMT, self._mm, 0)
        if magic != MAGIC or version != VERSION or cap != RING_CAPACITY \
                or rec_size != RECORD_SIZE:
            self.close()
            raise ValueError(f"bad step ring {path}")
        # a restarted container continues the sequence: the reader's
        # cursor stays monotone across writer generations
        self._writes = writes
        struct.pack_into("<i", self._mm, 16, os.getpid())
        if trace_id:
            struct.pack_into(f"<{TRACE_ID_LEN}s", self._mm,
                             _TRACE_ID_OFFSET,
                             trace_id.encode()[:TRACE_ID_LEN])

    @property
    def writes(self) -> int:
        return self._writes

    def record(self, duration_ns: int, throttle_wait_ns: int = 0,
               hbm_highwater_bytes: int = 0, compiled: bool = False,
               start_mono_ns: int | None = None, spilled_bytes: int = 0,
               spill_events: int = 0, fill_events: int = 0,
               comm_time_ns: int = 0, bytes_transferred: int = 0,
               collective_count: int = 0,
               spill_fill_time_ns: int = 0,
               exec_error: bool = False) -> None:
        """Publish one step record (the hot path). Seqlock bracket per
        the shared-mmap protocol: odd seq first, payload, even seq last
        — ``seq | 1`` so a crashed writer's odd leftover can't invert
        parity and let torn reads validate."""
        if start_mono_ns is None:
            start_mono_ns = time.monotonic_ns() - duration_ns
        index = self._writes
        off = record_offset(index % RING_CAPACITY)
        seq, = struct.unpack_from("<Q", self._mm, off)
        wseq = seq | 1
        struct.pack_into("<Q", self._mm, off, wseq)      # odd: writing
        flags = (FLAG_COMPILE if compiled else 0) | \
            (FLAG_EXEC_ERROR if exec_error else 0)
        struct.pack_into(_RECORD_FMT, self._mm, off, wseq, index,
                         start_mono_ns, duration_ns, throttle_wait_ns,
                         hbm_highwater_bytes, flags, 0,
                         spilled_bytes, spill_events, fill_events,
                         comm_time_ns, bytes_transferred,
                         collective_count, 0, spill_fill_time_ns)
        struct.pack_into("<Q", self._mm, off, wseq + 1)  # even: stable
        self._writes = index + 1
        struct.pack_into("<Q", self._mm, _WRITES_OFFSET, self._writes)

    def close(self) -> None:
        if getattr(self, "_mm", None) is not None:
            self._mm.close()
            self._mm = None
        if getattr(self, "_lock_ctx", None) is not None:
            try:
                self._lock_ctx.__exit__(None, None, None)
            # unlock-at-teardown: the kernel drops the OFD lock with the
            # fd regardless, and interpreter shutdown can fail even the
            # import inside the unlock — nothing here is actionable
            # vtlint: disable=exception-hygiene
            except Exception:  # noqa: BLE001
                pass
            self._lock_ctx = None
        if getattr(self, "_fd", None) is not None:
            os.close(self._fd)
            self._fd = None


class StepRingReader:
    """Monitor-side reader: lock-free seqlock reads, cursor-tailed."""

    def __init__(self, path: str):
        self.path = path
        self._fd = os.open(path, os.O_RDONLY)
        try:
            self._mm = mmap.mmap(self._fd, FILE_SIZE,
                                 prot=mmap.PROT_READ)
        except (ValueError, OSError):
            os.close(self._fd)
            self._fd = None
            raise
        magic, version, cap, rec_size, pid, _, _, raw_tid = \
            struct.unpack_from(_HEADER_FMT, self._mm, 0)
        if magic != MAGIC or version != VERSION or cap != RING_CAPACITY \
                or rec_size != RECORD_SIZE:
            self.close()
            raise ValueError(f"bad step ring {path}")
        self.writer_pid = pid
        # the ring is writable by the TENANT: the trace id read back is
        # untrusted bytes headed for a Prometheus label — keep only the
        # charset real trace ids use (hex/uuid/word chars) so quotes or
        # newlines can't inject forged series into the node scrape
        raw = raw_tid.split(b"\0", 1)[0].decode(errors="replace")
        self.trace_id = "".join(
            c for c in raw if c.isalnum() or c in "._-")[:TRACE_ID_LEN]

    def close(self) -> None:
        if getattr(self, "_mm", None) is not None:
            self._mm.close()
            self._mm = None
        if getattr(self, "_fd", None) is not None:
            os.close(self._fd)
            self._fd = None

    def _writes(self) -> int | None:
        """The head counter, double-read until stable: a u64 store is
        not atomic for a byte-wise mmap reader, and a torn head must
        never bound the scan. None when it never stabilizes — the
        caller skips that poll; advancing the monotone cursor to a torn
        value would stall the tenant's telemetry forever."""
        for _ in range(8):
            w1, = struct.unpack_from("<Q", self._mm, _WRITES_OFFSET)
            w2, = struct.unpack_from("<Q", self._mm, _WRITES_OFFSET)
            if w1 == w2:
                return w1
        return None

    def head(self) -> int | None:
        """Public head counter (total records ever published), or None
        when it never stabilizes — the vtheal stall signal polls this
        instead of tailing records: progress is the head advancing."""
        return self._writes()

    def read_record(self, index: int, retries: int = 8
                    ) -> StepRecord | None:
        """Seqlock read of one logical record; None when the slot is
        mid-write for all retries or was overwritten by a newer index."""
        off = record_offset(index % RING_CAPACITY)
        for _ in range(retries):
            seq1, = struct.unpack_from("<Q", self._mm, off)
            if seq1 & 1:
                time.sleep(0.0002)
                continue
            (_, rec_index, start_ns, dur_ns, wait_ns, hbm, flags,
             _pad, spilled, spills, fills, comm_ns, comm_bytes,
             collectives, _pad2, spill_fill_ns) = struct.unpack_from(
                 _RECORD_FMT, self._mm, off)
            seq2, = struct.unpack_from("<Q", self._mm, off)
            if seq1 != seq2:
                continue
            if rec_index != index:
                return None     # lapped: slot already holds a newer step
            return StepRecord(rec_index, start_ns, dur_ns, wait_ns, hbm,
                              flags, spilled, spills, fills, comm_ns,
                              comm_bytes, collectives, spill_fill_ns)
        return None

    def poll(self, cursor: int) -> tuple[list[StepRecord], int, int]:
        """(records, new_cursor, dropped) — every record with index in
        [cursor, head) still resident in the ring, in order. ``dropped``
        counts records the writer overwrote before this poll reached
        them (reader lagged by more than RING_CAPACITY). The returned
        cursor is monotone within one ring generation; a stable head
        BELOW the cursor means the file was recreated (writer reset to
        0), and the tail restarts from the new generation's records
        instead of freezing forever on the stale cursor."""
        head = self._writes()
        if head is None or head == cursor:
            return [], cursor, 0
        if head < cursor:
            cursor = 0
            if head == 0:
                return [], 0, 0
        start = max(cursor, head - RING_CAPACITY)
        dropped = start - cursor
        out: list[StepRecord] = []
        for index in range(start, head):
            rec = self.read_record(index)
            if rec is None:
                # overwritten (or persistently mid-write) while we
                # scanned: everything at or before it is gone too
                dropped += 1
                continue
            out.append(rec)
        return out, head, dropped


# Layout tables consumed by the ABI contract test and the abi-drift
# vtlint rule (field -> offset; the C++ mirror static-asserts the same).
HEADER_OFFSETS = {
    "magic": 0, "version": 4, "capacity": 8, "record_size": 12,
    "writer_pid": 16, "pad": 20, "writes": 24, "trace_id": 32,
}
RECORD_OFFSETS = {
    "seq": 0, "index": 8, "start_mono_ns": 16, "duration_ns": 24,
    "throttle_wait_ns": 32, "hbm_highwater_bytes": 40, "flags": 48,
    "spilled_bytes": 56, "spill_events": 64, "fill_events": 68,
    "comm_time_ns": 72, "bytes_transferred": 80, "collective_count": 88,
    "spill_fill_time_ns": 96,
}
