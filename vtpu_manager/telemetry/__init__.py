"""vttel: tenant-side step telemetry.

What a tenant *experiences* per step — latency, throttle-stall time, HBM
high-water, compile hits — written from the step loop into a crash-safe
per-container seqlock shm ring (stepring.py), tailed by the node monitor
into per-pod Prometheus histograms (aggregate.py), and rolled up into a
node pressure annotation the scheduler scores against (pressure.py).
Gated behind the ``StepTelemetry`` feature gate: off, the plugin injects
nothing and the tenant-side check is one env-var branch.

The limit-side gauges (metrics/collector.py) say what a tenant is
*allowed*; vttel says what it *got* — the co-located-interference signal
FlexNPU-style fractional sharing needs (PAPERS.md).
"""

from vtpu_manager.telemetry.aggregate import TenantStepTelemetry
from vtpu_manager.telemetry.stepring import (StepRecord, StepRingReader,
                                             StepRingWriter)

__all__ = ["StepRecord", "StepRingReader", "StepRingWriter",
           "TenantStepTelemetry"]
