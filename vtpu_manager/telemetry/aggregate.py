"""vttel read side: tail step rings, fold deltas into per-pod metrics.

The monitor's collector owns one :class:`TenantStepTelemetry` for the
node. Each scrape calls :meth:`scan`, which discovers the rings under
the container config root (``<base>/<entry>/telemetry/step_telemetry.ring``
— same directory walk as the vtpu.config join), tails each by its
persisted sequence cursor, and folds the new records into *cumulative*
per-pod histograms: a Prometheus histogram must never go backwards, and
the ring only remembers the last RING_CAPACITY steps, so the scrape-time
fold (not the ring) is the system of record.

Derived signals per tenant: throttle-wait fraction over the *last
window* (between the two most recent polls — the interference signal),
steps/sec over the same window, the ring-overwrite drop counter, and the
HBM high-water. The node **pressure rollup** (max tenant throttle-wait
fraction + HBM headroom under the high-waters) feeds both the monitor's
gauges and the node-pressure annotation the scheduler ingests as a soft
scoring hint (telemetry/pressure.py).
"""

from __future__ import annotations

import logging
import os
import time

from vtpu_manager.telemetry import stepring
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

# step + throttle-wait ladder: sub-ms jitted steps up to multi-second
# compile-bound ones
DURATION_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                      0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# HBM high-water ladder: 16 MiB .. 64 GiB covers v5e..v5p per-chip HBM
HBM_BUCKETS_BYTES = tuple(1 << s for s in range(24, 37))
# vtcomm per-step bytes-moved ladder: 1 KiB .. 4 GiB in powers of 4
# (loss-scalar readbacks up to full-gradient all-reduces)
COMM_BUCKETS_BYTES = tuple(1 << s for s in range(10, 33, 2))

STEP_HIST = "vtpu_tenant_step_duration_seconds"
WAIT_HIST = "vtpu_tenant_throttle_wait_seconds"
HBM_HIST = "vtpu_tenant_hbm_highwater_bytes"
WAIT_FRAC = "vtpu_tenant_throttle_wait_fraction"
STEPS_PER_S = "vtpu_tenant_steps_per_second"
DROPS = "vtpu_tenant_step_ring_dropped_total"
INFO = "vtpu_tenant_step_info"
PRESSURE_FRAC = "vtpu_node_pressure_throttle_frac"
PRESSURE_HEADROOM = "vtpu_node_pressure_hbm_headroom_bytes"
# vtcomm families (CommTelemetry gate on only — off renders none)
COMM_HIST = "vtpu_tenant_comm_time_seconds"
COMM_BYTES_HIST = "vtpu_tenant_comm_bytes"
COMM_FRAC = "vtpu_tenant_comm_time_fraction"


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        for i, le in enumerate(self.buckets):
            if value <= le:
                self.counts[i] += 1
        self.sum += value
        self.count += 1

    def render(self, name: str, labels: str, lines: list[str]) -> None:
        # counts are ALREADY cumulative (observe increments every bucket
        # >= value) — do not sum here like the fresh-per-scrape trace
        # renderer does, that would double-count
        for le, n in zip(self.buckets, self.counts):
            lines.append(f'{name}_bucket{{{labels},le="{le:g}"}} {n}')
        lines.append(f'{name}_bucket{{{labels},le="+Inf"}} {self.count}')
        lines.append(f'{name}_sum{{{labels}}} {round(self.sum, 9):g}')
        lines.append(f'{name}_count{{{labels}}} {self.count}')


class _TenantState:
    """Cumulative fold + last-window derivatives for one ring."""

    __slots__ = ("pod_uid", "container", "trace_id", "cursor", "dropped",
                 "step_hist", "wait_hist", "hbm_hist", "hbm_highwater",
                 "window_frac", "window_rate", "last_poll_monotonic",
                 "primed", "comm_hist", "comm_bytes_hist",
                 "comm_window_frac")

    def __init__(self, pod_uid: str, container: str):
        self.pod_uid = pod_uid
        self.container = container
        self.trace_id = ""
        self.cursor = 0
        self.dropped = 0
        # False until the first poll: history already overwritten before
        # this aggregator ever looked is a baseline, not reader lag —
        # charging it as drops would fire data-loss alerts on every
        # monitor restart
        self.primed = False
        self.step_hist = _Hist(DURATION_BUCKETS_S)
        self.wait_hist = _Hist(DURATION_BUCKETS_S)
        self.hbm_hist = _Hist(HBM_BUCKETS_BYTES)
        self.hbm_highwater = 0
        self.window_frac = 0.0
        self.window_rate = 0.0
        self.last_poll_monotonic = 0.0
        # vtcomm (folded only when the aggregator's comm flag is on):
        # per-step measured comm-time / bytes-moved histograms + the
        # comm fraction of step time over the last window
        self.comm_hist = _Hist(DURATION_BUCKETS_S)
        self.comm_bytes_hist = _Hist(COMM_BUCKETS_BYTES)
        self.comm_window_frac = 0.0

    def fold(self, records: list[stepring.StepRecord], dropped: int,
             now_monotonic: float, comm: bool = False) -> None:
        self.dropped += dropped
        dur_sum = 0.0
        wait_sum = 0.0
        comm_sum = 0.0
        for rec in records:
            dur = rec.duration_ns / 1e9
            wait = rec.throttle_wait_ns / 1e9
            self.step_hist.observe(dur)
            self.wait_hist.observe(wait)
            self.hbm_hist.observe(rec.hbm_highwater_bytes)
            self.hbm_highwater = max(self.hbm_highwater,
                                     rec.hbm_highwater_bytes)
            dur_sum += dur
            wait_sum += wait
            # the ledger's no-signal rule: an all-zero comm block (gate
            # off at the shim, pre-arm, pre-v3 writer) is NOT a
            # measured zero — only comm-carrying records feed the
            # histograms, and a tenant with none stays series-less
            if comm and (rec.comm_time_ns or rec.bytes_transferred
                         or rec.collective_count):
                self.comm_hist.observe(rec.comm_time_ns / 1e9)
                self.comm_bytes_hist.observe(rec.bytes_transferred)
                comm_sum += rec.comm_time_ns / 1e9
        if records:
            # window derivatives from the records themselves, not the
            # poll interval: wall-vs-step time needs no clock agreement
            # with the tenant, and an idle window decays both to 0
            self.window_frac = wait_sum / dur_sum if dur_sum else 0.0
            if comm and self.comm_hist.count:
                # comm-measured tenants only: a window of genuine zero
                # comm decays the gauge, but a never-measured tenant
                # keeps no gauge at all (no signal != measured zero)
                self.comm_window_frac = comm_sum / dur_sum \
                    if dur_sum else 0.0
            if self.last_poll_monotonic:
                window_s = max(now_monotonic - self.last_poll_monotonic,
                               1e-9)
                # dropped records still HAPPENED: a tenant faster than
                # RING_CAPACITY per scrape must not read slower than it
                # is just because the ring lapped
                self.window_rate = (len(records) + dropped) / window_s
        elif self.last_poll_monotonic and now_monotonic \
                - self.last_poll_monotonic > 0:
            self.window_frac = 0.0
            self.window_rate = 0.0
            self.comm_window_frac = 0.0
        self.last_poll_monotonic = now_monotonic


class TenantStepTelemetry:
    """Node-wide scan/fold/render over every tenant's step ring."""

    def __init__(self, base_dir: str = consts.MANAGER_BASE_DIR,
                 comm: bool = False):
        self.base_dir = base_dir
        # vtcomm (CommTelemetry gate): fold + render the comm block's
        # histograms and the comm-fraction gauge. Off (the default) is
        # the gate-off contract — zero vtpu_tenant_comm_* series even
        # though v3 rings carry the (zeroed) block.
        self.comm = comm
        self._tenants: dict[tuple[str, str], _TenantState] = {}

    # -- discovery (same dir shapes as the collector's config join) ---------

    def _ring_paths(self) -> dict[tuple[str, str], str]:
        out: dict[tuple[str, str], str] = {}
        if not os.path.isdir(self.base_dir):
            return out
        for entry in sorted(os.listdir(self.base_dir)):
            ring = os.path.join(self.base_dir, entry,
                                consts.TELEMETRY_SUBDIR,
                                consts.STEP_RING_NAME)
            if not os.path.isfile(ring):
                continue
            pod_uid, _, container = entry.partition("_")
            out[(pod_uid, container)] = ring
        return out

    # -- scrape-path fold ----------------------------------------------------

    def scan(self) -> int:
        """Tail every ring once; tolerate rings appearing, vanishing, or
        being mid-create — a broken ring must cost its own tenant's
        freshness, never the scrape. Returns how many existing rings
        could not be read, so the collector's last-scrape-error flag can
        surface a wedged ring instead of silently serving stale
        series."""
        failed = 0
        now = time.monotonic()
        paths = self._ring_paths()
        # a removed tenant's series go with it (same lifecycle as the
        # per-container limit gauges)
        for key in list(self._tenants):
            if key not in paths:
                del self._tenants[key]
        for key, path in paths.items():
            state = self._tenants.get(key)
            if state is None:
                state = self._tenants[key] = _TenantState(*key)
            try:
                reader = stepring.StepRingReader(path)
            except (OSError, ValueError) as e:
                log.warning("step ring %s unreadable: %s", path, e)
                failed += 1
                continue
            try:
                if reader.trace_id:
                    state.trace_id = reader.trace_id
                records, cursor, dropped = reader.poll(state.cursor)
                state.cursor = cursor
                if not state.primed:
                    state.primed = True
                    dropped = 0
                state.fold(records, dropped, now, comm=self.comm)
            finally:
                reader.close()
        return failed

    # -- outputs -------------------------------------------------------------

    def tenants(self) -> list[_TenantState]:
        return list(self._tenants.values())

    def pressure(self, node_hbm_total: int) -> tuple[float, int]:
        """(max tenant throttle-wait fraction over the last window, HBM
        headroom = node HBM minus the sum of tenant high-waters, floored
        at 0). The scheduler's soft signal: a node whose tenants stall in
        the throttle, or whose high-waters approach physical HBM, scores
        down without ever failing the capacity gate."""
        max_frac = 0.0
        highwater_sum = 0
        for state in self._tenants.values():
            max_frac = max(max_frac, state.window_frac)
            highwater_sum += state.hbm_highwater
        return max_frac, max(0, node_hbm_total - highwater_sum)

    def render(self, node_name: str) -> str:
        lines = [
            f"# HELP {STEP_HIST} Tenant step duration from the step-"
            f"telemetry rings",
            f"# TYPE {STEP_HIST} histogram",
        ]
        tenants = sorted(self._tenants.values(),
                         key=lambda s: (s.pod_uid, s.container))
        for s in tenants:
            labels = (f'node="{node_name}",pod_uid="{s.pod_uid}",'
                      f'container="{s.container}"')
            s.step_hist.render(STEP_HIST, labels, lines)
        lines += [f"# HELP {WAIT_HIST} Time each step stalled in the "
                  f"compute throttle",
                  f"# TYPE {WAIT_HIST} histogram"]
        for s in tenants:
            labels = (f'node="{node_name}",pod_uid="{s.pod_uid}",'
                      f'container="{s.container}"')
            s.wait_hist.render(WAIT_HIST, labels, lines)
        lines += [f"# HELP {HBM_HIST} Per-step HBM high-water",
                  f"# TYPE {HBM_HIST} histogram"]
        for s in tenants:
            labels = (f'node="{node_name}",pod_uid="{s.pod_uid}",'
                      f'container="{s.container}"')
            s.hbm_hist.render(HBM_HIST, labels, lines)
        lines += [f"# HELP {WAIT_FRAC} Fraction of step time stalled in "
                  f"the throttle over the last scrape window",
                  f"# TYPE {WAIT_FRAC} gauge"]
        for s in tenants:
            lines.append(f'{WAIT_FRAC}{{node="{node_name}",'
                         f'pod_uid="{s.pod_uid}",'
                         f'container="{s.container}"}} '
                         f"{round(s.window_frac, 6)}")
        lines += [f"# HELP {STEPS_PER_S} Steps per second over the last "
                  f"scrape window",
                  f"# TYPE {STEPS_PER_S} gauge"]
        for s in tenants:
            lines.append(f'{STEPS_PER_S}{{node="{node_name}",'
                         f'pod_uid="{s.pod_uid}",'
                         f'container="{s.container}"}} '
                         f"{round(s.window_rate, 3)}")
        if self.comm:
            # vtcomm families (gate on only — the off branch renders
            # exactly the pre-v3 text, asserted byte-identical). Only
            # comm-MEASURED tenants get series: an unarmed tenant's
            # zeroed comm pad must not render as "measured zero"
            # (headers stay discoverable, the vttel convention).
            measured = [s for s in tenants if s.comm_hist.count]
            lines += [f"# HELP {COMM_HIST} Measured collective+transfer "
                      f"time inside each step (v3 comm block)",
                      f"# TYPE {COMM_HIST} histogram"]
            for s in measured:
                labels = (f'node="{node_name}",pod_uid="{s.pod_uid}",'
                          f'container="{s.container}"')
                s.comm_hist.render(COMM_HIST, labels, lines)
            lines += [f"# HELP {COMM_BYTES_HIST} Bytes observed moving "
                      f"per step (H2D/D2H transfers + collective "
                      f"payload lower bound)",
                      f"# TYPE {COMM_BYTES_HIST} histogram"]
            for s in measured:
                labels = (f'node="{node_name}",pod_uid="{s.pod_uid}",'
                          f'container="{s.container}"')
                s.comm_bytes_hist.render(COMM_BYTES_HIST, labels, lines)
            lines += [f"# HELP {COMM_FRAC} Fraction of step time spent "
                      f"in measured communication over the last scrape "
                      f"window",
                      f"# TYPE {COMM_FRAC} gauge"]
            for s in measured:
                lines.append(f'{COMM_FRAC}{{node="{node_name}",'
                             f'pod_uid="{s.pod_uid}",'
                             f'container="{s.container}"}} '
                             f"{round(s.comm_window_frac, 6)}")
        lines += [f"# HELP {DROPS} Step records overwritten before the "
                  f"monitor tailed them (reader lagged the ring)",
                  f"# TYPE {DROPS} counter"]
        for s in tenants:
            lines.append(f'{DROPS}{{node="{node_name}",'
                         f'pod_uid="{s.pod_uid}",'
                         f'container="{s.container}"}} {s.dropped}')
        lines += [f"# HELP {INFO} Step-telemetry stream identity; the "
                  f"trace_id label joins the vtrace timeline",
                  f"# TYPE {INFO} gauge"]
        for s in tenants:
            lines.append(f'{INFO}{{node="{node_name}",'
                         f'pod_uid="{s.pod_uid}",'
                         f'container="{s.container}",'
                         f'trace_id="{s.trace_id}"}} 1')
        return "\n".join(lines) + "\n"

    def render_pressure(self, node_name: str, node_hbm_total: int) -> str:
        frac, headroom = self.pressure(node_hbm_total)
        return (
            f"# HELP {PRESSURE_FRAC} Max tenant throttle-wait fraction "
            f"on this node (vttel pressure rollup)\n"
            f"# TYPE {PRESSURE_FRAC} gauge\n"
            f'{PRESSURE_FRAC}{{node="{node_name}"}} {round(frac, 6)}\n'
            f"# HELP {PRESSURE_HEADROOM} Node HBM minus the sum of "
            f"tenant step high-waters\n"
            f"# TYPE {PRESSURE_HEADROOM} gauge\n"
            f'{PRESSURE_HEADROOM}{{node="{node_name}"}} {headroom}\n')


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def step_stats_for_pod(base_dir: str, *keys: str) -> list[dict]:
    """Steady-state step stats for one pod, straight off its rings —
    the `vtrace --pod` splice. Any of ``keys`` may match the config-dir
    pod uid or the ring's vtrace trace id (records carry it so the step
    stream and the allocation timeline join on the same key); one
    directory pass serves every key. One ring holds only the last
    RING_CAPACITY steps; the dict says how many of the total that is."""
    out: list[dict] = []
    # empty keys must match NOTHING: rings written without a trace id
    # store "" too, and "" == "" would splice every untraced tenant's
    # steps onto whatever pod was asked about
    wanted = {k for k in keys if k}
    if not wanted or not os.path.isdir(base_dir):
        return out
    for entry in sorted(os.listdir(base_dir)):
        ring_path = os.path.join(base_dir, entry,
                                 consts.TELEMETRY_SUBDIR,
                                 consts.STEP_RING_NAME)
        if not os.path.isfile(ring_path):
            continue
        pod_uid, _, container = entry.partition("_")
        try:
            reader = stepring.StepRingReader(ring_path)
        except (OSError, ValueError):
            continue
        try:
            if not (wanted & {pod_uid, reader.trace_id}):
                continue
            records, head, _ = reader.poll(0)
            durs = sorted(r.duration_ns / 1e9 for r in records)
            waits = [r.throttle_wait_ns / 1e9 for r in records]
            dur_sum = sum(durs)
            row = {
                "pod_uid": pod_uid,
                "container": container,
                "trace_id": reader.trace_id,
                "steps_total": head,
                "steps_resident": len(records),
                "compile_steps": sum(1 for r in records if r.compiled),
                "p50_s": round(_quantile(durs, 0.5), 6),
                "p99_s": round(_quantile(durs, 0.99), 6),
                "throttle_wait_frac": round(
                    sum(waits) / dur_sum, 6) if dur_sum else 0.0,
                "hbm_highwater_bytes": max(
                    (r.hbm_highwater_bytes for r in records), default=0),
            }
            # vtcomm splice: present ONLY when the ring carries a
            # measured comm block (CommTelemetry armed this tenant) —
            # a gate-off ring's zeroed pad adds no keys, so the CLI
            # output stays byte-identical
            comm_ns = sum(r.comm_time_ns for r in records)
            comm_bytes = sum(r.bytes_transferred for r in records)
            collectives = sum(r.collective_count for r in records)
            if comm_ns or comm_bytes or collectives:
                row["comm_time_frac"] = round(
                    comm_ns / 1e9 / dur_sum, 6) if dur_sum else 0.0
                row["comm_bytes_per_step"] = (
                    comm_bytes // len(records)) if records else 0
                row["collectives"] = collectives
            out.append(row)
        finally:
            reader.close()
    return out
