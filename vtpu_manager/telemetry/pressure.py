"""Node pressure annotation: vttel's feedback edge into the scheduler.

The node daemon publishes a tiny rollup of what its tenants are
*experiencing* — max throttle-wait fraction over the last window and HBM
headroom under the step high-waters — as a node annotation, the same
channel the device registry uses. The scheduler snapshot decodes it at
event-apply time and the filter folds it into scoring as a **soft
penalty only**: pressure can reorder otherwise-equal nodes, it can never
fail the capacity gate (a pressured node with the only free chips still
schedules).

Wire format is deliberately parse-cheap (the scheduler may parse it per
node event): ``"<throttle_frac>:<hbm_headroom_bytes>@<wall_ts>"``. The
timestamp makes staleness explicit — a daemon that stops publishing must
decay to "no signal", not pin its last panic forever.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass

from vtpu_manager.util import consts, stalecodec

log = logging.getLogger(__name__)

# a rollup older than this reads as no-signal (the publisher cadence is
# seconds; 120 s means "daemon gone for two minutes")
MAX_PRESSURE_AGE_S = 120.0

# re-exported for existing importers; the one copy lives in stalecodec
FUTURE_SKEW_TOLERANCE_S = stalecodec.FUTURE_SKEW_TOLERANCE_S

# scoring weight: a fully-stalled node (frac 1.0) loses this many score
# points — bigger than any packing/topology delta, smaller than the +100
# gang-domain bonus (gang locality still wins; see filter.node_score)
PRESSURE_SCORE_WEIGHT = 50.0


@dataclass(frozen=True)
class NodePressure:
    throttle_frac: float
    hbm_headroom_bytes: int
    ts: float

    def encode(self) -> str:
        return stalecodec.stamp(
            f"{self.throttle_frac:.4f}:{self.hbm_headroom_bytes}",
            self.ts)


def parse_pressure(raw: str | None,
                   now: float | None = None,
                   max_age_s: float = MAX_PRESSURE_AGE_S
                   ) -> NodePressure | None:
    """Decode the annotation; None when absent, malformed, or stale —
    every bad shape degrades to no-signal, never to a wrong penalty."""
    split = stalecodec.split_stamp(raw)
    if split is None:
        return None
    body, ts = split
    frac_raw, _, headroom_raw = body.partition(":")
    try:
        frac = float(frac_raw)
        headroom = int(headroom_raw)
    except (TypeError, ValueError):
        return None
    if not math.isfinite(frac):
        # "nan" parses as float but poisons every comparison downstream:
        # min/max pass NaN through and a NaN score corrupts the whole
        # node ordering — garbage must mean no-signal
        return None
    if not stalecodec.is_fresh(ts, now, max_age_s):
        return None
    return NodePressure(min(max(frac, 0.0), 1.0), max(headroom, 0), ts)


def pressure_penalty(pressure: "NodePressure | None",
                     now: float | None = None) -> float:
    """Score points to subtract for one node's pressure. Staleness is
    re-judged HERE, not only at parse time: the snapshot path caches the
    parsed pressure on the NodeEntry and a dead publisher emits no
    further node events, so without a use-time check its last panic
    would pin forever instead of decaying to no-signal."""
    if pressure is None:
        return 0.0
    if not stalecodec.is_fresh(pressure.ts, now, MAX_PRESSURE_AGE_S):
        return 0.0
    return PRESSURE_SCORE_WEIGHT * pressure.throttle_frac


class PressurePublisher:
    """Daemon-side loop: scan the rings, patch the node annotation.

    Runs in the device-plugin daemon (the binary that already owns node
    annotation publication) behind the StepTelemetry gate. Failures are
    tolerated per tick — pressure is advisory, and the annotation's own
    timestamp ages it out if publication stops."""

    def __init__(self, client, node_name: str, aggregator,
                 node_hbm_total: int, policy=None,
                 interval_s: float = 15.0):
        from vtpu_manager.resilience.policy import RetryPolicy
        self.client = client
        self.node_name = node_name
        self.aggregator = aggregator
        self.node_hbm_total = node_hbm_total
        self.policy = policy or RetryPolicy(max_attempts=3, deadline_s=10.0)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def publish_once(self) -> NodePressure:
        self.aggregator.scan()
        frac, headroom = self.aggregator.pressure(self.node_hbm_total)
        pressure = NodePressure(frac, headroom, time.time())
        self.policy.run(
            lambda: self.client.patch_node_annotations(
                self.node_name,
                {consts.node_pressure_annotation(): pressure.encode()}),
            op="telemetry.pressure_patch")
        return pressure

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.publish_once()
                except Exception:  # noqa: BLE001 — advisory signal; the
                    # annotation timestamp ages a silent failure out
                    log.warning("node pressure publish failed",
                                exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vttel-pressure")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
