"""Kubelet pod-resources client: the authoritative container<->pod map.

Reference: pkg/client/pod_resources.go:1-202 — dial the kubelet's
pod-resources unix socket per call (the kubelet serves
/v1alpha1.PodResources/List), collect which pod/container owns which
device IDs, and tear the connection down. The metrics lister
(pkg/metrics/lister/container_lister.go:1-266) uses this to attribute
containers instead of trusting its own bookkeeping.

TPU redesign notes: same wire contract (the kubelet side is unchanged on a
TPU node); the generic grpc call avoids codegen, matching the rest of the
kubelet-facing surface (deviceplugin/base.py). Authority order mirrors the
reference: live socket first, kubelet device-manager checkpoint as the
(possibly stale) fallback; neither available disables cross-checking
rather than failing the scrape.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

from vtpu_manager.deviceplugin.api import podresources_pb2 as pb
from vtpu_manager.deviceplugin import checkpoint as ckpt
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

POD_RESOURCES_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"
_MAX_MSG = 16 * 1024 * 1024          # reference defaultPodResourcesMaxSize
_CALL_TIMEOUT_S = 2.0                # reference defaultCallTimeout


@dataclass(frozen=True)
class ContainerEntry:
    pod_name: str
    namespace: str
    container: str
    resource: str
    device_ids: tuple[str, ...]


@dataclass(frozen=True)
class KubeletView:
    """What the kubelet says about vtpu tenancy, in whichever key space
    the available source provides.

    - source "podresources": `containers` holds container NAMES with vtpu
      devices (the v1alpha1 API identifies pods by name/namespace, not
      UID, so that is the comparable unit against config-dir names);
    - source "checkpoint": `pairs` holds (pod_uid, container) — the exact
      key our config directories use;
    - source "": neither endpoint reachable; no cross-check possible.
    """
    source: str
    containers: frozenset[str] | None = None
    pairs: frozenset[tuple[str, str]] | None = None

    def corroborates(self, pod_uid: str, container: str) -> bool | None:
        """True/False when this view can judge the attribution; None when
        no source was available (skip, do not alarm)."""
        if self.pairs is not None:
            return (pod_uid, container) in self.pairs
        if self.containers is not None:
            return container in self.containers
        return None


def list_pod_resources(socket_path: str = POD_RESOURCES_SOCKET,
                       timeout_s: float = _CALL_TIMEOUT_S
                       ) -> list[ContainerEntry] | None:
    """One List call against the kubelet socket; None when the socket is
    missing or the call fails (callers fall back to the checkpoint).
    Connection per call, like the reference — the monitor scrapes every
    15-30 s and a held connection would outlive kubelet restarts."""
    if not os.path.exists(socket_path):
        return None
    try:
        import grpc
    except ImportError:                          # pragma: no cover
        return None
    try:
        with grpc.insecure_channel(
                f"unix://{socket_path}",
                options=[("grpc.max_receive_message_length", _MAX_MSG)],
        ) as channel:
            call = channel.unary_unary(
                "/v1alpha1.PodResources/List",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=(
                    pb.ListPodResourcesResponse.FromString),
            )
            resp = call(pb.ListPodResourcesRequest(), timeout=timeout_s)
    except Exception as e:
        log.warning("pod-resources List failed on %s: %s", socket_path, e)
        return None
    out = []
    for pod in resp.pod_resources:
        for container in pod.containers:
            for dev in container.devices:
                out.append(ContainerEntry(
                    pod.name, pod.namespace, container.name,
                    dev.resource_name, tuple(dev.device_ids)))
    return out


def kubelet_view(socket_path: str = POD_RESOURCES_SOCKET,
                 checkpoint_path: str = ckpt.KUBELET_CHECKPOINT
                 ) -> KubeletView:
    """The kubelet's view of vtpu-holding containers, from the strongest
    available source."""
    domain = consts.resource_domain()
    entries = list_pod_resources(socket_path)
    if entries is not None:
        return KubeletView(
            source="podresources",
            containers=frozenset(e.container for e in entries
                                 if e.resource.startswith(domain)))
    cps = ckpt.read_checkpoint(checkpoint_path)
    if cps:
        return KubeletView(
            source="checkpoint",
            pairs=frozenset((c.pod_uid, c.container) for c in cps
                            if c.resource.startswith(domain)))
    return KubeletView(source="")
