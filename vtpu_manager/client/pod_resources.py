"""Kubelet pod-resources client: the authoritative container<->pod map.

Reference: pkg/client/pod_resources.go:1-202 — dial the kubelet's
pod-resources unix socket per call (the kubelet serves
/v1alpha1.PodResources/List), collect which pod/container owns which
device IDs, and tear the connection down. The metrics lister
(pkg/metrics/lister/container_lister.go:1-266) uses this to attribute
containers instead of trusting its own bookkeeping.

TPU redesign notes: same wire contract (the kubelet side is unchanged on a
TPU node); the generic grpc call avoids codegen, matching the rest of the
kubelet-facing surface (deviceplugin/base.py). Authority order mirrors the
reference: live socket first, kubelet device-manager checkpoint as the
(possibly stale) fallback; neither available disables cross-checking
rather than failing the scrape.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

from vtpu_manager.deviceplugin.api import podresources_pb2 as pb
from vtpu_manager.deviceplugin import checkpoint as ckpt
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

POD_RESOURCES_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"
_MAX_MSG = 16 * 1024 * 1024          # reference defaultPodResourcesMaxSize
_CALL_TIMEOUT_S = 2.0                # reference defaultCallTimeout


@dataclass(frozen=True)
class ContainerEntry:
    pod_name: str
    namespace: str
    container: str
    resource: str
    device_ids: tuple[str, ...]


@dataclass(frozen=True)
class KubeletView:
    """What the kubelet says about vtpu tenancy, in whichever key spaces
    the available sources provide.

    - `containers`: container NAMES with vtpu devices from the live
      pod-resources socket (the v1alpha1 API identifies pods by
      name/namespace, not UID — a name-only key space);
    - `pairs`: (pod_uid, container) from the kubelet device-manager
      checkpoint — the exact key our config directories use, but
      possibly stale;
    - source "podresources+checkpoint" / "podresources" / "checkpoint" /
      "" names the strongest combination reachable this scrape.

    Both sources are consulted when both answer (ADVICE r3 medium): name
    matching alone would corroborate an orphaned/spoofed config dir
    (bogus-uid_main) whenever ANY vtpu pod runs a container with that
    common name — exactly the case the mismatch gauge claims to catch —
    so liveness comes from the socket and identity from the UID-keyed
    checkpoint, and a judgment uses the strongest key space available.
    """
    source: str
    containers: frozenset[str] | None = None
    pairs: frozenset[tuple[str, str]] | None = None

    def corroborates(self, pod_uid: str, container: str) -> bool | None:
        """True/False when this view can judge the attribution; None when
        no source was available (skip, do not alarm). With both sources
        up, corroboration requires the (pod_uid, container) pair in the
        checkpoint AND the container name live on the socket."""
        if self.pairs is not None and self.containers is not None:
            return ((pod_uid, container) in self.pairs
                    and container in self.containers)
        if self.pairs is not None:
            return (pod_uid, container) in self.pairs
        if self.containers is not None:
            return container in self.containers
        return None


def list_pod_resources(socket_path: str = POD_RESOURCES_SOCKET,
                       timeout_s: float = _CALL_TIMEOUT_S
                       ) -> list[ContainerEntry] | None:
    """One List call against the kubelet socket; None when the socket is
    missing or the call fails (callers fall back to the checkpoint).
    Connection per call, like the reference — the monitor scrapes every
    15-30 s and a held connection would outlive kubelet restarts."""
    if not os.path.exists(socket_path):
        return None
    try:
        import grpc
    except ImportError:                          # pragma: no cover
        return None
    try:
        with grpc.insecure_channel(
                f"unix://{socket_path}",
                options=[("grpc.max_receive_message_length", _MAX_MSG)],
        ) as channel:
            call = channel.unary_unary(
                "/v1alpha1.PodResources/List",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=(
                    pb.ListPodResourcesResponse.FromString),
            )
            resp = call(pb.ListPodResourcesRequest(), timeout=timeout_s)
    except Exception as e:
        log.warning("pod-resources List failed on %s: %s", socket_path, e)
        return None
    out = []
    for pod in resp.pod_resources:
        for container in pod.containers:
            for dev in container.devices:
                out.append(ContainerEntry(
                    pod.name, pod.namespace, container.name,
                    dev.resource_name, tuple(dev.device_ids)))
    return out


def kubelet_view(socket_path: str = POD_RESOURCES_SOCKET,
                 checkpoint_path: str = ckpt.KUBELET_CHECKPOINT
                 ) -> KubeletView:
    """The kubelet's view of vtpu-holding containers, combining every
    source that answers (see KubeletView for why both)."""
    domain = consts.resource_domain()
    entries = list_pod_resources(socket_path)
    containers = (frozenset(e.container for e in entries
                            if e.resource.startswith(domain))
                  if entries is not None else None)
    cps = ckpt.read_checkpoint(checkpoint_path)
    pairs = (frozenset((c.pod_uid, c.container) for c in cps
                       if c.resource.startswith(domain))
             if cps else None)
    source = "+".join(
        name for name, got in (("podresources", containers is not None),
                               ("checkpoint", pairs is not None)) if got)
    return KubeletView(source=source, containers=containers, pairs=pairs)
