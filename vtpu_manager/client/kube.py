"""Minimal Kubernetes API client protocol + in-cluster REST implementation.

Reference: pkg/client (G13) — patch helpers, listers, eviction, binding. The
Go reference uses client-go; this image has no kubernetes Python package, so
we implement the few verbs the control plane needs over the REST API with
stdlib urllib (control-plane QPS is low). The scheduler snapshot
(scheduler/snapshot.py) additionally needs list+watch semantics — the
client-go informer contract: a versioned LIST to seed, then a WATCH from
that resourceVersion streaming ADDED/MODIFIED/DELETED/BOOKMARK events, with
410 Gone meaning "your version was compacted away, relist".

All objects are plain dicts in k8s JSON shape. Every component takes the
KubeClient protocol so tests swap in FakeKubeClient (the fake-clientset
pattern, SURVEY.md §4).
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import urllib.error
import urllib.request
from typing import Iterable, Protocol

from vtpu_manager.resilience import failpoints

log = logging.getLogger(__name__)


class KubeError(RuntimeError):
    def __init__(self, status: int, message: str,
                 retry_after: float | None = None):
        super().__init__(f"kube api {status}: {message}")
        self.status = status
        # apiserver pacing hint (Retry-After header on 429/5xx): the
        # resilience RetryPolicy floors its backoff at this
        self.retry_after = retry_after


def _retry_after_s(headers) -> float | None:
    """Seconds from a Retry-After header; None when absent/unparseable
    (HTTP-date form is ignored — the apiserver sends delta-seconds)."""
    raw = headers.get("Retry-After") if headers is not None else None
    if not raw:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


class KubeClient(Protocol):
    def list_nodes(self) -> list[dict]: ...
    def get_node(self, name: str) -> dict: ...
    def patch_node_annotations(self, name: str, annotations: dict) -> dict: ...
    def list_pods(self, namespace: str | None = None,
                  node_name: str | None = None,
                  field_selector: str | None = None) -> list[dict]: ...
    def get_pod(self, namespace: str, name: str) -> dict: ...
    def patch_pod_annotations(self, namespace: str, name: str,
                              annotations: dict) -> dict: ...
    def bind_pod(self, namespace: str, name: str, node: str) -> None: ...
    def delete_pod(self, namespace: str, name: str,
                   grace_seconds: int | None = None) -> None: ...
    def evict_pod(self, namespace: str, name: str) -> None: ...
    def create_event(self, namespace: str, event: dict) -> None: ...
    def list_pdbs(self, namespace: str | None = None) -> list[dict]: ...
    # -- list+watch (scheduler snapshot; SURVEY informer analogue) ----------
    def list_pods_with_version(self) -> tuple[list[dict], str]: ...
    def list_nodes_with_version(self) -> tuple[list[dict], str]: ...
    def watch_pods(self, resource_version: str,
                   timeout_s: float = 30.0) -> Iterable[dict]: ...
    def watch_nodes(self, resource_version: str,
                    timeout_s: float = 30.0) -> Iterable[dict]: ...
    # -- coordination leases (vtha shard leader election) -------------------
    # CAS contract: update_lease with a stale resource_version raises
    # KubeError(409) — the apiserver's optimistic concurrency is the one
    # serialization point shard leadership rests on (scheduler/lease.py).
    def get_lease(self, namespace: str, name: str) -> dict: ...
    def create_lease(self, namespace: str, name: str,
                     annotations: dict) -> dict: ...
    def update_lease(self, namespace: str, name: str, annotations: dict,
                     resource_version: str) -> dict: ...


SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class InClusterClient:
    """REST client using the pod service account (in-cluster only)."""

    def __init__(self, api_server: str | None = None,
                 token_path: str = f"{SERVICE_ACCOUNT_DIR}/token",
                 ca_path: str = f"{SERVICE_ACCOUNT_DIR}/ca.crt"):
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base = api_server or f"https://{host}:{port}"
        with open(token_path) as f:
            self._token = f.read().strip()
        self._ctx = ssl.create_default_context(cafile=ca_path)

    # -- plumbing -----------------------------------------------------------

    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str = "application/json") -> dict:
        failpoints.fire("kube.request", method=method, path=path)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base + path, data=data,
                                     method=method)
        req.add_header("Authorization", f"Bearer {self._token}")
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req, context=self._ctx,
                                        timeout=30) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            raise KubeError(e.code, e.read().decode(errors="replace"),
                            retry_after=_retry_after_s(e.headers)) from e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            # transport failure (refused/reset/DNS/timeout): status 0 is
            # the retryable-by-definition class — the request may never
            # have reached the apiserver
            raise KubeError(0, f"transport: {e}") from e

    @staticmethod
    def _merge_patch_annotations(annotations: dict) -> dict:
        return {"metadata": {"annotations": annotations}}

    # -- verbs --------------------------------------------------------------

    def list_nodes(self) -> list[dict]:
        return self._request("GET", "/api/v1/nodes").get("items", [])

    def get_node(self, name: str) -> dict:
        return self._request("GET", f"/api/v1/nodes/{name}")

    def patch_node_annotations(self, name: str, annotations: dict) -> dict:
        return self._request(
            "PATCH", f"/api/v1/nodes/{name}",
            self._merge_patch_annotations(annotations),
            content_type="application/merge-patch+json")

    def list_pods(self, namespace: str | None = None,
                  node_name: str | None = None,
                  field_selector: str | None = None) -> list[dict]:
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        selectors = []
        if node_name:
            selectors.append(f"spec.nodeName={node_name}")
        if field_selector:
            selectors.append(field_selector)
        if selectors:
            path += "?fieldSelector=" + ",".join(selectors)
        return self._request("GET", path).get("items", [])

    # -- list+watch (scheduler snapshot) ------------------------------------

    def list_pods_with_version(self) -> tuple[list[dict], str]:
        doc = self._request("GET", "/api/v1/pods")
        return (doc.get("items", []),
                (doc.get("metadata") or {}).get("resourceVersion", ""))

    def list_nodes_with_version(self) -> tuple[list[dict], str]:
        doc = self._request("GET", "/api/v1/nodes")
        return (doc.get("items", []),
                (doc.get("metadata") or {}).get("resourceVersion", ""))

    def watch_pods(self, resource_version: str,
                   timeout_s: float = 30.0) -> Iterable[dict]:
        return self._watch("/api/v1/pods", resource_version, timeout_s)

    def watch_nodes(self, resource_version: str,
                    timeout_s: float = 30.0) -> Iterable[dict]:
        return self._watch("/api/v1/nodes", resource_version, timeout_s)

    def _watch(self, path: str, resource_version: str,
               timeout_s: float) -> Iterable[dict]:
        """Streaming watch: yields decoded watch events (``{"type": ...,
        "object": ...}``) as the apiserver sends them, returning when the
        server closes the connection (timeoutSeconds elapsed). Raises
        KubeError(410) when the resourceVersion was compacted away —
        either as an HTTP status or as an in-stream ERROR event, both of
        which the apiserver uses — so the snapshot relists."""
        failpoints.fire("kube.watch", path=path)
        query = (f"?watch=true&allowWatchBookmarks=true"
                 f"&resourceVersion={resource_version}"
                 f"&timeoutSeconds={max(1, int(timeout_s))}")
        req = urllib.request.Request(self.base + path + query, method="GET")
        req.add_header("Authorization", f"Bearer {self._token}")
        req.add_header("Accept", "application/json")
        try:
            resp = urllib.request.urlopen(req, context=self._ctx,
                                          timeout=timeout_s + 30)
        except urllib.error.HTTPError as e:
            raise KubeError(e.code, e.read().decode(errors="replace"),
                            retry_after=_retry_after_s(e.headers)) from e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            raise KubeError(0, f"transport: {e}") from e
        with resp:
            for line in resp:
                event = parse_watch_line(line)
                if event is None:
                    continue
                raise_on_watch_error(event)
                yield event

    def get_pod(self, namespace: str, name: str) -> dict:
        return self._request("GET",
                             f"/api/v1/namespaces/{namespace}/pods/{name}")

    def patch_pod_annotations(self, namespace: str, name: str,
                              annotations: dict) -> dict:
        return self._request(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}",
            self._merge_patch_annotations(annotations),
            content_type="application/merge-patch+json")

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        self._request("POST",
                      f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
                      {"apiVersion": "v1", "kind": "Binding",
                       "metadata": {"name": name, "namespace": namespace},
                       "target": {"apiVersion": "v1", "kind": "Node",
                                  "name": node}})

    def delete_pod(self, namespace: str, name: str,
                   grace_seconds: int | None = None) -> None:
        path = f"/api/v1/namespaces/{namespace}/pods/{name}"
        if grace_seconds is not None:
            path += f"?gracePeriodSeconds={grace_seconds}"
        self._request("DELETE", path)

    def evict_pod(self, namespace: str, name: str) -> None:
        self._request("POST",
                      f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
                      {"apiVersion": "policy/v1", "kind": "Eviction",
                       "metadata": {"name": name, "namespace": namespace}})

    def create_event(self, namespace: str, event: dict) -> None:
        self._request("POST", f"/api/v1/namespaces/{namespace}/events", event)

    def list_pdbs(self, namespace: str | None = None) -> list[dict]:
        path = (f"/apis/policy/v1/namespaces/{namespace}"
                "/poddisruptionbudgets" if namespace
                else "/apis/policy/v1/poddisruptionbudgets")
        return self._request("GET", path).get("items", [])

    # -- coordination leases (vtha) -----------------------------------------

    _LEASE_BASE = "/apis/coordination.k8s.io/v1/namespaces"

    def get_lease(self, namespace: str, name: str) -> dict:
        return self._request(
            "GET", f"{self._LEASE_BASE}/{namespace}/leases/{name}")

    def create_lease(self, namespace: str, name: str,
                     annotations: dict) -> dict:
        return self._request(
            "POST", f"{self._LEASE_BASE}/{namespace}/leases",
            {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
             "metadata": {"name": name, "namespace": namespace,
                          "annotations": annotations},
             "spec": {}})

    def update_lease(self, namespace: str, name: str, annotations: dict,
                     resource_version: str) -> dict:
        # PUT with the expected resourceVersion: the apiserver rejects a
        # stale writer with 409 Conflict — this IS the CAS
        return self._request(
            "PUT", f"{self._LEASE_BASE}/{namespace}/leases/{name}",
            {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
             "metadata": {"name": name, "namespace": namespace,
                          "annotations": annotations,
                          "resourceVersion": resource_version},
             "spec": {}})

    # -- DRA objects --------------------------------------------------------

    def get_resourceclaim(self, namespace: str, name: str) -> dict:
        return self._request(
            "GET", f"/apis/resource.k8s.io/v1beta1/namespaces/{namespace}"
                   f"/resourceclaims/{name}")

    def create_resourceclaim_template(self, template: dict) -> dict:
        ns = template["metadata"].get("namespace", "default")
        return self._request(
            "POST", f"/apis/resource.k8s.io/v1beta1/namespaces/{ns}"
                    "/resourceclaimtemplates", template)

    def apply_resourceslice(self, slice_doc: dict) -> dict:
        name = slice_doc["metadata"]["name"]
        try:
            return self._request(
                "PUT", f"/apis/resource.k8s.io/v1beta1/resourceslices/{name}",
                slice_doc)
        except KubeError as e:
            if e.status != 404:
                raise
            return self._request(
                "POST", "/apis/resource.k8s.io/v1beta1/resourceslices",
                slice_doc)


# -- watch frame helpers (shared by InClusterClient and tests) --------------

def parse_watch_line(line: bytes) -> dict | None:
    """One newline-delimited watch frame -> event dict, or None for blank/
    undecodable frames (a torn final line when the server hangs up is
    normal; the next watch re-syncs from the last applied version)."""
    line = line.strip()
    if not line:
        return None
    try:
        return json.loads(line)
    except json.JSONDecodeError:
        log.debug("undecodable watch frame (%d bytes), skipping", len(line))
        return None


def raise_on_watch_error(event: dict) -> None:
    """In-stream ERROR events carry a Status object; 410 Gone must surface
    as KubeError(410) so consumers relist exactly like the HTTP case."""
    if event.get("type") != "ERROR":
        return
    status = event.get("object") or {}
    code = int(status.get("code") or 500)
    raise KubeError(code, str(status.get("message", "watch error")))
