"""Fake in-memory KubeClient (the fake-clientset test pattern).

Reference test strategy: scheduler/webhook/controller tests run against
k8s.io/client-go/kubernetes/fake with real informers (SURVEY.md §4); this is
the Python equivalent. Thread-safe; records bindings/evictions/events for
assertions.

Watch semantics (the informer analogue the scheduler snapshot consumes):
every API mutation appends an ADDED/MODIFIED/DELETED event to a bounded
per-kind queue under a single monotonically increasing resourceVersion.
``watch_pods``/``watch_nodes`` return the events after the given version
plus a trailing BOOKMARK (so consumers advance their version even when
idle), and raise ``KubeError(410)`` when the requested version predates
the retained window — ``compact_watch_events()`` forces that in tests,
and a retention cap forces it for real when a consumer falls far behind,
exactly the apiserver contract that makes relist-on-410 load-bearing.
"""

from __future__ import annotations

import copy
import threading
from collections import deque

from vtpu_manager.client.kube import KubeError
from vtpu_manager.resilience import failpoints

# Events retained per kind before the oldest are compacted away (a watcher
# further behind than this gets 410 Gone and must relist). Big enough that
# only a genuinely wedged consumer hits it; small enough to bound memory in
# the 100k-pod sustained harness.
WATCH_RETENTION = 100_000


class FakeKubeClient:
    def __init__(self, upsert_on_patch: bool = False,
                 copy_on_read: bool = True,
                 watch_retention: int = WATCH_RETENTION):
        # upsert_on_patch: smoke-server convenience — a patched-but-unknown
        # pod is created instead of 404ing (tests keep strict semantics).
        # copy_on_read=False models informer-cache semantics (client-go
        # informers hand out SHARED objects callers must not mutate) — the
        # right fidelity for scale harnesses where per-read deepcopy of
        # 100k pods would swamp the cost being measured. Tests keep the
        # safe default. Watch events follow the same rule: shared refs in
        # informer-fidelity mode (a queued event can show a later patch —
        # benign for last-write-wins consumers), snapshots otherwise.
        self.upsert_on_patch = upsert_on_patch
        self.copy_on_read = copy_on_read
        self._lock = threading.RLock()
        self.nodes: dict[str, dict] = {}
        self.pods: dict[tuple[str, str], dict] = {}
        # fieldSelector index, maintained on API mutations exactly like the
        # apiserver's spec.nodeName index: list_pods with
        # field_selector="spec.nodeName!=" walks only scheduled pods, so a
        # 100k-pending-pod cluster does not tax every scheduled-pod list.
        self._scheduled: dict[tuple[str, str], dict] = {}
        self.bindings: list[tuple[str, str, str]] = []   # (ns, pod, node)
        self.evictions: list[tuple[str, str]] = []
        self.deletions: list[tuple[str, str]] = []
        self.events: list[dict] = []
        self.resourceclaims: dict[tuple[str, str], dict] = {}
        self.resourceslices: dict[str, dict] = {}
        self.pdbs: list[dict] = []
        # vtha coordination leases: (ns, name) -> lease dict. Every write
        # is appended to lease_history so tests can assert CAS/token
        # monotonicity over the whole run, not just the final state.
        self.leases: dict[tuple[str, str], dict] = {}
        self.lease_history: list[tuple[str, str, dict]] = []
        # -- watch machinery ------------------------------------------------
        self._rv = 0                          # one version for both kinds
        self._watch_retention = watch_retention
        self._watch_events: dict[str, deque] = {"pods": deque(),
                                                "nodes": deque()}
        self._compacted_rv: dict[str, int] = {"pods": 0, "nodes": 0}

    # -- watch plumbing -----------------------------------------------------

    def _record_event(self, kind: str, type_: str, obj: dict) -> None:
        """Append one watch event (caller holds self._lock)."""
        self._rv += 1
        snap = copy.deepcopy(obj) if self.copy_on_read else obj
        queue = self._watch_events[kind]
        queue.append((self._rv, type_, snap))
        while len(queue) > self._watch_retention:
            dropped_rv, _, _ = queue.popleft()
            self._compacted_rv[kind] = dropped_rv

    def compact_watch_events(self, kind: str | None = None) -> None:
        """Test hook: forget all retained events, so any watcher not fully
        caught up gets 410 Gone (the apiserver etcd-compaction case)."""
        with self._lock:
            for k in ([kind] if kind else ["pods", "nodes"]):
                self._watch_events[k].clear()
                self._compacted_rv[k] = self._rv

    def _watch(self, kind: str, resource_version: str,
               timeout_s: float) -> list[dict]:
        failpoints.fire("kube.watch", op=kind)
        try:
            after = int(resource_version or 0)
        except ValueError as e:
            raise KubeError(400, f"bad resourceVersion "
                                 f"{resource_version!r}") from e
        with self._lock:
            if after < self._compacted_rv[kind]:
                raise KubeError(
                    410, f"too old resource version: {after} "
                         f"({self._compacted_rv[kind]})")
            out = [{"type": t, "object": obj, "resourceVersion": str(rv)}
                   for rv, t, obj in self._watch_events[kind] if rv > after]
            # trailing bookmark: consumers advance even on idle watches
            # (and the bookmark-handling path is exercised on every pump)
            out.append({"type": "BOOKMARK",
                        "object": {"metadata":
                                   {"resourceVersion": str(self._rv)}},
                        "resourceVersion": str(self._rv)})
            return out

    def watch_pods(self, resource_version: str,
                   timeout_s: float = 30.0) -> list[dict]:
        return self._watch("pods", resource_version, timeout_s)

    def watch_nodes(self, resource_version: str,
                    timeout_s: float = 30.0) -> list[dict]:
        return self._watch("nodes", resource_version, timeout_s)

    def list_pods_with_version(self) -> tuple[list[dict], str]:
        failpoints.fire("kube.request", op="list_pods_with_version")
        with self._lock:
            items = [copy.deepcopy(p) if self.copy_on_read else p
                     for p in self.pods.values()]
            return items, str(self._rv)

    def list_nodes_with_version(self) -> tuple[list[dict], str]:
        failpoints.fire("kube.request", op="list_nodes_with_version")
        with self._lock:
            items = [copy.deepcopy(n) if self.copy_on_read else n
                     for n in self.nodes.values()]
            return items, str(self._rv)

    # -- fixture helpers ----------------------------------------------------

    def add_node(self, node: dict) -> None:
        with self._lock:
            name = node["metadata"]["name"]
            type_ = "MODIFIED" if name in self.nodes else "ADDED"
            stored = copy.deepcopy(node)
            self.nodes[name] = stored
            self._record_event("nodes", type_, stored)

    def add_pdb(self, pdb: dict) -> None:
        with self._lock:
            self.pdbs.append(copy.deepcopy(pdb))

    def add_pod(self, pod: dict) -> None:
        meta = pod["metadata"]
        key = (meta.get("namespace", "default"), meta["name"])
        with self._lock:
            type_ = "MODIFIED" if key in self.pods else "ADDED"
            stored = copy.deepcopy(pod)
            self.pods[key] = stored
            if (stored.get("spec") or {}).get("nodeName"):
                self._scheduled[key] = stored
            else:
                self._scheduled.pop(key, None)
            self._record_event("pods", type_, stored)

    # -- KubeClient protocol ------------------------------------------------

    def list_nodes(self) -> list[dict]:
        failpoints.fire("kube.request", op="list_nodes")
        with self._lock:
            return [copy.deepcopy(n) for n in self.nodes.values()]

    def get_node(self, name: str) -> dict:
        failpoints.fire("kube.request", op="get_node")
        with self._lock:
            if name not in self.nodes:
                raise KubeError(404, f"node {name} not found")
            return copy.deepcopy(self.nodes[name])

    def patch_node_annotations(self, name: str, annotations: dict) -> dict:
        failpoints.fire("kube.request", op="patch_node_annotations")
        with self._lock:
            node = self.nodes.get(name)
            if node is None:
                raise KubeError(404, f"node {name} not found")
            anns = node.setdefault("metadata", {}).setdefault(
                "annotations", {})
            for k, v in annotations.items():
                if v is None:
                    anns.pop(k, None)
                else:
                    anns[k] = v
            self._record_event("nodes", "MODIFIED", node)
            return copy.deepcopy(node)

    def list_pods(self, namespace=None, node_name=None,
                  field_selector=None) -> list[dict]:
        # Recognize exactly the selectors the real client sends; anything
        # else must blow up HERE, not silently return the full list and
        # let a test pass against behavior the apiserver won't have
        # (ADVICE r3).
        if field_selector not in (None, "", "spec.nodeName!="):
            raise NotImplementedError(
                f"FakeKubeClient.list_pods: unsupported field_selector "
                f"{field_selector!r} (known: 'spec.nodeName!=')")
        scheduled_only = field_selector == "spec.nodeName!="
        failpoints.fire("kube.request", op="list_pods")
        with self._lock:
            source = self._scheduled if scheduled_only else self.pods
            out = []
            for (ns, _), pod in source.items():
                if namespace and ns != namespace:
                    continue
                if node_name and \
                        (pod.get("spec") or {}).get("nodeName") != node_name:
                    continue
                out.append(copy.deepcopy(pod) if self.copy_on_read else pod)
            return out

    def get_pod(self, namespace: str, name: str) -> dict:
        failpoints.fire("kube.request", op="get_pod")
        with self._lock:
            pod = self.pods.get((namespace, name))
            if pod is None:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            return copy.deepcopy(pod)

    def patch_pod_annotations(self, namespace: str, name: str,
                              annotations: dict) -> dict:
        failpoints.fire("kube.request", op="patch_pod_annotations")
        with self._lock:
            pod = self.pods.get((namespace, name))
            if pod is None:
                if not self.upsert_on_patch:
                    raise KubeError(404, f"pod {namespace}/{name} not found")
                pod = {"metadata": {"name": name, "namespace": namespace,
                                    "annotations": {}},
                       "spec": {}, "status": {"phase": "Pending"}}
                self.pods[(namespace, name)] = pod
            anns = pod.setdefault("metadata", {}).setdefault("annotations", {})
            for k, v in annotations.items():
                if v is None:
                    anns.pop(k, None)
                else:
                    anns[k] = v
            self._record_event("pods", "MODIFIED", pod)
            return copy.deepcopy(pod)

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        failpoints.fire("kube.request", op="bind_pod")
        with self._lock:
            pod = self.pods.get((namespace, name))
            if pod is None:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            pod.setdefault("spec", {})["nodeName"] = node
            self._scheduled[(namespace, name)] = pod
            self.bindings.append((namespace, name, node))
            self._record_event("pods", "MODIFIED", pod)

    def delete_pod(self, namespace: str, name: str,
                   grace_seconds=None) -> None:
        failpoints.fire("kube.request", op="delete_pod")
        with self._lock:
            if (namespace, name) not in self.pods:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            gone = self.pods.pop((namespace, name))
            self._scheduled.pop((namespace, name), None)
            self.deletions.append((namespace, name))
            self._record_event("pods", "DELETED", gone)

    def evict_pod(self, namespace: str, name: str) -> None:
        failpoints.fire("kube.request", op="evict_pod")
        with self._lock:
            if (namespace, name) not in self.pods:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            gone = self.pods.pop((namespace, name))
            self._scheduled.pop((namespace, name), None)
            self.evictions.append((namespace, name))
            self._record_event("pods", "DELETED", gone)

    def create_event(self, namespace: str, event: dict) -> None:
        failpoints.fire("kube.request", op="create_event")
        with self._lock:
            self.events.append(copy.deepcopy(event))

    def list_pdbs(self, namespace=None) -> list[dict]:
        with self._lock:
            return [copy.deepcopy(p) for p in self.pdbs
                    if not namespace
                    or p["metadata"].get("namespace", "default") == namespace]

    # -- coordination leases (vtha) -----------------------------------------

    def get_lease(self, namespace: str, name: str) -> dict:
        failpoints.fire("kube.request", op="get_lease")
        with self._lock:
            lease = self.leases.get((namespace, name))
            if lease is None:
                raise KubeError(404, f"lease {namespace}/{name} not found")
            return copy.deepcopy(lease)

    def create_lease(self, namespace: str, name: str,
                     annotations: dict) -> dict:
        failpoints.fire("kube.request", op="create_lease")
        with self._lock:
            if (namespace, name) in self.leases:
                raise KubeError(409, f"lease {namespace}/{name} exists")
            self._rv += 1
            lease = {"metadata": {"name": name, "namespace": namespace,
                                  "annotations": dict(annotations),
                                  "resourceVersion": str(self._rv)},
                     "spec": {}}
            self.leases[(namespace, name)] = lease
            self.lease_history.append(("create", name, dict(annotations)))
            return copy.deepcopy(lease)

    def update_lease(self, namespace: str, name: str, annotations: dict,
                     resource_version: str) -> dict:
        failpoints.fire("kube.request", op="update_lease")
        with self._lock:
            lease = self.leases.get((namespace, name))
            if lease is None:
                raise KubeError(404, f"lease {namespace}/{name} not found")
            current = lease["metadata"].get("resourceVersion", "")
            if resource_version != current:
                # the CAS contract: a stale writer (lost a race with
                # another scheduler) is rejected exactly like the
                # apiserver's optimistic-concurrency 409
                raise KubeError(
                    409, f"lease {namespace}/{name} conflict: have "
                         f"{current}, got {resource_version}")
            self._rv += 1
            lease["metadata"]["annotations"] = dict(annotations)
            lease["metadata"]["resourceVersion"] = str(self._rv)
            self.lease_history.append(("update", name, dict(annotations)))
            return copy.deepcopy(lease)

    # -- DRA objects --------------------------------------------------------

    def add_resourceclaim(self, claim: dict) -> None:
        meta = claim["metadata"]
        with self._lock:
            self.resourceclaims[(meta.get("namespace", "default"),
                                 meta["name"])] = copy.deepcopy(claim)

    def get_resourceclaim(self, namespace: str, name: str) -> dict:
        with self._lock:
            claim = self.resourceclaims.get((namespace, name))
            if claim is None:
                raise KubeError(404,
                                f"resourceclaim {namespace}/{name} not found")
            return copy.deepcopy(claim)

    def create_resourceclaim_template(self, template: dict) -> dict:
        meta = template["metadata"]
        key = (meta.get("namespace", "default"), meta["name"])
        with self._lock:
            if not hasattr(self, "resourceclaim_templates"):
                self.resourceclaim_templates = {}
            if key in self.resourceclaim_templates:
                from vtpu_manager.client.kube import KubeError
                raise KubeError(409, f"template {key} exists")
            self.resourceclaim_templates[key] = copy.deepcopy(template)
            return copy.deepcopy(template)

    def apply_resourceslice(self, slice_doc: dict) -> dict:
        with self._lock:
            self.resourceslices[slice_doc["metadata"]["name"]] = \
                copy.deepcopy(slice_doc)
            return copy.deepcopy(slice_doc)
