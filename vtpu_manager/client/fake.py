"""Fake in-memory KubeClient (the fake-clientset test pattern).

Reference test strategy: scheduler/webhook/controller tests run against
k8s.io/client-go/kubernetes/fake with real informers (SURVEY.md §4); this is
the Python equivalent. Thread-safe; records bindings/evictions/events for
assertions.
"""

from __future__ import annotations

import copy
import threading

from vtpu_manager.client.kube import KubeError


class FakeKubeClient:
    def __init__(self, upsert_on_patch: bool = False,
                 copy_on_read: bool = True):
        # upsert_on_patch: smoke-server convenience — a patched-but-unknown
        # pod is created instead of 404ing (tests keep strict semantics).
        # copy_on_read=False models informer-cache semantics (client-go
        # informers hand out SHARED objects callers must not mutate) — the
        # right fidelity for scale harnesses where per-read deepcopy of
        # 100k pods would swamp the cost being measured. Tests keep the
        # safe default.
        self.upsert_on_patch = upsert_on_patch
        self.copy_on_read = copy_on_read
        self._lock = threading.RLock()
        self.nodes: dict[str, dict] = {}
        self.pods: dict[tuple[str, str], dict] = {}
        # fieldSelector index, maintained on API mutations exactly like the
        # apiserver's spec.nodeName index: list_pods with
        # field_selector="spec.nodeName!=" walks only scheduled pods, so a
        # 100k-pending-pod cluster does not tax every scheduled-pod list.
        self._scheduled: dict[tuple[str, str], dict] = {}
        self.bindings: list[tuple[str, str, str]] = []   # (ns, pod, node)
        self.evictions: list[tuple[str, str]] = []
        self.deletions: list[tuple[str, str]] = []
        self.events: list[dict] = []
        self.resourceclaims: dict[tuple[str, str], dict] = {}
        self.resourceslices: dict[str, dict] = {}
        self.pdbs: list[dict] = []

    # -- fixture helpers ----------------------------------------------------

    def add_node(self, node: dict) -> None:
        with self._lock:
            self.nodes[node["metadata"]["name"]] = copy.deepcopy(node)

    def add_pdb(self, pdb: dict) -> None:
        with self._lock:
            self.pdbs.append(copy.deepcopy(pdb))

    def add_pod(self, pod: dict) -> None:
        meta = pod["metadata"]
        key = (meta.get("namespace", "default"), meta["name"])
        with self._lock:
            stored = copy.deepcopy(pod)
            self.pods[key] = stored
            if (stored.get("spec") or {}).get("nodeName"):
                self._scheduled[key] = stored
            else:
                self._scheduled.pop(key, None)

    # -- KubeClient protocol ------------------------------------------------

    def list_nodes(self) -> list[dict]:
        with self._lock:
            return [copy.deepcopy(n) for n in self.nodes.values()]

    def get_node(self, name: str) -> dict:
        with self._lock:
            if name not in self.nodes:
                raise KubeError(404, f"node {name} not found")
            return copy.deepcopy(self.nodes[name])

    def patch_node_annotations(self, name: str, annotations: dict) -> dict:
        with self._lock:
            node = self.nodes.get(name)
            if node is None:
                raise KubeError(404, f"node {name} not found")
            anns = node.setdefault("metadata", {}).setdefault(
                "annotations", {})
            for k, v in annotations.items():
                if v is None:
                    anns.pop(k, None)
                else:
                    anns[k] = v
            return copy.deepcopy(node)

    def list_pods(self, namespace=None, node_name=None,
                  field_selector=None) -> list[dict]:
        # Recognize exactly the selectors the real client sends; anything
        # else must blow up HERE, not silently return the full list and
        # let a test pass against behavior the apiserver won't have
        # (ADVICE r3).
        if field_selector not in (None, "", "spec.nodeName!="):
            raise NotImplementedError(
                f"FakeKubeClient.list_pods: unsupported field_selector "
                f"{field_selector!r} (known: 'spec.nodeName!=')")
        scheduled_only = field_selector == "spec.nodeName!="
        with self._lock:
            source = self._scheduled if scheduled_only else self.pods
            out = []
            for (ns, _), pod in source.items():
                if namespace and ns != namespace:
                    continue
                if node_name and \
                        (pod.get("spec") or {}).get("nodeName") != node_name:
                    continue
                out.append(copy.deepcopy(pod) if self.copy_on_read else pod)
            return out

    def get_pod(self, namespace: str, name: str) -> dict:
        with self._lock:
            pod = self.pods.get((namespace, name))
            if pod is None:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            return copy.deepcopy(pod)

    def patch_pod_annotations(self, namespace: str, name: str,
                              annotations: dict) -> dict:
        with self._lock:
            pod = self.pods.get((namespace, name))
            if pod is None:
                if not self.upsert_on_patch:
                    raise KubeError(404, f"pod {namespace}/{name} not found")
                pod = {"metadata": {"name": name, "namespace": namespace,
                                    "annotations": {}},
                       "spec": {}, "status": {"phase": "Pending"}}
                self.pods[(namespace, name)] = pod
            anns = pod.setdefault("metadata", {}).setdefault("annotations", {})
            for k, v in annotations.items():
                if v is None:
                    anns.pop(k, None)
                else:
                    anns[k] = v
            return copy.deepcopy(pod)

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        with self._lock:
            pod = self.pods.get((namespace, name))
            if pod is None:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            pod.setdefault("spec", {})["nodeName"] = node
            self._scheduled[(namespace, name)] = pod
            self.bindings.append((namespace, name, node))

    def delete_pod(self, namespace: str, name: str,
                   grace_seconds=None) -> None:
        with self._lock:
            if (namespace, name) not in self.pods:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            del self.pods[(namespace, name)]
            self._scheduled.pop((namespace, name), None)
            self.deletions.append((namespace, name))

    def evict_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            if (namespace, name) not in self.pods:
                raise KubeError(404, f"pod {namespace}/{name} not found")
            del self.pods[(namespace, name)]
            self._scheduled.pop((namespace, name), None)
            self.evictions.append((namespace, name))

    def create_event(self, namespace: str, event: dict) -> None:
        with self._lock:
            self.events.append(copy.deepcopy(event))

    def list_pdbs(self, namespace=None) -> list[dict]:
        with self._lock:
            return [copy.deepcopy(p) for p in self.pdbs
                    if not namespace
                    or p["metadata"].get("namespace", "default") == namespace]

    # -- DRA objects --------------------------------------------------------

    def add_resourceclaim(self, claim: dict) -> None:
        meta = claim["metadata"]
        with self._lock:
            self.resourceclaims[(meta.get("namespace", "default"),
                                 meta["name"])] = copy.deepcopy(claim)

    def get_resourceclaim(self, namespace: str, name: str) -> dict:
        with self._lock:
            claim = self.resourceclaims.get((namespace, name))
            if claim is None:
                raise KubeError(404,
                                f"resourceclaim {namespace}/{name} not found")
            return copy.deepcopy(claim)

    def create_resourceclaim_template(self, template: dict) -> dict:
        meta = template["metadata"]
        key = (meta.get("namespace", "default"), meta["name"])
        with self._lock:
            if not hasattr(self, "resourceclaim_templates"):
                self.resourceclaim_templates = {}
            if key in self.resourceclaim_templates:
                from vtpu_manager.client.kube import KubeError
                raise KubeError(409, f"template {key} exists")
            self.resourceclaim_templates[key] = copy.deepcopy(template)
            return copy.deepcopy(template)

    def apply_resourceslice(self, slice_doc: dict) -> dict:
        with self._lock:
            self.resourceslices[slice_doc["metadata"]["name"]] = \
                copy.deepcopy(slice_doc)
            return copy.deepcopy(slice_doc)
