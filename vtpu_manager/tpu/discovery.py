"""TPU chip discovery backends.

Reference: pkg/device/nvidia (G12) wraps go-nvml/go-nvlib for GPU discovery;
here discovery is TPU-native with three backends, best available first:

1. SysfsBackend — enumerate /dev/accel* + /sys/class/accel (the TPU VFIO
   driver's device nodes) and derive chip count; chip type / HBM size from
   the TPU_ACCELERATOR_TYPE env or the GCE metadata-style env fallbacks the
   TPU VM images set.
2. JaxBackend — ask a local JAX process (authoritative when libtpu is
   importable on the node agent).
3. FakeBackend — synthetic chips for tests and the fake-client smoke path
   (the reference's fake-NVML equivalent).

All backends yield (chips, mesh) in the framework's own model
(vtpu_manager.device.types) with uuids resolved through DeviceIDStore so
synthetic ids survive restarts.
"""

from __future__ import annotations

import glob
import os
import re
from dataclasses import dataclass
from typing import Protocol

from vtpu_manager.device.types import ChipSpec, MeshSpec

# Chip models: (hbm_bytes, cores_per_chip) — public TPU specs.
CHIP_MODELS = {
    "tpu-v4": (32 * 2**30, 2),
    "tpu-v5e": (16 * 2**30, 1),
    "tpu-v5p": (95 * 2**30, 2),
    "tpu-v6e": (32 * 2**30, 1),
}
DEFAULT_CHIP_TYPE = "tpu-v5e"


@dataclass
class DiscoveryResult:
    chips: list[ChipSpec]
    mesh: MeshSpec
    chip_type: str


class DiscoveryBackend(Protocol):
    def discover(self) -> DiscoveryResult | None: ...


def _accel_type_env() -> tuple[str, tuple[int, int]]:
    """Parse TPU_ACCELERATOR_TYPE ('v5litepod-8') and TPU_TOPOLOGY ('2x4')
    into (chip_type, host mesh shape)."""
    accel = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    topo = os.environ.get("TPU_TOPOLOGY", "")
    chip_type = DEFAULT_CHIP_TYPE
    if accel.startswith("v5lite") or accel.startswith("v5e"):
        chip_type = "tpu-v5e"
    elif accel.startswith("v5p"):
        chip_type = "tpu-v5p"
    elif accel.startswith("v4"):
        chip_type = "tpu-v4"
    elif accel.startswith("v6"):
        chip_type = "tpu-v6e"
    shape = (0, 0)
    m = re.match(r"^(\d+)x(\d+)", topo)
    if m:
        shape = (int(m.group(1)), int(m.group(2)))
    return chip_type, shape


def _grid_coords(n: int, shape: tuple[int, int]) -> list[tuple[int, int, int]]:
    sx, sy = shape if shape != (0, 0) else (1, n)
    return [(i % sx, i // sx, 0) for i in range(n)]


class SysfsBackend:
    """Chip count from the accelerator device nodes."""

    def __init__(self, dev_glob: str = "/dev/accel*"):
        self.dev_glob = dev_glob

    def discover(self) -> DiscoveryResult | None:
        nodes = sorted(glob.glob(self.dev_glob))
        if not nodes:
            return None
        n = len(nodes)
        chip_type, shape = _accel_type_env()
        hbm, cores = CHIP_MODELS.get(chip_type, CHIP_MODELS[DEFAULT_CHIP_TYPE])
        if shape == (0, 0):
            shape = (1, n)
        coords = _grid_coords(n, shape)
        chips = [ChipSpec(uuid=f"accel-{i}", index=i, chip_type=chip_type,
                          memory=hbm, core_count=cores, coords=coords[i])
                 for i in range(n)]
        return DiscoveryResult(chips=chips,
                               mesh=MeshSpec((shape[0], shape[1], 1)),
                               chip_type=chip_type)


class JaxBackend:
    """Authoritative when libtpu is loadable in the agent process."""

    def discover(self) -> DiscoveryResult | None:
        try:
            import jax
            devices = [d for d in jax.devices() if d.platform != "cpu"]
        except Exception:
            return None
        if not devices:
            return None
        chip_type, shape = _accel_type_env()
        hbm, cores = CHIP_MODELS.get(chip_type, CHIP_MODELS[DEFAULT_CHIP_TYPE])
        n = len(devices)
        if shape == (0, 0):
            shape = (1, n)
        coords = _grid_coords(n, shape)
        chips = []
        for i, dev in enumerate(devices):
            coord = getattr(dev, "coords", None)
            if coord is not None and len(coord) >= 2:
                c = (int(coord[0]), int(coord[1]),
                     int(coord[2]) if len(coord) > 2 else 0)
            else:
                c = coords[i]
            mem = hbm
            stats = None
            try:
                stats = dev.memory_stats()
            except Exception:
                pass
            if stats and stats.get("bytes_limit"):
                mem = int(stats["bytes_limit"])
            chips.append(ChipSpec(uuid=f"jax-{dev.id}", index=i,
                                  chip_type=chip_type, memory=mem,
                                  core_count=cores, coords=c))
        return DiscoveryResult(chips=chips,
                               mesh=MeshSpec((shape[0], shape[1], 1)),
                               chip_type=chip_type)


class FakeBackend:
    def __init__(self, n_chips: int = 4, chip_type: str = DEFAULT_CHIP_TYPE,
                 mesh_shape: tuple[int, int] | None = None,
                 chips_per_host: int = 0):
        self.n_chips = n_chips
        self.chip_type = chip_type
        self.mesh_shape = mesh_shape or (1, n_chips)
        self.chips_per_host = chips_per_host

    def discover(self) -> DiscoveryResult | None:
        hbm, cores = CHIP_MODELS.get(self.chip_type,
                                     CHIP_MODELS[DEFAULT_CHIP_TYPE])
        coords = _grid_coords(self.n_chips, self.mesh_shape)
        chips = []
        for i in range(self.n_chips):
            host = i // self.chips_per_host if self.chips_per_host else 0
            chips.append(ChipSpec(uuid=f"fake-{i}", index=i,
                                  chip_type=self.chip_type, memory=hbm,
                                  core_count=cores, coords=coords[i],
                                  host_id=host, numa=host))
        return DiscoveryResult(
            chips=chips,
            mesh=MeshSpec((self.mesh_shape[0], self.mesh_shape[1], 1)),
            chip_type=self.chip_type)


def discover(backends: list[DiscoveryBackend] | None = None
             ) -> DiscoveryResult | None:
    """First backend that finds chips wins."""
    for backend in backends or [SysfsBackend(), JaxBackend()]:
        result = backend.discover()
        if result is not None and result.chips:
            return result
    return None
