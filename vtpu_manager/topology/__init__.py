"""vtici: the ICI link-capacity plane (ICILinkAware gate, default off).

The reference's signature placement feature scores NVLink link weights
(pkg/device/gpuallocator/besteffort_policy.go); the TPU-native analogue
models each node's ICI mesh as an explicit **link graph** — one edge per
physical torus link — and makes link *contention* a measured, scored,
audited, shim-enforceable quantity:

- :mod:`links` — the graph itself: edges derived from ``MeshSpec``
  (2-D/3-D torus with per-axis wrap), each resident tenant's
  communicator box folded into per-link load, and the worst-link
  contention of any candidate chip selection computable in one pass;
- :mod:`linkload` — the feedback edge into the scheduler: a compact
  per-node link-load annotation in the pressure/headroom
  staleness-codec family, published by the device-plugin daemon
  (vtuse duty signal when fresh, allocated core % fallback) and
  decoded by BOTH scheduler data paths (TTL per candidate, snapshot
  at event-apply/relist).

Gate off = byte-identical: no annotation published, the scheduler
never parses or scores link state, ``select_submesh`` keeps its exact
pre-vtici box choice, and configs carry ``ici_link_pct=0`` (the v4
wire bytes).
"""

from vtpu_manager.topology.links import (LinkGraph, box_diameter,
                                         fold_box_load, internal_links,
                                         worst_link_load)
from vtpu_manager.topology.linkload import (LINK_BOX_WEIGHT,
                                            LINK_SCORE_WEIGHT,
                                            LINK_TERM_CAP,
                                            LinkLoadPublisher,
                                            NodeLinkLoad,
                                            compute_link_load,
                                            fallback_totals, link_term,
                                            load_is_fresh, load_map,
                                            measured_total,
                                            parse_link_load,
                                            render_fallback_metrics,
                                            reset_fallback_totals,
                                            tenant_weight)

__all__ = [
    "LinkGraph", "internal_links", "fold_box_load", "worst_link_load",
    "box_diameter", "NodeLinkLoad", "parse_link_load", "link_term",
    "load_map", "load_is_fresh", "compute_link_load", "tenant_weight",
    "LINK_SCORE_WEIGHT", "LINK_TERM_CAP", "LINK_BOX_WEIGHT",
    "LinkLoadPublisher", "fallback_totals", "measured_total",
    "render_fallback_metrics", "reset_fallback_totals",
]
