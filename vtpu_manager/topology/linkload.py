"""Node link-load annotation: vtici's feedback edge into the scheduler.

Same codec family as the vttel pressure / vtuse headroom / vtovc
overcommit annotations — parse-cheap on purpose (the snapshot path
decodes it per node event, the TTL path per visited candidate),
staleness explicit by timestamp:

    "<x>.<y>.<z>.<axis>:<load>;...@<wall_ts>"

one ``;``-separated segment per LOADED link (zero-load links are
omitted), identified by its origin cell + axis (links.py LinkId), load
in chip-duty units (one fully-busy tenant box = 1.0 on each of its
internal links; co-resident boxes stack). The timestamp makes
staleness explicit — a publisher that goes dark must decay to
"no signal" (link_term 0.0, the byte-identical pre-vtici score), never
pin its last contention claim forever.

Per-tenant traffic weight, per the vtuse precedence rule: the measured
duty/step signal when the ledger has a fresh sample for the tenant,
the allocated core %% fallback otherwise (allocated-but-unmeasured
traffic is assumed worst-case — the safe direction for a contention
signal the scheduler steers AWAY from).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass, field

from vtpu_manager.device.types import MeshSpec
from vtpu_manager.resilience import failpoints
from vtpu_manager.topology.links import fold_box_load
from vtpu_manager.util import consts
from vtpu_manager.util import stalecodec

log = logging.getLogger(__name__)

# staleness family constants (pressure/headroom/overcommit values)
MAX_LINK_AGE_S = 120.0

# defensive parse bounds: a 64-chip 4x4x4 wrapped torus has 192 links;
# the segment cap covers it with headroom, the length cap bounds the
# split cost an adversarial annotation can impose on the event path
MAX_LINK_SEGMENTS = 256
MAX_LINK_LEN = 6144

# scoring weight of the link-contention penalty: one fully-contended
# bottleneck link (load 1.0 = a whole busy tenant box already on it)
# costs 40 points — above the vtcs warm bonus (30) and any packing
# delta, below the pressure ceiling (50) and far below the +100 gang
# bonus, so gang locality still wins and a hot node is repelled, never
# vetoed. Capped so stacked residents cannot outvote the gang bonus.
LINK_SCORE_WEIGHT = 40.0
LINK_TERM_CAP = 40.0

# within-node box choice (select_submesh link dimension): contention
# outweighs the 10-point cube-ness step — a compact box on a contended
# ring loses to a slightly-less-cubic quiet one, which is exactly the
# measured spread-vs-binpack tradeoff this plane exists to make — and
# diameter breaks ties among equally-quiet boxes
LINK_BOX_WEIGHT = 50.0
LINK_DIAMETER_WEIGHT = 0.5


@dataclass(frozen=True)
class NodeLinkLoad:
    """Decoded per-node link-load rollup."""

    links: dict = field(default_factory=dict)   # LinkId -> load
    ts: float = 0.0

    def encode(self) -> str:
        segs = []
        for (cell, axis), load in sorted(self.links.items()):
            if load <= 0.0:
                continue
            segs.append(f"{cell[0]}.{cell[1]}.{cell[2]}.{axis}"
                        f":{load:.3f}")
            if len(segs) >= MAX_LINK_SEGMENTS:
                break
        return stalecodec.stamp(";".join(segs), self.ts)


def parse_link_load(raw: str | None, now: float | None = None,
                    max_age_s: float = MAX_LINK_AGE_S
                    ) -> NodeLinkLoad | None:
    """Decode the annotation; None when absent, malformed, or stale —
    every bad shape degrades to no-signal, never to a wrong contention
    claim the scheduler would steer on."""
    split = stalecodec.split_stamp(raw, max_len=MAX_LINK_LEN)
    if split is None:
        return None
    body, ts = split
    if not stalecodec.is_fresh(ts, now=now, max_age_s=max_age_s):
        return None
    out: dict = {}
    segments = 0
    for seg in body.split(";"):
        if not seg:
            continue
        segments += 1
        if segments > MAX_LINK_SEGMENTS:
            return None
        key, _, load_raw = seg.partition(":")
        parts = key.split(".")
        if len(parts) != 4:
            return None
        try:
            x, y, z, axis = (int(parts[0]), int(parts[1]),
                             int(parts[2]), int(parts[3]))
            load = float(load_raw)
        except (TypeError, ValueError):
            return None
        if not math.isfinite(load):
            # NaN parses but poisons every max() downstream — the
            # garbage-means-no-signal rule of the whole codec family
            return None
        if not 0 <= axis <= 2:
            return None
        out[((x, y, z), axis)] = max(load, 0.0)
    return NodeLinkLoad(links=out, ts=ts)


def load_is_fresh(ll: "NodeLinkLoad | None",
                  now: float | None = None) -> bool:
    """Use-time staleness verdict (the pressure-penalty rule): the
    snapshot path caches the parsed object on the NodeEntry and a dead
    publisher emits no further node events, so every consumer must
    re-judge freshness at the moment it scores on it."""
    if ll is None:
        return False
    return stalecodec.is_fresh(ll.ts, now=now, max_age_s=MAX_LINK_AGE_S)


def load_map(ll: "NodeLinkLoad | None",
             now: float | None = None) -> dict | None:
    """The LinkId -> load dict for scoring, or None when the signal is
    absent or stale — None is the gate-off identity (zero link
    evaluation, byte-identical placement)."""
    if not load_is_fresh(ll, now):
        return None
    return ll.links


def link_term(worst_link: float) -> float:
    """Score points to SUBTRACT for a candidate selection's worst-link
    contention. Soft like pressure/storm/spill: reorders fits, never
    vetoes one — a contended node with the only free chips still
    schedules."""
    if worst_link <= 0.0:
        return 0.0
    return min(worst_link * LINK_SCORE_WEIGHT, LINK_TERM_CAP)


# ---------------------------------------------------------------------------
# per-tenant traffic weights (publisher side)
# ---------------------------------------------------------------------------

def tenant_weight(alloc_core_frac: float,
                  duty_frac: float | None,
                  comm_frac: float | None = None) -> float:
    """One tenant's per-link traffic weight, by the vtcomm precedence
    rule — each step one notch less measured than the last:

    1. ``comm_frac``: the tenant's MEASURED comm link-duty (v3 comm
       block via the vtuse ledger) — the links' own accounting;
    2. ``duty_frac``: the measured COMPUTE duty share — the pre-vtcomm
       heuristic that assumes link duty tracks compute duty;
    3. allocated core fraction (0 allocation = uncapped tenant = 1.0,
       the worst-case assumption a steering signal must make)."""
    if comm_frac is not None:
        return min(max(comm_frac, 0.0), 1.0)
    if duty_frac is not None:
        return min(max(duty_frac, 0.0), 1.0)
    if alloc_core_frac <= 0.0:
        return 1.0
    return min(alloc_core_frac, 1.0)


# Publisher-side weight-source audit (the vtcomm small fix: a torn fold
# used to degrade to allocated weights SILENTLY). Module-level like the
# resilience counters: the device-plugin's /metrics handler renders
# them, tests read them directly.
FALLBACK_REASONS = ("duty", "allocated", "torn_fold")
_fallback_total: dict[str, int] = {}
_measured_total = 0


def bump_fallback(reason: str) -> None:
    _fallback_total[reason] = _fallback_total.get(reason, 0) + 1


def fallback_totals() -> dict[str, int]:
    return dict(_fallback_total)


def measured_total() -> int:
    return _measured_total


def reset_fallback_totals() -> None:
    """Test hook (the resilience-counter pattern)."""
    global _measured_total
    _fallback_total.clear()
    _measured_total = 0


def render_fallback_metrics(node: str) -> str:
    """Prometheus text for the publisher's weight-source audit; empty
    until a publisher ran (no ICILinkAware publisher = no new series,
    the gate-off contract)."""
    if not _fallback_total and not _measured_total:
        return ""
    lines = [
        "# HELP vtpu_linkload_fallback_total Link-load tenant weights "
        "published from a fallback source (duty = no measured comm "
        "signal, allocated = no fresh duty either, torn_fold = the "
        "ledger fold failed and the whole tick degraded to allocated)",
        "# TYPE vtpu_linkload_fallback_total counter",
    ]
    for reason in FALLBACK_REASONS:
        if reason in _fallback_total:
            lines.append(
                f'vtpu_linkload_fallback_total{{node="{node}",'
                f'reason="{reason}"}} {_fallback_total[reason]}')
    lines += [
        "# HELP vtpu_linkload_measured_total Link-load tenant weights "
        "published from the measured comm signal",
        "# TYPE vtpu_linkload_measured_total counter",
        f'vtpu_linkload_measured_total{{node="{node}"}} '
        f"{_measured_total}",
    ]
    return "\n".join(lines) + "\n"


def compute_link_load(base_dir: str, mesh: MeshSpec, ledger=None,
                      now: float | None = None, comm: bool = False,
                      sources: dict | None = None) -> NodeLinkLoad:
    """Fold every resident tenant's communicator box into per-link
    load. Tenant boxes come from the per-container vtpu.config files
    (the devices' mesh coords ARE the box — the same chips the
    scheduler allocated); weights by the vtcomm precedence rule:
    measured comm duty (``comm=True`` + a fresh v3 comm signal) ->
    measured compute duty -> allocated core %%. Every tenant's chosen
    source is recorded in ``sources`` (tkey -> "measured"/"duty"/
    "allocated") and the module fallback counters, so a degraded
    publish is auditable instead of silent."""
    from vtpu_manager.config import tenantdirs
    global _measured_total
    now = time.time() if now is None else now
    duty: dict[tuple[str, str], tuple[float, int]] = {}
    comm_sig: dict[tuple[str, str], tuple[float, float]] = {}
    if ledger is not None:
        try:
            ledger.fold()
            for s in ledger.tenants():
                if s.confidence(now) <= 0.0:
                    continue
                tot, n = duty.get((s.pod_uid, s.container), (0.0, 0))
                duty[(s.pod_uid, s.container)] = \
                    (tot + s.used_ewma / 100.0, n + 1)
            if comm:
                comm_sig = ledger.comm_signals(now)
        except Exception:  # noqa: BLE001 — the duty feed is advisory;
            # a torn fold degrades this tick to the allocated fallback
            # — RECORDED (vtpu_linkload_fallback_total{torn_fold}), so
            # a publisher silently serving allocated weights is visible
            log.warning("ledger fold failed; link load falls back to "
                        "allocated weights", exc_info=True)
            duty = {}
            comm_sig = {}
            bump_fallback("torn_fold")
    load: dict = {}
    for pod_uid, label, cfg, _is_dra, _mtime in \
            tenantdirs.iter_container_configs(base_dir):
        if not cfg.devices:
            continue
        cells = {tuple(d.mesh) for d in cfg.devices}
        if len(cells) < 2:
            continue            # no internal links, no ICI traffic
        alloc = sum(d.hard_core for d in cfg.devices) \
            / (100.0 * len(cfg.devices))
        tkey = (pod_uid, label)
        d = duty.get(tkey)
        duty_frac = (d[0] / d[1]) if d and d[1] else None
        cs = comm_sig.get(tkey)
        comm_frac = cs[0] if cs else None
        if comm_frac is not None:
            source = "measured"
            _measured_total += 1
        elif duty_frac is not None:
            source = "duty"
            bump_fallback("duty")
        else:
            source = "allocated"
            bump_fallback("allocated")
        if sources is not None:
            sources[tkey] = source
        fold_box_load(load, cells,
                      tenant_weight(alloc, duty_frac, comm_frac), mesh)
    return NodeLinkLoad(links=load, ts=now)


# ---------------------------------------------------------------------------
# publisher daemon (device-plugin side: the node-annotation owner)
# ---------------------------------------------------------------------------

class LinkLoadPublisher:
    """Daemon loop: fold resident boxes, patch the node annotation.

    Runs in the device-plugin daemon behind the ICILinkAware gate (the
    PressurePublisher discipline: failures tolerated per tick — the
    signal is advisory, and the annotation's own timestamp ages a
    silent death out to no-signal on the scheduler side)."""

    def __init__(self, client, node_name: str, mesh: MeshSpec,
                 base_dir: str, ledger=None, policy=None,
                 interval_s: float = 15.0, comm: bool = False):
        from vtpu_manager.resilience.policy import RetryPolicy
        self.client = client
        self.node_name = node_name
        self.mesh = mesh
        self.base_dir = base_dir
        self.ledger = ledger
        # vtcomm (CommTelemetry gate): prefer each tenant's MEASURED
        # comm link-duty over the compute-duty heuristic. Off keeps the
        # pre-vtcomm chain byte-for-byte.
        self.comm = comm
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            deadline_s=10.0)
        self.interval_s = interval_s
        # weight source of the last publish per tenant (the audit view
        # tests and the doc surface read): tkey -> measured/duty/
        # allocated
        self.last_sources: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def publish_once(self) -> NodeLinkLoad:
        sources: dict = {}
        ll = compute_link_load(self.base_dir, self.mesh,
                               ledger=self.ledger, comm=self.comm,
                               sources=sources)
        self.last_sources = sources
        # chaos: a failed publish must decay the scheduler to
        # no-signal via the annotation's own timestamp — never crash
        # the daemon loop or wedge the other publishers
        failpoints.fire("ici.publish", node=self.node_name)
        self.policy.run(
            lambda: self.client.patch_node_annotations(
                self.node_name,
                {consts.node_ici_link_load_annotation(): ll.encode()}),
            op="topology.linkload_patch")
        return ll

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.publish_once()
                except Exception:  # noqa: BLE001 — advisory signal;
                    # the annotation timestamp ages a silent failure
                    # out to no-signal (link_term decays to 0.0)
                    log.warning("link-load publish failed",
                                exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtici-linkload")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
