"""ICI link graph: explicit per-link capacity/load over a torus mesh.

The mesh the registry already publishes (``MeshSpec``: shape + per-axis
wrap) becomes an explicit edge set. A link is identified by its
**origin cell and axis** — the edge from ``cell`` to
``cell + 1 (mod size)`` along that axis. This representation is exact
for a torus: a wrapped axis of size n contributes n links per ring
(including the physically distinct wrap link on a size-2 axis, where
cells 0 and 1 are joined by TWO links), a non-wrapped axis n-1, and a
size-1 axis none.

Load model: a tenant whose communicator box spans cells C contributes
its traffic weight to EVERY link internal to C — the uniform-per-link
profile of ring all-reduce, which sends ~(2(n-1)/n)·bytes over each
ring link regardless of ring length. Worst-link contention of a
candidate selection is then ``max over its internal links of the
folded resident load`` (the candidate's own weight shifts every
internal link equally, so it cancels out of any cross-box or
cross-node comparison).

Everything here is pure arithmetic over small node meshes (<= 64
chips); no I/O, no staleness — the codec in linkload.py owns the wire
format and the staleness rules.
"""

from __future__ import annotations

import functools
import itertools

from vtpu_manager.device.types import MeshSpec

Cell = tuple[int, int, int]
# (origin cell, axis): the link from origin to origin+1 (mod size) on
# that axis — see the module docstring for why this is exact on a torus
LinkId = tuple[Cell, int]

# uniform relative capacity per physical ICI link; the contention
# metric is load / capacity, so relative units are all scoring needs
LINK_CAPACITY = 1.0


class LinkGraph:
    """The edge set of one node's ICI mesh. Immutable after build;
    instances are memoized per MeshSpec (meshes are frozen dataclasses
    shared via the registry decode cache)."""

    __slots__ = ("mesh", "links")

    def __init__(self, mesh: MeshSpec, links: frozenset):
        self.mesh = mesh
        self.links = links          # frozenset[LinkId]

    @staticmethod
    @functools.lru_cache(maxsize=256)
    def from_mesh(mesh: MeshSpec) -> "LinkGraph":
        links = set()
        sx, sy, sz = mesh.shape
        for cell in itertools.product(range(sx), range(sy), range(sz)):
            for axis in range(3):
                lid = link_from(cell, axis, mesh)
                if lid is not None:
                    links.add(lid)
        return LinkGraph(mesh, frozenset(links))

    def capacity(self, link: LinkId) -> float:  # noqa: ARG002 — uniform
        return LINK_CAPACITY

    def total_capacity(self) -> float:
        return LINK_CAPACITY * len(self.links)


def link_from(cell: Cell, axis: int, mesh: MeshSpec) -> LinkId | None:
    """The link leaving ``cell`` in +axis direction, or None when the
    mesh has no such physical link (size-1 axis, or past the edge of a
    non-wrapping axis)."""
    size = mesh.shape[axis]
    if size <= 1:
        return None
    if cell[axis] == size - 1 and not mesh.wrap[axis]:
        return None
    return (cell, axis)


def link_endpoints(link: LinkId, mesh: MeshSpec) -> tuple[Cell, Cell]:
    cell, axis = link
    other = list(cell)
    other[axis] = (cell[axis] + 1) % mesh.shape[axis]
    return cell, tuple(other)


def internal_links(cells, mesh: MeshSpec) -> list[LinkId]:
    """Links with BOTH endpoints inside ``cells`` — the edges a
    communicator box spanning those cells puts collective traffic on.
    One pass over |cells| x 3 axes."""
    cell_set = set(cells)
    out = []
    for cell in cell_set:
        for axis in range(3):
            lid = link_from(cell, axis, mesh)
            if lid is None:
                continue
            if link_endpoints(lid, mesh)[1] in cell_set:
                out.append(lid)
    return out


def fold_box_load(load: dict, cells, weight: float,
                  mesh: MeshSpec) -> None:
    """Fold one tenant's communicator box into a per-link load map
    (LinkId -> load). Uniform per internal link (the ring all-reduce
    profile); a single-chip box has no internal links and folds
    nothing."""
    if weight <= 0.0:
        return
    for lid in internal_links(cells, mesh):
        load[lid] = load.get(lid, 0.0) + weight


def worst_link_load(cells, load: dict | None, mesh: MeshSpec) -> float:
    """Worst-link contention of a candidate selection: the max folded
    resident load (per unit capacity) over the selection's internal
    links. 0.0 for empty/absent load, single-chip selections, or
    selections whose links carry no resident traffic."""
    if not load:
        return 0.0
    worst = 0.0
    for lid in internal_links(cells, mesh):
        v = load.get(lid, 0.0) / LINK_CAPACITY
        if v > worst:
            worst = v
    return worst


def _axis_dist(a: int, b: int, size: int, wrap: bool) -> int:
    d = abs(a - b)
    return min(d, size - d) if wrap and size else d


def box_diameter(cells, mesh: MeshSpec) -> int:
    """Max pairwise torus-manhattan distance inside the selection —
    the ICI hop bound of its collectives (the secondary link
    dimension, after worst-link contention)."""
    cells = list(cells)
    worst = 0
    for c1, c2 in itertools.combinations(cells, 2):
        d = sum(_axis_dist(c1[i], c2[i], mesh.shape[i], mesh.wrap[i])
                for i in range(3))
        if d > worst:
            worst = d
    return worst
