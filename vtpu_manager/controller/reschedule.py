"""Reschedule + recovery controllers: failed allocations get evicted.

Reference: pkg/controller/reschedule/reschedule.go:1-131 (evict pods whose
allocation-status annotation is "failed") and recovery.go:1-224 (evict pods
whose recorded devices vanished from the kubelet checkpoint — chip swaps,
uuid changes). Behind the Reschedule feature gate. Eviction (not delete)
respects PDBs; delete is the fallback when the eviction API is rejected.

Resilience (vtfault):

- every API call routes through ``KubeResilience`` (RetryPolicy +
  CircuitBreaker) instead of the old silent ``except KubeError: return
  0`` — a failing reconcile now counts
  (``vtpu_reschedule_reconcile_failures_total``), logs, and backs the
  loop interval off exponentially while the apiserver is unhappy;
- the crash-window reaper (resilience/recovery.py): pods whose
  bind-intent expired while still unbound get their dead commitment
  cleared (scheduler crashed between commit and bind), and bound pods
  stuck in "allocating" with no real allocation get evicted (plugin
  crashed mid-Allocate);
- the registry's per-pod bindings are reaped for pods that no longer
  exist, fed from the same pod list.
"""

from __future__ import annotations

import logging
import threading
import time

from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.deviceplugin.checkpoint import (KUBELET_CHECKPOINT,
                                                  devices_for_resource)
from vtpu_manager.deviceplugin.vnum import device_uuid
from vtpu_manager.device.types import get_pod_device_claims
from vtpu_manager.resilience import failpoints, recovery
from vtpu_manager.resilience.policy import (COUNTERS, CircuitOpenError,
                                            KubeResilience)
from vtpu_manager.scheduler import lease as lease_mod
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

# loop-interval backoff cap while reconciles fail (2**5 = 32x interval)
MAX_BACKOFF_DOUBLINGS = 5


class RescheduleController:
    def __init__(self, client: KubeClient, node_name: str,
                 known_uuids: set[str] | None = None,
                 checkpoint_path: str = KUBELET_CHECKPOINT,
                 interval_s: float = 15.0,
                 resilience: KubeResilience | None = None,
                 intent_ttl_s: float = consts.DEFAULT_STUCK_GRACE_S,
                 registry=None, intent_scan_every: int = 4,
                 lease_probe=None, cluster_scan_leader=None,
                 plan_probe=None,
                 clock=time.time):
        self.client = client
        self.node_name = node_name
        # vtha: ``lease_probe(shard) -> LeaseState | None`` (typically
        # scheduler.lease.read_lease_state). With it, the
        # committed-unbound reaper keys eligibility off fencing token +
        # lease LIVENESS: an intent stamped by a scheduler that still
        # holds its shard lease under the same token belongs to a live —
        # possibly just slow — peer and is never reaped on wall-clock
        # alone; a stale token is reapable immediately. None (single
        # scheduler) keeps the PR 4 wall-clock rule untouched.
        self.lease_probe = lease_probe
        # vtscale: ``plan_probe() -> int`` returns the current published
        # shard-plan epoch (typically a closure over plan.read_plan).
        # With it, an intent whose fence stamp carries an OLDER epoch is
        # reapable immediately — its partition was superseded by a
        # rolling reshard, so its commit-time confirm() can never land
        # (the new-epoch incarnation CAS-bumped the token), even when
        # the stamped shard name no longer exists in the new plan and
        # no lease probe can vouch for it. None (no published plan, or
        # gate off) = byte-identical pre-vtscale behavior.
        self.plan_probe = plan_probe
        self._plan_epoch_cache: int | None = None
        self._clock = clock
        self._lease_states: dict[str, object] = {}
        self.known_uuids = known_uuids or set()
        self.checkpoint_path = checkpoint_path
        self.interval_s = interval_s
        self.resilience = resilience or KubeResilience()
        # how long a bind-intent may sit unresolved before the crash
        # window it marks is reaped (aligned with the scheduler's stuck
        # grace: both date the same commitment)
        self.intent_ttl_s = intent_ttl_s
        # RegistryServer (ClientMode): fed the live pod-uid set so
        # bindings of vanished pods are reaped each reconcile
        self.registry = registry
        # cadence of the CLUSTER-wide pod list that feeds the
        # committed-but-unbound reaper (those pods carry only the
        # predicate-node annotation, which no field selector can reach).
        # Every other pass uses the server-side nodeName selector —
        # O(node) not O(cluster), the original load profile. 1 = scan
        # every pass (the chaos harness does).
        self.intent_scan_every = max(1, intent_scan_every)
        # vtpilot: ``cluster_scan_leader() -> bool`` elects ONE
        # controller fleet-wide to pay the cluster LIST (wired to the
        # autopilot/coordination lease's held_fresh when the
        # SLOAutopilot gate is on). Non-leaders keep their node-scoped
        # passes untouched. None (the default) = byte-identical
        # pre-vtpilot behavior: every controller scans on cadence. A
        # RAISING probe falls back to scanning — duplicate LISTs cost
        # apiserver load, a never-reaped crash window costs
        # correctness.
        self.cluster_scan_leader = cluster_scan_leader
        self._pass_index = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.evicted: list[tuple[str, str]] = []   # observability for tests
        self.requeued: list[tuple[str, str]] = []  # cleared commitments
        self.consecutive_failures = 0
        self.reconcile_failures_total = 0

    # -- one reconcile pass -------------------------------------------------

    def reconcile_once(self) -> int:
        evictions = 0
        self._pass_index += 1
        cluster_scan = (self._pass_index % self.intent_scan_every) == 1 \
            or self.intent_scan_every == 1
        if cluster_scan and self.cluster_scan_leader is not None:
            try:
                cluster_scan = bool(self.cluster_scan_leader())
            except Exception as e:
                # a broken probe must degrade to the pre-vtpilot shape
                # (everyone scans), never to nobody-reaps
                log.warning("cluster-scan leader probe failed (%s); "
                            "scanning anyway", e)
        try:
            if cluster_scan:
                # the crash-window reaper must see pods COMMITTED to
                # this node but not yet bound — those carry only the
                # predicate-node annotation, which no field selector
                # reaches, so this cadenced pass pays one cluster LIST
                all_pods = self.resilience.call(self.client.list_pods,
                                                op="reschedule.list_pods")
                pods, committed, _ = self._partition(all_pods)
            else:
                pods = self.resilience.call(
                    lambda: self.client.list_pods(
                        node_name=self.node_name),
                    op="reschedule.list_pods")
                committed = []
        except (KubeError, CircuitOpenError) as e:
            self.consecutive_failures += 1
            self.reconcile_failures_total += 1
            COUNTERS.bump("reschedule.reconcile", "failure")
            log.warning("reschedule reconcile: pod list failed "
                        "(consecutive failure #%d): %s",
                        self.consecutive_failures, e)
            return 0
        self.consecutive_failures = 0
        # lease states probed at most once per shard per pass (the
        # committed list can hold many pods of one shard); the plan
        # epoch likewise — one probe per pass, not per pod
        self._lease_states: dict[str, object] = {}
        self._plan_epoch_cache = None
        now = self._clock()
        # registrations only exist for pods allocated (hence bound) on
        # THIS node, so the resident set is the right liveness truth for
        # the registry reap — node-scoped on every pass
        resident_uids = {(p.get("metadata") or {}).get("uid", "")
                         for p in pods}
        checkpoint = devices_for_resource(consts.vtpu_number_resource(),
                                          self.checkpoint_path)
        # crash window 1: committed-but-unbound pods whose intent expired
        for pod in committed:
            self._reap_dead_commitment(pod, now)
        for pod in pods:
            meta = pod.get("metadata") or {}
            anns = meta.get("annotations") or {}
            phase = (pod.get("status") or {}).get("phase", "")
            if phase in ("Succeeded", "Failed"):
                continue
            ns = meta.get("namespace", "default")
            name = meta.get("name", "")
            uid = meta.get("uid", "")

            if anns.get(consts.allocation_status_annotation()) == \
                    consts.ALLOC_STATUS_FAILED:
                # the device plugin could not fulfil the scheduler's
                # commitment; send the pod back through scheduling
                self._evict(ns, name, "allocation failed on node")
                evictions += 1
                continue

            # crash window 2: bound, status "allocating", no real
            # allocation, and the bind-intent (or the predicate stamp)
            # expired — the plugin died mid-Allocate and could not even
            # patch "failed"
            if self._allocating_stuck(anns, now):
                self._evict(ns, name,
                            "stuck in allocating past the bind-intent "
                            "ttl (plugin crash window)")
                evictions += 1
                continue

            if self.known_uuids and anns.get(
                    consts.real_allocated_annotation()):
                claims = get_pod_device_claims(pod)
                missing = [c.uuid for c in (claims.all_claims()
                                            if claims else [])
                           if c.uuid not in self.known_uuids]
                if missing:
                    self._evict(ns, name,
                                f"allocated devices gone: {missing}")
                    evictions += 1
                    continue

            # recovery: pod holds checkpointed kubelet devices that no
            # longer exist on this node (chip uuid change across restart)
            held = checkpoint.get(uid)
            if held and self.known_uuids:
                ghost = [d for d in held
                         if device_uuid(d) not in self.known_uuids]
                if ghost:
                    self._evict(ns, name,
                                f"kubelet checkpoint references missing "
                                f"devices: {ghost[:4]}")
                    evictions += 1
        if self.registry is not None:
            self.registry.reap_orphans(resident_uids)
        return evictions

    def _partition(self, all_pods: list[dict]
                   ) -> tuple[list[dict], list[dict], set[str]]:
        """(resident pods, committed-but-unbound pods, all live uids).
        Residents carry our nodeName; committed pods carry only the
        predicate-node annotation (the filter committed, bind never
        landed)."""
        resident: list[dict] = []
        committed: list[dict] = []
        live_uids: set[str] = set()
        for pod in all_pods:
            meta = pod.get("metadata") or {}
            live_uids.add(meta.get("uid", ""))
            node = (pod.get("spec") or {}).get("nodeName") or ""
            if node == self.node_name:
                resident.append(pod)
            elif not node and (meta.get("annotations") or {}).get(
                    consts.predicate_node_annotation()) == self.node_name:
                committed.append(pod)
        return resident, committed, live_uids

    def _intent_reap_eligible(self, anns: dict, now: float) -> bool:
        """Whether a committed-but-unbound pod's intent may be reaped.
        Wall-clock expiry alone is wrong in an active-active deployment:
        a slow peer's in-flight bind looks identical to a dead one's.
        With a lease probe, the fencing stamp decides:

        - stamp token == lease token AND the lease is live -> the owning
          scheduler is alive and may still land this bind: NOT reapable;
        - stamp token < lease token -> ownership moved on: the stamp's
          incarnation is fenced off (its commit-time confirm() can no
          longer succeed) and the commitment is stale by definition —
          reapable without any wall-clock wait;
        - no usable lease signal (no stamp, probe failed, lease gone) ->
          the PR 4 wall-clock rule.

        vtscale adds one rule ahead of all of these: a stamp whose plan
        EPOCH is older than the published plan's is reapable
        immediately — a rolling reshard fenced that whole partition off,
        token comparisons within it no longer mean anything."""
        fence = lease_mod.parse_fence_epoch(
            (anns or {}).get(consts.shard_fence_annotation()))
        if fence is not None and self.plan_probe is not None \
                and fence[2] > 0:
            if self._plan_epoch_cache is None:
                try:
                    self._plan_epoch_cache = int(self.plan_probe() or 0)
                except Exception:
                    # a failing probe must degrade to the lease/wall-
                    # clock rules, not block reaping
                    self._plan_epoch_cache = 0
            if 0 < fence[2] < self._plan_epoch_cache:
                return True
        if fence is not None and self.lease_probe is not None:
            if fence[0] not in self._lease_states:
                self._lease_states[fence[0]] = self.lease_probe(fence[0])
            state = self._lease_states[fence[0]]
            if state is not None:
                if state.token > fence[1]:
                    return True
                if state.token == fence[1] and state.live(now):
                    return False
        return recovery.intent_expired(anns, now, self.intent_ttl_s)

    def _allocating_stuck(self, anns: dict, now: float) -> bool:
        if anns.get(consts.allocation_status_annotation()) != \
                consts.ALLOC_STATUS_ALLOCATING:
            return False
        if anns.get(consts.real_allocated_annotation()):
            return False
        return recovery.intent_expired(anns, now, self.intent_ttl_s)

    def _reap_dead_commitment(self, pod: dict, now: float) -> bool:
        """Clear the annotations of a commitment whose bind never landed
        (scheduler crashed between the intent patch and the Binding
        POST). Clearing — not evicting — because the pod is still
        Pending: erasing the dead commitment returns it to the
        scheduling queue's normal flow."""
        meta = pod.get("metadata") or {}
        anns = meta.get("annotations") or {}
        if anns.get(consts.real_allocated_annotation()):
            # the plugin fulfilled the commitment (watch-lag Allocate can
            # complete before the Binding lands): the allocation record
            # is live state — clearing it would LEAK the devices
            return False
        if not self._intent_reap_eligible(anns, now):
            return False
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        log.warning("reaping dead bind commitment for %s/%s (intent "
                    "expired unbound)", ns, name)
        try:
            self.resilience.call(
                lambda: self.client.patch_pod_annotations(
                    ns, name, recovery.commitment_clear_patch()),
                op="reschedule.clear_commitment")
        except (KubeError, CircuitOpenError) as e:
            log.warning("commitment clear failed for %s/%s: %s",
                        ns, name, e)
            return False
        self.requeued.append((ns, name))
        self._emit_event(ns, name, "dead bind commitment cleared "
                                   "(scheduler crash window)")
        return True

    def _evict(self, namespace: str, name: str, reason: str) -> None:
        log.warning("evicting %s/%s: %s", namespace, name, reason)
        failpoints.fire("controller.evict", namespace=namespace, pod=name)
        try:
            self.resilience.call(
                lambda: self.client.evict_pod(namespace, name),
                op="reschedule.evict")
        except (KubeError, CircuitOpenError):
            try:
                self.resilience.call(
                    lambda: self.client.delete_pod(namespace, name,
                                                   grace_seconds=30),
                    op="reschedule.delete")
            except (KubeError, CircuitOpenError):
                log.error("both evict and delete failed for %s/%s",
                          namespace, name)
                return
        self.evicted.append((namespace, name))
        self._emit_event(namespace, name, reason)

    def _emit_event(self, namespace: str, name: str, reason: str) -> None:
        try:
            self.client.create_event(namespace, {
                "metadata": {"generateName": "vtpu-reschedule-"},
                "involvedObject": {"kind": "Pod", "namespace": namespace,
                                   "name": name},
                "reason": "VtpuReschedule",
                "message": reason[:1024],
                "type": "Warning",
            })
        except KubeError:
            log.warning("reschedule event emit failed for %s/%s",
                        namespace, name)

    # -- loop ---------------------------------------------------------------

    def current_interval_s(self) -> float:
        """Loop pacing: the base interval, doubled per consecutive
        reconcile failure (capped) — a throttling apiserver gets relief,
        and the first clean pass snaps back to the base cadence."""
        doublings = min(self.consecutive_failures, MAX_BACKOFF_DOUBLINGS)
        return self.interval_s * (2 ** doublings)

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.current_interval_s()):
                try:
                    self.reconcile_once()
                except Exception:
                    log.exception("reschedule reconcile failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtpu-reschedule")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
