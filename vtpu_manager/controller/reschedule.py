"""Reschedule + recovery controllers: failed allocations get evicted.

Reference: pkg/controller/reschedule/reschedule.go:1-131 (evict pods whose
allocation-status annotation is "failed") and recovery.go:1-224 (evict pods
whose recorded devices vanished from the kubelet checkpoint — chip swaps,
uuid changes). Behind the Reschedule feature gate. Eviction (not delete)
respects PDBs; delete is the fallback when the eviction API is rejected.
"""

from __future__ import annotations

import logging
import threading

from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.deviceplugin.checkpoint import (KUBELET_CHECKPOINT,
                                                  devices_for_resource)
from vtpu_manager.deviceplugin.vnum import device_uuid
from vtpu_manager.device.types import get_pod_device_claims
from vtpu_manager.util import consts

log = logging.getLogger(__name__)


class RescheduleController:
    def __init__(self, client: KubeClient, node_name: str,
                 known_uuids: set[str] | None = None,
                 checkpoint_path: str = KUBELET_CHECKPOINT,
                 interval_s: float = 15.0):
        self.client = client
        self.node_name = node_name
        self.known_uuids = known_uuids or set()
        self.checkpoint_path = checkpoint_path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.evicted: list[tuple[str, str]] = []   # observability for tests

    # -- one reconcile pass -------------------------------------------------

    def reconcile_once(self) -> int:
        evictions = 0
        try:
            pods = self.client.list_pods(node_name=self.node_name)
        except KubeError:
            return 0
        checkpoint = devices_for_resource(consts.vtpu_number_resource(),
                                          self.checkpoint_path)
        for pod in pods:
            meta = pod.get("metadata") or {}
            anns = meta.get("annotations") or {}
            phase = (pod.get("status") or {}).get("phase", "")
            if phase in ("Succeeded", "Failed"):
                continue
            ns = meta.get("namespace", "default")
            name = meta.get("name", "")
            uid = meta.get("uid", "")

            if anns.get(consts.allocation_status_annotation()) == \
                    consts.ALLOC_STATUS_FAILED:
                # the device plugin could not fulfil the scheduler's
                # commitment; send the pod back through scheduling
                self._evict(ns, name, "allocation failed on node")
                evictions += 1
                continue

            if self.known_uuids and anns.get(
                    consts.real_allocated_annotation()):
                claims = get_pod_device_claims(pod)
                missing = [c.uuid for c in (claims.all_claims()
                                            if claims else [])
                           if c.uuid not in self.known_uuids]
                if missing:
                    self._evict(ns, name,
                                f"allocated devices gone: {missing}")
                    evictions += 1
                    continue

            # recovery: pod holds checkpointed kubelet devices that no
            # longer exist on this node (chip uuid change across restart)
            held = checkpoint.get(uid)
            if held and self.known_uuids:
                ghost = [d for d in held
                         if device_uuid(d) not in self.known_uuids]
                if ghost:
                    self._evict(ns, name,
                                f"kubelet checkpoint references missing "
                                f"devices: {ghost[:4]}")
                    evictions += 1
        return evictions

    def _evict(self, namespace: str, name: str, reason: str) -> None:
        log.warning("evicting %s/%s: %s", namespace, name, reason)
        try:
            self.client.evict_pod(namespace, name)
        except KubeError:
            try:
                self.client.delete_pod(namespace, name, grace_seconds=30)
            except KubeError:
                log.error("both evict and delete failed for %s/%s",
                          namespace, name)
                return
        self.evicted.append((namespace, name))
        try:
            self.client.create_event(namespace, {
                "metadata": {"generateName": "vtpu-reschedule-"},
                "involvedObject": {"kind": "Pod", "namespace": namespace,
                                   "name": name},
                "reason": "VtpuReschedule",
                "message": reason[:1024],
                "type": "Warning",
            })
        except KubeError:
            pass

    # -- loop ---------------------------------------------------------------

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.reconcile_once()
                except Exception:
                    log.exception("reschedule reconcile failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtpu-reschedule")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
