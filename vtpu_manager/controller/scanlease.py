"""Elected reschedule-controller cluster scan (the vtscale leftover).

The committed-but-unbound reaper needs ONE cluster-wide pod LIST per
cadence round — those pods carry only the predicate-node annotation,
which no field selector reaches — but pre-this-module every controller
paid it unless the SLOAutopilot gate happened to be on (the reaper's
leadership rode the autopilot coordination lease, with lease I/O on
every probe call). This module gives the scan its OWN activity lease
under the Reschedule gate, the webhook-HA pattern:

- the entrypoint runs the **renew ticker** (one background thread per
  controller: acquire when vacant, renew while held, stand by on a
  live foreign lease — the ShardLease machinery unchanged, under its
  own Lease object so it never contends with scheduler shards);
- the controller's ``cluster_scan_leader`` probe reads only the cheap
  local ``held_fresh()`` — **no lease I/O ever rides the reconcile
  path** (the webhook handlers' no-I/O rule);
- the probe **fails open to scanning**: while the lease machinery is
  unproven (apiserver unreachable, ticker not yet run) it raises, and
  the controller's existing fallback scans anyway — duplicate LISTs
  cost apiserver load, a never-reaped crash window costs correctness.

Followers keep their node-scoped passes untouched either way.
"""

from __future__ import annotations

import logging
import threading

from vtpu_manager.scheduler.lease import (DEFAULT_LEASE_NAMESPACE,
                                          LeaseLostError, ShardLease)

log = logging.getLogger(__name__)

# the shard name on the dedicated Lease object; distinct from every
# scheduler shard and from the autopilot coordination shard, so scan
# leadership never couples to either plane's election
RESCHEDULE_SCAN_SHARD = "reschedule-scan"
DEFAULT_TICK_S = 5.0


class ScanLeaseTicker:
    """Background renew ticker + local-read probe for the scan lease."""

    def __init__(self, client, holder: str,
                 namespace: str = DEFAULT_LEASE_NAMESPACE,
                 ttl_s: float = 30.0, tick_s: float = DEFAULT_TICK_S):
        self.lease = ShardLease(client, RESCHEDULE_SCAN_SHARD, holder,
                                ttl_s=ttl_s, namespace=namespace)
        self.tick_s = tick_s
        # True once any tick completed its lease I/O without raising —
        # before that (and after an I/O-failing tick) the probe must
        # fail open: "not leader" would silently mean "nobody scans"
        self._proven = False
        self.tick_failures_total = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- ticker (entrypoint-run, the webhook renew-ticker rule) --------------

    def tick_once(self) -> None:
        """One maintenance step: renew while held (a definitive loss
        re-enters the acquire race immediately), acquire when vacant,
        stand by on a live foreign lease."""
        try:
            if self.lease.held:
                try:
                    self.lease.renew()
                except LeaseLostError:
                    self.lease.try_acquire()
            else:
                self.lease.try_acquire()
            self._proven = True
        except Exception:
            # apiserver trouble: leadership is unproven, the probe
            # fails open until a tick succeeds again
            self._proven = False
            self.tick_failures_total += 1
            raise

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.tick_s):
                try:
                    self.tick_once()
                except Exception as e:  # noqa: BLE001 — lease trouble
                    # must not kill the ticker; the probe is already
                    # failing open and the next tick retries
                    log.warning("scan-lease tick failed: %s", e)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtscan-lease")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self.lease.held:
            try:
                self.lease.release()
            except Exception:  # noqa: BLE001 — best-effort handoff;
                # the TTL expires the lease for the next acquirer
                log.debug("scan-lease release failed", exc_info=True)

    # -- probe (reconcile-path, local reads ONLY) ----------------------------

    def probe(self) -> bool:
        """``cluster_scan_leader`` value: am I the scan leader right
        now? Pure local reads (held_fresh is a clock compare). Raises
        while leadership is unproven — the controller's existing
        fail-open catch scans anyway."""
        if not self._proven and not self.lease.held_fresh():
            raise RuntimeError(
                "scan lease unproven (ticker has not completed a "
                "lease round-trip); failing open to scanning")
        return self.lease.held_fresh()
