"""The node's health publisher: evidence in, one annotation out.

Runs in the device-plugin daemon behind the HealthPlane gate (the
LinkLoadPublisher discipline: failures tolerated per tick — and here
the decay direction matters doubly: a dead publisher's annotation ages
out and the cordon LIFTS, with the legacy registry ``healthy`` flip as
the non-decaying backstop).

Per tick: (1) run the chip probe per chip — the same external command
contract as manager.HealthWatcher, but with exec-failure fail-open
(a probe that cannot RUN proves nothing about the chip; it bumps the
audit counter and the ladder sees no sample); (2) collect shim-side
ring evidence (signals.py stall/exec); (3) probe ICI neighbor links
when a link prober is wired; (4) fold the ladder, fire the flip
failpoint/counters for every state transition, and patch the
stalecodec annotation.
"""

from __future__ import annotations

import logging
import threading
import time

from vtpu_manager.health import metrics as health_metrics
from vtpu_manager.health import signals
from vtpu_manager.health.ladder import NodeHealthLadder
from vtpu_manager.resilience import failpoints
from vtpu_manager.topology.links import LinkGraph
from vtpu_manager.util import consts

log = logging.getLogger(__name__)


class ChipHealthPublisher:
    """Daemon loop: probe + fold + patch.

    ``chips`` maps chip index -> mesh cell (or None when the node has
    no mesh) — the registry's own view, so a failed link's endpoints
    resolve back to chip indices. ``probe(index)`` returns the chip
    verdict (True healthy / False unhealthy) or None for no-sample;
    it must raise OSError only for exec-failure (the fail-open leg).
    ``link_probe(link_id)`` likewise returns the edge verdict or None.
    """

    def __init__(self, client, node_name: str, chips: dict,
                 base_dir: str, probe=None, link_probe=None,
                 mesh=None, policy=None, interval_s: float = 15.0,
                 clock=time.time):
        from vtpu_manager.resilience.policy import RetryPolicy
        self.client = client
        self.node_name = node_name
        self.chips = dict(chips)
        self.base_dir = base_dir
        self.probe = probe
        self.link_probe = link_probe
        self.mesh = mesh
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            deadline_s=10.0)
        self.interval_s = interval_s
        self.clock = clock
        self.ladder = NodeHealthLadder(clock=clock)
        self.tracker = signals.StallTracker()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- evidence ------------------------------------------------------------

    def _probe_chips(self, now: float) -> None:
        if self.probe is None:
            return
        for index in self.chips:
            failpoints.fire("health.probe", node=self.node_name,
                            chip=index)
            try:
                verdict = self.probe(index)
            except OSError:
                # the probe failed to RUN: fail-open — no evidence
                # either way, only the audit counter (the satellite
                # fix's contract, shared with manager.HealthWatcher)
                health_metrics.bump_probe_exec_failure()
                continue
            if verdict is None:
                continue
            self.ladder.observe_chip(index, "probe", not verdict, now)

    def _probe_links(self, now: float) -> None:
        if self.link_probe is None or self.mesh is None:
            return
        cell_to_chip = {cell: i for i, cell in self.chips.items()
                        if cell is not None}
        for lid in sorted(LinkGraph.from_mesh(self.mesh).links):
            verdict = self.link_probe(lid)
            if verdict is None:
                continue
            self.ladder.observe_link(lid, not verdict)
        # a probe-confirmed dead edge is chip evidence for BOTH
        # endpoints (the ladder's weakest cordon-capable signal: one
        # dead link alone is suspect; with a failing probe it compounds)
        from vtpu_manager.topology.links import link_endpoints
        failed = self.ladder.failed_links()
        touched = set()
        for lid in failed:
            for cell in link_endpoints(lid, self.mesh):
                index = cell_to_chip.get(cell)
                if index is not None:
                    touched.add(index)
        for index, cell in self.chips.items():
            if cell is None:
                continue
            self.ladder.observe_chip(index, "link", index in touched,
                                     now)

    # -- the tick ------------------------------------------------------------

    def publish_once(self, now: float | None = None):
        now = self.clock() if now is None else now
        self._probe_chips(now)
        ring_ev = signals.collect_ring_evidence(self.base_dir,
                                                self.tracker, now)
        for index, ev in ring_ev.items():
            if index not in self.chips:
                continue
            self.ladder.observe_chip(index, "stall", ev["stall"], now)
            self.ladder.observe_chip(index, "exec", ev["exec"], now)
        self._probe_links(now)
        health = self.ladder.fold(now)
        for index, old, new in self.ladder.last_flips:
            # chaos: a crash here must leave the LAST published state
            # standing until the annotation ages out — never a torn one
            failpoints.fire("health.flip", node=self.node_name,
                            chip=index, to=new)
            health_metrics.bump_flip(new)
            log.info("chip %s/%d health %s -> %s", self.node_name,
                     index, old, new)
        health_metrics.set_chip_states(
            {i: s for i, (s, _c) in health.chips.items()})
        self.policy.run(
            lambda: self.client.patch_node_annotations(
                self.node_name,
                {consts.node_chip_health_annotation():
                 health.encode()}),
            op="health.publish_patch")
        return health

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.publish_once()
                except Exception:  # noqa: BLE001 — advisory signal;
                    # the annotation timestamp ages a silent failure
                    # out to no-signal (the cordon lifts, the legacy
                    # registry flip backstops a truly dead chip)
                    log.warning("chip-health publish failed",
                                exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtheal-publisher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
