"""Rescue-side fold: failed chips -> chip-failure verdicts.

The autopilot consumes vtslo verdicts; this module makes chip failure
speak the same wire dialect so the WHOLE guard chain (hysteresis,
cooldown, dual token buckets, fence stamping, vtexplain + ledger audit)
applies unchanged — a chip failure is just one more cause with one more
executor (actions.rescue_gang), not a parallel control loop.

Verdict shape: ``{"kind": "chip-failure", "tenant": "<uid>/<label>",
"node", "chips": [...], "episode_onset_ts", "goodput"}``. The onset is
the health annotation's OWN fold timestamp, so each publisher tick is a
distinct detector episode — HYSTERESIS_EPISODES=2 means a gang is
rescued in the first autopilot window after the SECOND tick that still
says failed, never off one noisy fold (the bench's "first
hysteresis-eligible window" clock).

Priority: verdicts sort by vtslo goodput DESCENDING — the most
productive gang is rescued first (it loses the most per stranded
second), and the ordering is the tie-breaker the token buckets see
when a failed chip hosts more gangs than one window may move.
"""

from __future__ import annotations

import os
import time

from vtpu_manager.health import codec
from vtpu_manager.util import consts


def ring_goodput(base_dir: str, pod_uid: str, container: str) -> float:
    """The tenant's vtslo goodput ratio straight off its step ring;
    1.0 (the neutral prior) when the ring is absent or unreadable —
    an unmeasured gang is assumed fully productive, the safe direction
    for a rescue PRIORITY (it only moves the gang up the queue)."""
    from vtpu_manager.slo.attribution import attribute, goodput_ratio
    from vtpu_manager.telemetry import stepring
    ring_path = os.path.join(base_dir, f"{pod_uid}_{container}",
                             consts.TELEMETRY_SUBDIR,
                             consts.STEP_RING_NAME)
    if not os.path.isfile(ring_path):
        return 1.0
    try:
        reader = stepring.StepRingReader(ring_path)
    except (OSError, ValueError):
        return 1.0
    try:
        records, _, _ = reader.poll(0)
    finally:
        reader.close()
    if not records:
        return 1.0
    comps: dict[str, int] = {}
    for rec in records:
        for name, ns in attribute(rec).items():
            comps[name] = comps.get(name, 0) + ns
    return goodput_ratio(comps)


def node_chip_health(client, node: str,
                     now: float | None = None):
    """The node's parsed, freshness-judged health annotation (or None
    — no signal, no cordon, no rescue)."""
    node_obj = client.get_node(node) or {}
    raw = (node_obj.get("metadata", {}).get("annotations", {})
           or {}).get(consts.node_chip_health_annotation())
    return codec.parse_chip_health(raw, now=now)


def unhealthy_nodes(client, now: float | None = None) -> set:
    """Nodes whose fresh health annotation cordons ANY chip — the
    rescue executor's target-exclusion set (never migrate a gang onto
    a box the same plane is draining)."""
    out = set()
    for name in sorted(getattr(client, "nodes", {}) or {}):
        ch = node_chip_health(client, name, now=now)
        if codec.cordon_mask(ch, now=now):
            out.add(name)
    return out


def rescue_verdicts(node: str, base_dir: str, health,
                    now: float | None = None,
                    goodput_for=None) -> list[dict]:
    """Chip-failure verdicts for every gang resident on a FAILED chip
    of ``node`` (degraded chips cordon admissions but keep their
    residents), goodput-descending."""
    from vtpu_manager.config import tenantdirs
    now = time.time() if now is None else now
    failed = codec.failed_chips(health, now=now)
    if not failed:
        return []
    if goodput_for is None:
        goodput_for = lambda uid, cont: ring_goodput(base_dir, uid, cont)  # noqa: E731
    out = []
    for pod_uid, label, cfg, _is_dra, _mtime in \
            tenantdirs.iter_container_configs(base_dir):
        chips = sorted(d.host_index for d in cfg.devices
                       if d.host_index in failed)
        if not chips:
            continue
        container = label.partition("/")[0]
        out.append({
            "kind": "chip-failure",
            "tenant": f"{pod_uid}/{label}",
            "node": node,
            "chips": chips,
            "episode_onset_ts": round(health.ts, 3),
            "goodput": round(goodput_for(pod_uid, container), 4),
        })
    out.sort(key=lambda v: (-v["goodput"], v["tenant"]))
    return out


def chip_failure_verdicts(client, base_dir_for_node,
                          now: float | None = None,
                          goodput_for=None) -> list[dict]:
    """Cluster-wide verdict feed leg: every node's fresh health
    annotation folded into chip-failure verdicts. The monitor chains
    this with the vtslo /slo fan-in into one ``verdict_feed`` —
    both speak the same wire shape by construction."""
    now = time.time() if now is None else now
    out: list[dict] = []
    for name in sorted(getattr(client, "nodes", {}) or {}):
        health = node_chip_health(client, name, now=now)
        if health is None:
            continue
        base = base_dir_for_node(name)
        if not base:
            continue
        out.extend(rescue_verdicts(name, base, health, now=now,
                                   goodput_for=goodput_for))
    return out
