"""vtheal telemetry counters — the ONE home of every
``vtpu_chip_health_*`` / ``vtpu_health_rescue_*`` literal (the
metrics-registry one-home rule; docs/telemetry.md carries the
operator inventory).

Module-level like the resilience and linkload counters: the
device-plugin's /metrics handler renders the node-side families when
the HealthPlane gate armed a publisher, the monitor's handler renders
the rescue family when the autopilot dispatched a chip-failure action;
both render "" until something bumped — a gate-off process emits zero
new series, the byte-identical contract.
"""

from __future__ import annotations

from vtpu_manager.health import codec

RESCUE_OUTCOMES = ("migrated", "parked", "failed")

_chip_states: dict[int, str] = {}      # last published state per chip
_flip_total: dict[str, int] = {}       # to-state -> flips
_probe_exec_failures = 0               # probe cmd failed to EXECUTE
_rescue_total: dict[str, int] = {}     # outcome -> rescues
_published = False


def set_chip_states(states: dict) -> None:
    """Last published ladder output (index -> state, non-healthy only),
    recorded by the publisher after each fold."""
    global _published
    _chip_states.clear()
    _chip_states.update(states)
    _published = True


def bump_flip(to_state: str) -> None:
    _flip_total[to_state] = _flip_total.get(to_state, 0) + 1


def bump_probe_exec_failure() -> None:
    """The probe COMMAND failed to run (OSError/timeout) — fail-open
    evidence quality, not chip evidence (the satellite fix's audit
    counter: a misconfigured probe must be visible, never a flip)."""
    global _probe_exec_failures
    _probe_exec_failures += 1


def probe_exec_failures() -> int:
    return _probe_exec_failures


def bump_rescue(outcome: str) -> None:
    _rescue_total[outcome] = _rescue_total.get(outcome, 0) + 1


def rescue_totals() -> dict[str, int]:
    return dict(_rescue_total)


def reset_health_totals() -> None:
    """Test hook (the resilience-counter pattern)."""
    global _probe_exec_failures, _published
    _chip_states.clear()
    _flip_total.clear()
    _rescue_total.clear()
    _probe_exec_failures = 0
    _published = False


def render_health_metrics(node: str) -> str:
    """Node-side families; empty until a HealthPlane publisher ran (no
    publisher = no new series, the gate-off contract)."""
    if not _published and not _flip_total and not _probe_exec_failures:
        return ""
    lines = [
        "# HELP vtpu_chip_health_state Debounced ladder state per chip "
        "(1 = the chip currently holds this state; healthy chips emit "
        "no series)",
        "# TYPE vtpu_chip_health_state gauge",
    ]
    for index in sorted(_chip_states):
        state = _chip_states[index]
        if state == codec.HEALTHY:
            continue
        lines.append(f'vtpu_chip_health_state{{node="{node}",'
                     f'chip="{index}",state="{state}"}} 1')
    lines += [
        "# HELP vtpu_chip_health_unhealthy Chips currently outside the "
        "healthy state (the fleet headline input)",
        "# TYPE vtpu_chip_health_unhealthy gauge",
        f'vtpu_chip_health_unhealthy{{node="{node}"}} '
        f"{sum(1 for s in _chip_states.values() if s != codec.HEALTHY)}",
        "# HELP vtpu_chip_health_flips_total Ladder state transitions "
        "published, by destination state",
        "# TYPE vtpu_chip_health_flips_total counter",
    ]
    for state in codec.STATES:
        if state in _flip_total:
            lines.append(f'vtpu_chip_health_flips_total{{node="{node}",'
                         f'to="{state}"}} {_flip_total[state]}')
    lines += [
        "# HELP vtpu_chip_health_probe_exec_failures_total Health-probe "
        "commands that failed to EXECUTE (fail-open: counted, never a "
        "flip)",
        "# TYPE vtpu_chip_health_probe_exec_failures_total counter",
        f"vtpu_chip_health_probe_exec_failures_total"
        f'{{node="{node}"}} {_probe_exec_failures}',
    ]
    return "\n".join(lines) + "\n"


def render_rescue_metrics() -> str:
    """Monitor-side family; empty until the autopilot dispatched a
    chip-failure rescue (same gate-off contract)."""
    if not _rescue_total:
        return ""
    lines = [
        "# HELP vtpu_health_rescue_total Gang rescues dispatched for "
        "failed chips, by outcome (migrated, parked = bounded "
        "park-and-retry, failed)",
        "# TYPE vtpu_health_rescue_total counter",
    ]
    for outcome in RESCUE_OUTCOMES:
        if outcome in _rescue_total:
            lines.append(f'vtpu_health_rescue_total'
                         f'{{outcome="{outcome}"}} '
                         f"{_rescue_total[outcome]}")
    return "\n".join(lines) + "\n"
