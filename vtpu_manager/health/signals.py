"""Shim-side health evidence: what the step rings say about the chips.

The probe command asks the hardware; this module asks the TENANTS — the
two views disagree in exactly the ways the ladder's weights encode:

- **stall**: a resident ring's ``writes`` head stopped advancing for
  STALL_AFTER_S. Alone this is a WEDGED TENANT (deadlocked input
  pipeline, a debugger, a crashed trainer) — real, but not the chip's
  fault; corroborated by a failing probe it is the dead-chip shape.
  Only rings that ever progressed can stall: a tenant that hasn't
  taken its first step yet is starting up, not stuck.
- **exec**: a trailing streak of FLAG_EXEC_ERROR records (>=
  EXEC_STREAK_N). One errored step is a retry the runtime absorbed; a
  streak is a chip that stopped executing while the tenant keeps
  submitting — strong evidence even when the probe (which may exercise
  a different code path) still passes.

Evidence is per-tenant but verdicts are per-chip: a signal folds onto
every chip of the tenant's allocation (the ring doesn't say which chip
errored; the ladder's confidence decay and the probe's per-chip verdict
do the narrowing). Multiple residents OR together — one stalled tenant
among healthy ones keeps the signal asserted, because the healthy ones
prove nothing about the stalled one's chips beyond what the probe says.
"""

from __future__ import annotations

import os

from vtpu_manager.telemetry import stepring
from vtpu_manager.util import consts

# a ring must sit still this long before it counts as stalled — several
# multiples of any sane step time, far under the ladder's SIGNAL_TTL_S
STALL_AFTER_S = 20.0

# trailing exec-error records before the streak asserts
EXEC_STREAK_N = 3


def exec_error_streak(records) -> int:
    """Length of the trailing run of exec-error records."""
    streak = 0
    for rec in reversed(records):
        if not rec.exec_error:
            break
        streak += 1
    return streak


class StallTracker:
    """Per-ring progress memory across evidence passes. ``observe``
    returns the stall verdict: True (stalled past the budget), False
    (progressing — retracts the signal), None (no verdict: never
    stepped, or sitting still but inside the budget)."""

    def __init__(self, stall_after_s: float = STALL_AFTER_S):
        self.stall_after_s = stall_after_s
        # key -> (last writes head, ts of last observed advance)
        self._seen: dict[str, tuple[int, float]] = {}

    def observe(self, key: str, writes: int, now: float) -> bool | None:
        last = self._seen.get(key)
        if last is None or writes != last[0]:
            self._seen[key] = (writes, now)
            return False if writes > 0 and last is not None else None
        if writes == 0:
            return None             # never stepped: startup, not stall
        if now - last[1] >= self.stall_after_s:
            return True
        return None

    def forget(self, key: str) -> None:
        self._seen.pop(key, None)


def collect_ring_evidence(base_dir: str, tracker: StallTracker,
                          now: float,
                          streak_n: int = EXEC_STREAK_N) -> dict:
    """One pass over the node's tenant partitions: chip index ->
    {"stall": bool, "exec": bool} for every chip with at least one
    resident ring (chips with no residents contribute nothing — the
    probe is their only witness). Unreadable rings/configs are skipped,
    the reader-side crash-window rule."""
    from vtpu_manager.config import tenantdirs
    evidence: dict[int, dict[str, bool]] = {}
    for pod_uid, label, cfg, _is_dra, _mtime in \
            tenantdirs.iter_container_configs(base_dir):
        if not cfg.devices:
            continue
        container = label.partition("/")[0]
        ring_path = os.path.join(base_dir, f"{pod_uid}_{container}",
                                 consts.TELEMETRY_SUBDIR,
                                 consts.STEP_RING_NAME)
        if not os.path.isfile(ring_path):
            continue
        try:
            reader = stepring.StepRingReader(ring_path)
        except (OSError, ValueError):
            continue
        try:
            writes = reader.head() or 0
            records, _, _ = reader.poll(max(0, writes - 16))
        finally:
            reader.close()
        stalled = tracker.observe(f"{pod_uid}/{label}", writes, now)
        erroring = exec_error_streak(records) >= streak_n
        for dev in cfg.devices:
            got = evidence.setdefault(dev.host_index,
                                      {"stall": False, "exec": False})
            if stalled is True:
                got["stall"] = True
            got["exec"] = got["exec"] or erroring
    return evidence
