"""Node chip-health annotation: vtheal's cordon edge into the scheduler.

Same codec family as the vttel pressure / vtuse headroom / vtovc
overcommit / vtici link-load annotations — parse-cheap on purpose (the
snapshot path decodes it per node event, the TTL path per visited
candidate), staleness explicit by timestamp:

    "<chip>:<state>:<conf>;...|L<x>.<y>.<z>.<axis>:failed;...@<wall_ts>"

one ``;``-separated segment per NON-HEALTHY chip (healthy chips are
omitted — an empty body is a clean bill of health), state the debounced
output of the suspect -> degraded -> failed ladder (ladder.py) and
``conf`` its 0-1 confidence; failed ICI link edges (links.py LinkId,
probe-confirmed dead neighbors) ride after the ``|``. The timestamp
makes staleness explicit — a publisher that goes dark must decay to
"no signal", which here means the cordon LIFTS: the scheduler never
keeps rejecting capacity on a dead publisher's last claim. That decay
direction is safe because the legacy registry ``healthy`` flip
(manager.HealthWatcher re-advertising the chip) is the non-decaying
backstop for a truly dead chip.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from vtpu_manager.util import stalecodec

# ladder vocabulary (wire + metrics label values). HEALTHY never
# appears on the wire — absence IS the healthy encoding.
HEALTHY = "healthy"
SUSPECT = "suspect"
DEGRADED = "degraded"
FAILED = "failed"
STATES = (HEALTHY, SUSPECT, DEGRADED, FAILED)

# the hard-gate subset: suspect chips schedule normally (a wedged
# tenant must not cordon its neighbors' capacity), degraded/failed
# chips are excluded like exhausted capacity
CORDON_STATES = frozenset({DEGRADED, FAILED})

# staleness family constant (pressure/headroom/overcommit/link-load)
MAX_HEALTH_AGE_S = 120.0

# defensive parse bounds, the linkload values: 64 chips + 192 torus
# links fit with headroom, the length cap bounds adversarial split cost
MAX_HEALTH_SEGMENTS = 256
MAX_HEALTH_LEN = 6144


@dataclass(frozen=True)
class NodeChipHealth:
    """Decoded per-node chip/link health rollup."""

    chips: dict = field(default_factory=dict)   # index -> (state, conf)
    links: frozenset = frozenset()              # failed LinkIds
    ts: float = 0.0

    def encode(self) -> str:
        segs = []
        for index, (state, conf) in sorted(self.chips.items()):
            if state == HEALTHY:
                continue
            segs.append(f"{index}:{state}:{min(max(conf, 0.0), 1.0):.2f}")
            if len(segs) >= MAX_HEALTH_SEGMENTS:
                break
        body = ";".join(segs)
        if self.links:
            lsegs = [f"L{c[0]}.{c[1]}.{c[2]}.{axis}:failed"
                     for (c, axis) in sorted(self.links)]
            body += "|" + ";".join(lsegs[:MAX_HEALTH_SEGMENTS])
        return stalecodec.stamp(body, self.ts)


def _parse_chip_seg(seg: str, out: dict) -> bool:
    parts = seg.split(":")
    if len(parts) != 3:
        return False
    try:
        index = int(parts[0])
        conf = float(parts[2])
    except (TypeError, ValueError):
        return False
    if index < 0 or parts[1] not in STATES or not math.isfinite(conf):
        # NaN confidence parses but poisons every comparison downstream
        # — the garbage-means-no-signal rule of the whole codec family
        return False
    out[index] = (parts[1], min(max(conf, 0.0), 1.0))
    return True


def _parse_link_seg(seg: str, out: set) -> bool:
    key, _, verdict = seg.partition(":")
    if verdict != "failed" or not key.startswith("L"):
        return False
    parts = key[1:].split(".")
    if len(parts) != 4:
        return False
    try:
        x, y, z, axis = (int(parts[0]), int(parts[1]),
                         int(parts[2]), int(parts[3]))
    except (TypeError, ValueError):
        return False
    if not 0 <= axis <= 2:
        return False
    out.add(((x, y, z), axis))
    return True


def parse_chip_health(raw: str | None, now: float | None = None,
                      max_age_s: float = MAX_HEALTH_AGE_S
                      ) -> NodeChipHealth | None:
    """Decode the annotation; None when absent, malformed, or stale —
    every bad shape degrades to no-signal (no cordon), never to a wrong
    health claim the scheduler would reject capacity on."""
    split = stalecodec.split_stamp(raw, max_len=MAX_HEALTH_LEN)
    if split is None:
        return None
    body, ts = split
    if not stalecodec.is_fresh(ts, now=now, max_age_s=max_age_s):
        return None
    chip_part, sep, link_part = body.partition("|")
    chips: dict = {}
    links: set = set()
    segments = 0
    for seg in chip_part.split(";"):
        if not seg:
            continue
        segments += 1
        if segments > MAX_HEALTH_SEGMENTS or \
                not _parse_chip_seg(seg, chips):
            return None
    if sep:
        for seg in link_part.split(";"):
            if not seg:
                continue
            segments += 1
            if segments > MAX_HEALTH_SEGMENTS or \
                    not _parse_link_seg(seg, links):
                return None
    return NodeChipHealth(chips=chips, links=frozenset(links), ts=ts)


def health_is_fresh(ch: "NodeChipHealth | None",
                    now: float | None = None) -> bool:
    """Use-time staleness verdict (the pressure-penalty rule): the
    snapshot path caches the parsed object on the NodeEntry and a dead
    publisher emits no further node events, so every consumer must
    re-judge freshness at the moment it gates on it."""
    if ch is None:
        return False
    return stalecodec.is_fresh(ch.ts, now=now,
                               max_age_s=MAX_HEALTH_AGE_S)


def cordon_mask(ch: "NodeChipHealth | None",
                now: float | None = None) -> frozenset:
    """Chip indices the hard admission gate must exclude — degraded or
    failed under a FRESH signal. Empty is the gate-off identity (no
    mask, byte-identical placement); a stale signal UN-cordons (see
    module docstring for why that direction is the safe one)."""
    if not health_is_fresh(ch, now):
        return frozenset()
    return frozenset(i for i, (state, _conf) in ch.chips.items()
                     if state in CORDON_STATES)


def failed_chips(ch: "NodeChipHealth | None",
                 now: float | None = None) -> frozenset:
    """The FAILED subset of the mask — what the rescue plane drains
    (degraded chips cordon new admissions but keep their residents)."""
    if not health_is_fresh(ch, now):
        return frozenset()
    return frozenset(i for i, (state, _conf) in ch.chips.items()
                     if state == FAILED)


def dead_links(ch: "NodeChipHealth | None",
               now: float | None = None) -> frozenset:
    """Failed LinkIds for submesh exclusion, or empty when the signal
    is absent/stale — same no-signal identity as the chip mask."""
    if not health_is_fresh(ch, now):
        return frozenset()
    return ch.links


def masked_registry(registry, mask: frozenset):
    """``registry`` with every chip in ``mask`` flipped unhealthy — the
    cordon's whole admission story: healthy_totals, fast_free_totals
    and the allocator's per-device UNHEALTHY rejection all key off
    ``ChipSpec.healthy``, so one masked view makes the hard gate exact
    in both scheduler paths with zero new per-chip logic.

    An empty mask returns ``registry`` itself (the no-signal identity),
    and masked views are memoized on the registry object keyed by mask
    — the overcommit ``virtual_registry`` discipline, so the TTL path's
    repeated visits to one snapshot cost one rebuild per distinct mask.
    """
    if not mask:
        return registry
    cache = getattr(registry, "_health_mask_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(registry, "_health_mask_cache", cache)
    got = cache.get(mask)
    if got is not None:
        return got
    import dataclasses
    chips = [dataclasses.replace(c, healthy=False)
             if c.index in mask else c for c in registry.chips]
    masked = type(registry)(chips=chips, mesh=registry.mesh,
                            mesh_domain=registry.mesh_domain)
    cache[mask] = masked
    return masked
