"""vtheal — the chip/link health plane (HealthPlane gate).

detect -> cordon -> rescue, closing the loop the reference closes for
GPUs with NVML XID/ECC watchers and DeviceTaints:

- **detect** (ladder.py, signals.py, publisher.py): the node folds the
  probe command, shim-side step-ring evidence (stall, exec-error
  streaks) and ICI link probes through a suspect -> degraded -> failed
  ladder with hysteresis + confidence decay, published as a stalecodec
  chip-health annotation (codec.py).
- **cordon** (codec.cordon_mask / masked_registry, consumed by both
  scheduler paths): degraded/failed chips become a HARD admission gate
  — capacity-shaped, audited as UnhealthyChip/DegradedLink — and
  select_submesh excludes boxes crossing failed chips/links.
- **rescue** (rescue.py + autopilot actions.rescue_gang): failed chips
  synthesize chip-failure verdicts the autopilot remediates through
  the PR 17 live-migration timeline under its existing guards, with
  bounded park-and-retry when no capacity exists.

Gate off = byte-identical everywhere: no annotation, no series, no
mask, no verdicts. The legacy manager.HealthWatcher whole-chip flip is
untouched either way — it is the non-decaying backstop this plane's
staleness-decays-to-no-cordon rule leans on.
"""

from vtpu_manager.health import codec, ladder, metrics, rescue, signals
from vtpu_manager.health.codec import (NodeChipHealth, cordon_mask,
                                       dead_links, failed_chips,
                                       health_is_fresh, masked_registry,
                                       parse_chip_health)
from vtpu_manager.health.ladder import ChipLadder, NodeHealthLadder
from vtpu_manager.health.publisher import ChipHealthPublisher
from vtpu_manager.health.rescue import (chip_failure_verdicts,
                                        rescue_verdicts,
                                        unhealthy_nodes)
from vtpu_manager.health.signals import StallTracker, \
    collect_ring_evidence

__all__ = [
    "ChipHealthPublisher", "ChipLadder", "NodeChipHealth",
    "NodeHealthLadder", "StallTracker", "chip_failure_verdicts",
    "codec", "collect_ring_evidence", "cordon_mask", "dead_links",
    "failed_chips", "health_is_fresh", "ladder", "masked_registry",
    "metrics", "parse_chip_health", "rescue", "rescue_verdicts",
    "signals", "unhealthy_nodes",
]
