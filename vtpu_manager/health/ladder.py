"""The suspect -> degraded -> failed ladder: vtheal's debouncer.

One chip's health verdict folds MULTIPLE independent evidence streams
(signals.py collects them, the publisher feeds them in):

    probe   the node's --health-probe-cmd verdict for the chip — the
            strongest single signal (it asks the hardware directly)
    stall   a resident tenant's step ring stopped advancing — alone
            this is a WEDGED TENANT, not a dead chip (the whole reason
            a single signal must not cordon); corroborated by a bad
            probe it's the classic dead-chip shape
    exec    an Execute-error streak in a resident ring (the shim-side
            FLAG_EXEC_ERROR evidence, stepring v4 flag bit)
    link    a probe-confirmed dead neighbor link touching the chip

Each observation carries a per-signal weight and decays linearly to
zero over SIGNAL_TTL_S (vtuse-style confidence decay: evidence is a
claim about NOW, not a latched fault). The chip's confidence is the
capped sum; thresholds map it to the ladder state. The weights are
chosen so no single signal reaches the cordon bar (stall alone =
suspect forever) while probe + any corroboration clears FAILED.

Hysteresis, both directions: stepping INTO the cordon set
(degraded/failed) must persist ESCALATE_FOLDS consecutive folds —
one noisy tick is a spike, two is a pattern (the autopilot's
HYSTERESIS_EPISODES discipline) — and stepping DOWN must persist
RECOVER_FOLDS, so a flapping chip doesn't whipsaw the scheduler's
admission gate or the rescue plane.

Links are simpler — there is no wedged-tenant ambiguity on an edge:
LINK_FAIL_PROBES consecutive probe-confirmed failures mark the edge
failed, LINK_CLEAR_PROBES consecutive healthy probes clear it.
"""

from __future__ import annotations

import time

from vtpu_manager.health import codec

# evidence weights: calibrated against the thresholds below so that
# stall alone < DEGRADED_AT (wedged tenant never cordons), probe alone
# crosses DEGRADED_AT (the hardware's own word is enough to stop NEW
# admissions), and probe + any second signal crosses FAILED_AT
# (corroborated dead chip -> drain the residents)
SIGNAL_WEIGHTS = {
    "probe": 0.60,
    "stall": 0.30,
    "exec": 0.35,
    "link": 0.45,
}

# evidence half-life: an observation's contribution decays linearly to
# zero over this window; a signal that stops re-asserting ages out and
# the ladder steps back down through the recovery hysteresis
SIGNAL_TTL_S = 60.0

# confidence -> state thresholds
SUSPECT_AT = 0.25
DEGRADED_AT = 0.55
FAILED_AT = 0.80

# fold-count hysteresis (see module docstring)
ESCALATE_FOLDS = 2
RECOVER_FOLDS = 3

# link edge debounce
LINK_FAIL_PROBES = 2
LINK_CLEAR_PROBES = 2

_RANK = {codec.HEALTHY: 0, codec.SUSPECT: 1,
         codec.DEGRADED: 2, codec.FAILED: 3}


def state_for(confidence: float) -> str:
    if confidence >= FAILED_AT:
        return codec.FAILED
    if confidence >= DEGRADED_AT:
        return codec.DEGRADED
    if confidence >= SUSPECT_AT:
        return codec.SUSPECT
    return codec.HEALTHY


class ChipLadder:
    """Per-chip evidence fold + debounced state."""

    __slots__ = ("state", "_evidence", "_pending", "_pending_folds")

    def __init__(self):
        self.state = codec.HEALTHY
        self._evidence: dict[str, float] = {}   # signal -> last bad ts
        self._pending: str | None = None
        self._pending_folds = 0

    def observe(self, signal: str, bad: bool, now: float) -> None:
        """Record one evidence sample. A healthy sample RETRACTS the
        signal immediately (the decay window is for signals that go
        silent, not ones that answer 'fine')."""
        if signal not in SIGNAL_WEIGHTS:
            raise ValueError(f"unknown health signal {signal!r}")
        if bad:
            self._evidence[signal] = now
        else:
            self._evidence.pop(signal, None)

    def confidence(self, now: float) -> float:
        total = 0.0
        for signal, ts in self._evidence.items():
            age = now - ts
            if age < 0 or age >= SIGNAL_TTL_S:
                continue
            total += SIGNAL_WEIGHTS[signal] * (1.0 - age / SIGNAL_TTL_S)
        return min(total, 1.0)

    def active_signals(self, now: float) -> tuple[str, ...]:
        return tuple(sorted(
            s for s, ts in self._evidence.items()
            if 0 <= now - ts < SIGNAL_TTL_S))

    def fold(self, now: float) -> str:
        """One debounce step: judge the evidence, apply the fold-count
        hysteresis, return the (possibly unchanged) state."""
        target = state_for(self.confidence(now))
        if target == self.state:
            self._pending, self._pending_folds = None, 0
            return self.state
        if target != self._pending:
            self._pending, self._pending_folds = target, 0
        self._pending_folds += 1
        escalating = _RANK[target] > _RANK[self.state]
        if escalating and target not in codec.CORDON_STATES:
            # suspect is advisory (no cordon) — flag it immediately so
            # the annotation carries early warning without debounce lag
            need = 1
        elif escalating:
            need = ESCALATE_FOLDS
        else:
            need = RECOVER_FOLDS
        if self._pending_folds >= need:
            self.state = target
            self._pending, self._pending_folds = None, 0
        return self.state


class NodeHealthLadder:
    """All of one node's chip ladders + link edge debounce; ``fold()``
    produces the codec object the publisher stamps, and records the
    state flips the flip failpoint/metrics fire on."""

    def __init__(self, clock=time.time):
        self.clock = clock
        self.chips: dict[int, ChipLadder] = {}
        # LinkId -> [bad_streak, good_streak, failed]
        self._links: dict = {}
        self.last_flips: list[tuple] = []   # (subject, old, new)

    def chip(self, index: int) -> ChipLadder:
        got = self.chips.get(index)
        if got is None:
            got = self.chips[index] = ChipLadder()
        return got

    def observe_chip(self, index: int, signal: str, bad: bool,
                     now: float | None = None) -> None:
        now = self.clock() if now is None else now
        self.chip(index).observe(signal, bad, now)

    def observe_link(self, link, bad: bool) -> None:
        streaks = self._links.setdefault(link, [0, 0, False])
        if bad:
            streaks[0] += 1
            streaks[1] = 0
            if streaks[0] >= LINK_FAIL_PROBES:
                streaks[2] = True
        else:
            streaks[1] += 1
            streaks[0] = 0
            if streaks[1] >= LINK_CLEAR_PROBES:
                streaks[2] = False

    def failed_links(self) -> frozenset:
        return frozenset(l for l, s in self._links.items() if s[2])

    def fold(self, now: float | None = None) -> codec.NodeChipHealth:
        now = self.clock() if now is None else now
        self.last_flips = []
        chips: dict = {}
        for index, ladder in sorted(self.chips.items()):
            old = ladder.state
            new = ladder.fold(now)
            if new != old:
                self.last_flips.append((index, old, new))
            if new != codec.HEALTHY:
                chips[index] = (new, round(ladder.confidence(now), 2))
        return codec.NodeChipHealth(chips=chips,
                                    links=self.failed_links(), ts=now)
