"""vtcs: cluster compile-artifact seeding — the fleet tier over vtcc.

vtcc (compilecache/) makes a NODE compile once; the cluster still
compiles once *per node*: an autoscaling burst that adds N nodes pays N
full XLA compiles of the same program fingerprint. This package closes
that gap with three pieces, all riding channels that already exist:

- ``advertise`` — each device-plugin publishes a compact node
  annotation of its hottest verified cache entries (bounded,
  LRU-ordered hottest-first, the pressure/headroom staleness-codec
  family) and fans every OTHER node's advertisement into a
  ``peers.json`` under the cache root, so in-container fetchers
  resolve warm peers without a kube client — warmth is visible
  cluster-wide with **no new control channel**.
- ``fetch`` — the node cache's ``get_or_compile`` miss path grows a
  fetch arm (``ClusterCompileCache``): under the existing born-flock'd
  single-flight lease (one fetcher per node per key; waiters reuse
  it), download the checksummed entry from an advertising peer's
  monitor (``/cache/entry?key=``), re-verify the 24B header before the
  atomic tmp+fsync+rename ``put``, and **fall open to a real compile**
  on any failure shape — peer gone, torn payload, timeout budget
  exceeded — via per-peer circuit breakers (the PR 4 ``KubeResilience``
  discipline).
- warm-preference scheduling — the shared ``_allocate_node`` body adds
  a soft ``warm_term`` bonus for fingerprint-carrying pods on nodes
  advertising that fingerprint (both data paths; the snapshot keeps a
  copy-on-write fp→nodes index), recorded in the vtexplain candidate
  breakdown so spread-vs-warm is auditable.

Everything is behind the ``ClusterCompileCache`` gate (default off =
byte-identical: no annotation, no peers file, no ``/cache/entry``
route, zero fetch I/O, placement untouched in both scheduler modes).
Measured (scripts/bench_clustercache.py): fleet-wide compiles for one
shared fingerprint = 1 across the simulated fleet, cold-*node*
time-to-first-step at warm-node order.
"""

from vtpu_manager.clustercache.advertise import (  # noqa: F401
    CacheAdvertiser, NodeWarmKeys, parse_warm_keys, warm_term)
from vtpu_manager.clustercache.fetch import (  # noqa: F401
    ClusterCompileCache, FetchError, read_entry_for_serving)
