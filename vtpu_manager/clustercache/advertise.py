"""vtcs warm-keys advertisement: which entries a node can seed peers with.

Wire format (the pressure/headroom/overcommit parse-cheap family,
staleness explicit by timestamp)::

    "<endpoint>|<fp>=<entry_key>,<fp>=<entry_key>,...@<wall_ts>"

- ``endpoint`` — ``host:port`` of this node's device-monitor, whose
  auth-gated ``/cache/entry?key=`` route serves the raw checksummed
  entries (empty = scheduler-visible warmth only, peers cannot fetch);
- one ``fp=key`` pair per advertised entry, **hottest first** (LRU
  order by last use), bounded at :data:`MAX_AD_KEYS` so the annotation
  stays registry-channel sized no matter how big the store grows;
- ``fp`` is the sanitized program fingerprint (the scheduler's match
  unit — a pod annotation names a program, not an artifact), ``key``
  the full 64-hex content address (the fetcher's match unit — an
  artifact is only reusable when topology + runtime versions hash
  identically, and the peer must hold EXACTLY that key).

A stale advertisement must decay to no-signal: ``warm_term`` re-judges
the timestamp at score time (the snapshot caches the parsed object and
a dead advertiser emits no further node events), and the fetch side
re-checks it before trusting the peers file. Garbage — unparseable
body, bad timestamp — reads as None; an individually malformed pair is
skipped (one corrupt segment must not blind the scheduler to the rest).

The fingerprint→key join the advertisement needs is recorded by the
cluster cache client at ``get_or_compile`` time as tiny marker files
under ``<root>/fps/`` (``fps/<fp>`` containing the entry key, mtime =
last use), so the advertiser scans markers, not payloads.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
from dataclasses import dataclass

from vtpu_manager.compilecache.keys import sanitize_fingerprint
from vtpu_manager.resilience import failpoints
from vtpu_manager.util import consts, stalecodec

log = logging.getLogger(__name__)

# staleness family constants (pressure/headroom/overcommit values)
MAX_AD_AGE_S = 120.0
FUTURE_SKEW_TOLERANCE_S = stalecodec.FUTURE_SKEW_TOLERANCE_S

# bound on advertised pairs: the annotation must stay registry-channel
# sized; 8 hottest keys cover a node's live program set (a node serves
# a handful of models, not its whole LRU history). Operators with
# wider program sets may raise it per node via --cache-ad-max-pairs,
# bounded at MAX_AD_KEYS_LIMIT — the cap review's hard ceiling, chosen
# so the WORST-CASE encoding (max-length fingerprints, full endpoint)
# still fits the 8 KiB registry-channel budget with headroom
# (test_ici.py asserts this red-on-overflow, so the ceiling cannot
# silently outgrow the budget).
MAX_AD_KEYS = 8
MAX_AD_KEYS_LIMIT = 32

# the registry-channel budget one advertisement may occupy: node
# annotations share the object's 256 KiB ceiling with the registry /
# pressure / headroom / overcommit channels, so each advertisement is
# held to 8 KiB
AD_BYTE_BUDGET = 8192

# defensive parse bound — an adversarial/corrupt annotation must not
# cost an unbounded split in the scheduler's event path. Equal to the
# byte budget: anything a compliant advertiser can publish parses.
MAX_AD_LEN = AD_BYTE_BUDGET

# scoring weight of the warm-preference bonus: enough to beat packing
# noise and a moderate anti-storm penalty (10/placement), below the
# pressure ceiling (50) and far below the +100 gang bonus — a gang
# stays on its slice, a stalling node still repels, but among otherwise
# comparable nodes the one holding the artifact wins.
WARM_SCORE_WEIGHT = 30.0

_KEY_RE = re.compile(r"^[0-9a-f]{64}$")

# how stale the advertiser-maintained peers file may be before the
# fetch side treats the fleet as unknown (covers an advertiser that
# died after its last fan-in; generous because every entry re-verifies)
PEERS_STALE_S = 300.0


def valid_entry_key(key: str) -> bool:
    """Whether ``key`` is a well-formed content address (64 lowercase
    hex). The serving route MUST check this — the key becomes a file
    name under entries/, and anything else is path traversal."""
    return bool(_KEY_RE.match(key or ""))


@dataclass(frozen=True)
class NodeWarmKeys:
    """Decoded warm-keys advertisement."""

    endpoint: str                       # "host:port" | "" (no fetch)
    pairs: tuple                        # ((fp, key), ...) hottest first
    ts: float

    @property
    def fps(self) -> frozenset:
        return frozenset(fp for fp, _k in self.pairs)

    @property
    def keys(self) -> frozenset:
        return frozenset(k for _fp, k in self.pairs)

    def encode(self) -> str:
        body = ",".join(f"{fp}={key}" for fp, key in self.pairs)
        return stalecodec.stamp(f"{self.endpoint}|{body}", self.ts)


def parse_warm_keys(raw: str | None, now: float | None = None,
                    max_age_s: float = MAX_AD_AGE_S
                    ) -> NodeWarmKeys | None:
    """Decode the annotation; None when absent, malformed, or stale —
    every bad shape degrades to no-signal, never to phantom warmth the
    scheduler would chase or the fetcher would dial."""
    split = stalecodec.split_stamp(raw, max_len=MAX_AD_LEN)
    if split is None:
        return None
    body, ts = split
    if not stalecodec.is_fresh(ts, now, max_age_s):
        return None
    endpoint, sep, pairs_raw = body.partition("|")
    if not sep:
        return None
    pairs = []
    for seg in pairs_raw.split(","):
        if not seg:
            continue
        fp, _, key = seg.partition("=")
        # a malformed pair is skipped, not fatal: one corrupt segment
        # must not blind consumers to the rest of the advertisement
        if not fp or fp != sanitize_fingerprint(fp) \
                or not valid_entry_key(key):
            continue
        pairs.append((fp, key))
        if len(pairs) >= MAX_AD_KEYS_LIMIT:
            # parse up to the hard ceiling, not the publisher DEFAULT:
            # a peer running --cache-ad-max-pairs above 8 must not have
            # its tail silently dropped by every consumer
            break
    return NodeWarmKeys(endpoint=endpoint, pairs=tuple(pairs), ts=ts)


def warm_is_fresh(warm: "NodeWarmKeys | None",
                  now: float | None = None) -> bool:
    if warm is None:
        return False
    return stalecodec.is_fresh(warm.ts, now, MAX_AD_AGE_S)


def warm_term(warm: "NodeWarmKeys | None", fingerprint: str,
              now: float | None = None) -> float:
    """Score points to ADD for one node already warm for the pod's
    program fingerprint. Soft like pressure/storm (reorders fits, never
    vetoes one), and staleness is re-judged HERE at score time — the
    snapshot path caches the parsed advertisement on the NodeEntry and
    a dead advertiser emits no further node events, so without a
    use-time check phantom warmth would attract pods forever."""
    if not fingerprint or not warm_is_fresh(warm, now):
        return 0.0
    return WARM_SCORE_WEIGHT if fingerprint in warm.fps else 0.0


# ---------------------------------------------------------------------------
# fingerprint markers: the fp -> entry-key join the advertiser scans
# ---------------------------------------------------------------------------

FPS_SUBDIR = "fps"


def record_fingerprint(root: str, fingerprint: str, key: str) -> None:
    """Land/refresh one ``fps/<fp>`` marker (content = entry key,
    mtime = last use). Atomic tmp+rename like every other store write;
    best-effort — the marker is advertisement metadata, and a full
    disk must cost fleet seeding, never the tenant's compile."""
    fp = sanitize_fingerprint(fingerprint)
    if not fp or not valid_entry_key(key):
        return
    fps_dir = os.path.join(root, FPS_SUBDIR)
    path = os.path.join(fps_dir, fp)
    try:
        try:
            with open(path) as f:
                if f.read() == key:
                    os.utime(path)      # refresh the LRU signal only
                    return
        except OSError:
            pass
        os.makedirs(fps_dir, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(key)
        os.rename(tmp, path)
    except OSError:
        log.debug("fingerprint marker write failed for %s", fp,
                  exc_info=True)


def scan_warm_pairs(root: str, max_keys: int = MAX_AD_KEYS) -> list:
    """((fp, key), ...) hottest-first from the marker dir, advertising
    only keys whose entry actually exists and is at least header-sized
    — a marker whose entry was evicted (or torn down to a stub) must
    not draw fetches that can only 404."""
    fps_dir = os.path.join(root, FPS_SUBDIR)
    entries_dir = os.path.join(root, "entries")
    rows = []
    try:
        names = os.listdir(fps_dir)
    except OSError:
        return []
    for name in names:
        if name.endswith(".tmp"):
            continue
        fp = sanitize_fingerprint(name)
        if fp != name:
            continue
        path = os.path.join(fps_dir, name)
        try:
            mtime = os.stat(path).st_mtime
            with open(path) as f:
                key = f.read().strip()
        except OSError:
            continue
        if not valid_entry_key(key):
            continue
        try:
            if os.stat(os.path.join(entries_dir, key)).st_size < 24:
                continue
        except OSError:
            continue
        rows.append((mtime, fp, key))
    rows.sort(reverse=True)
    return [(fp, key) for _m, fp, key in rows[:max_keys]]


# ---------------------------------------------------------------------------
# advertiser daemon (device-plugin side: the node-annotation owner)
# ---------------------------------------------------------------------------

class CacheAdvertiser:
    """Publish this node's warm keys and fan the fleet's in.

    Each tick: (1) scan the marker dir, encode the advertisement, patch
    the node annotation (the pressure-publisher discipline — failures
    tolerated per tick, the timestamp ages a silent death out);
    (2) LIST nodes over the client the daemon already holds, parse every
    OTHER node's advertisement, and materialize the result as
    ``peers.json`` under the cache root so in-container fetchers — which
    have the mount but no kube client — resolve peers from a file, the
    ``pids.config`` shape.
    """

    def __init__(self, client, node_name: str, cache_root: str,
                 endpoint: str = "", policy=None,
                 interval_s: float = 15.0,
                 max_keys: int = MAX_AD_KEYS):
        from vtpu_manager.resilience.policy import RetryPolicy
        self.client = client
        self.node_name = node_name
        self.cache_root = cache_root
        self.endpoint = endpoint
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            deadline_s=10.0)
        self.interval_s = interval_s
        # bounded at the hard ceiling so no flag value can push the
        # encoded advertisement past the registry-channel byte budget
        self.max_keys = max(1, min(max_keys, MAX_AD_KEYS_LIMIT))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def advertisement(self, now: float | None = None) -> NodeWarmKeys:
        now = time.time() if now is None else now
        return NodeWarmKeys(
            endpoint=self.endpoint,
            pairs=tuple(scan_warm_pairs(self.cache_root, self.max_keys)),
            ts=now)

    def publish_once(self) -> NodeWarmKeys:
        ad = self.advertisement()
        # chaos: a failed publish must decay peers to no-signal via the
        # annotation's own timestamp — never crash the daemon loop
        failpoints.fire("cache.advertise", node=self.node_name)
        self.policy.run(
            lambda: self.client.patch_node_annotations(
                self.node_name,
                {consts.node_cache_keys_annotation(): ad.encode()}),
            op="clustercache.advertise_patch")
        return ad

    # -- peer fan-in ---------------------------------------------------------

    def refresh_peers(self, now: float | None = None) -> int:
        """One LIST over the registry channel -> ``peers.json``. Returns
        peers written. The file carries its own timestamp so fetchers
        can judge ITS staleness independently of each embedded
        advertisement's (both are re-checked fetch-side)."""
        now = time.time() if now is None else now
        nodes = self.client.list_nodes()
        peers = []
        ann = consts.node_cache_keys_annotation()
        for node in nodes:
            meta = node.get("metadata") or {}
            name = meta.get("name", "")
            if not name or name == self.node_name:
                continue
            warm = parse_warm_keys(
                (meta.get("annotations") or {}).get(ann), now=now)
            if warm is None or not warm.endpoint or not warm.pairs:
                continue
            peers.append({"node": name, "endpoint": warm.endpoint,
                          "keys": {key: fp for fp, key in warm.pairs}})
        doc = {"ts": now, "peers": peers}
        path = os.path.join(self.cache_root, consts.CACHE_PEERS_NAME)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"))
        os.rename(tmp, path)
        return len(peers)

    def tick(self) -> None:
        self.publish_once()
        self.refresh_peers()

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — advisory plane: a
                    # failed tick costs freshness only, and both the
                    # annotation and peers.json carry timestamps that
                    # age silent failures out to no-signal
                    log.warning("cache advertisement tick failed",
                                exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtcs-advertiser")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


def read_peers(cache_root: str, now: float | None = None) -> list[dict]:
    """The fetch side's peer resolution: parse ``peers.json``, judge its
    staleness, return the peer rows. Any failure shape — absent file,
    torn JSON, stale fan-in — reads as "no peers" (the fetch arm then
    falls open to a compile, never to an error)."""
    path = os.path.join(cache_root, consts.CACHE_PEERS_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict):
        return []
    try:
        ts = float(doc.get("ts", 0.0))
    except (TypeError, ValueError):
        return []
    now = time.time() if now is None else now
    if not math.isfinite(ts) or \
            not -FUTURE_SKEW_TOLERANCE_S <= now - ts <= PEERS_STALE_S:
        return []
    peers = doc.get("peers")
    return peers if isinstance(peers, list) else []
