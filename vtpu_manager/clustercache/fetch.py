"""vtcs peer fetch: satisfy a compile-cache miss from a warm peer.

Both ends of the wire live here. The **serving** end
(:func:`read_entry_for_serving`) backs the device-monitor's auth-gated
``/cache/entry?key=`` route: a verified read of the raw checksummed
entry (24B header included, so the fetcher re-verifies end-to-end) that
quarantines corruption exactly like a local reader — the route can
never become a distribution channel for torn executables. The
**fetching** end (:class:`ClusterCompileCache`) plugs into the node
cache's single-flight miss path via the ``_fetch_remote`` hook:

1. resolve peers advertising EXACTLY this entry key from the
   advertiser-maintained ``peers.json`` (clustercache/advertise.py —
   the registry-channel fan-in materialized under the cache root, so
   in-container fetchers need no kube client);
2. download under the lease the caller already holds (one fetcher per
   node per key; waiters reuse whatever lands), each attempt bounded
   by its own timeout and the whole ladder by a total budget sized
   under the single-flight stale-lease window;
3. stage the payload to a temp file (the ``cache.fetch`` failpoint's
   partial-write tears it THERE — the torn-download state), read it
   back, and re-verify magic/length/checksum before returning it for
   the atomic ``put``;
4. fall open on every failure shape — peer gone, HTTP error, torn
   payload, budget exceeded — returning None so the caller compiles.
   Per-peer circuit breakers (the PR 4 discipline) stop a dead peer
   from taxing every subsequent miss with a connect timeout.
"""

from __future__ import annotations

import logging
import os
import secrets
import time
import urllib.error
import urllib.request

from vtpu_manager.clustercache import advertise
from vtpu_manager.compilecache.cache import CompileCache
from vtpu_manager.resilience import failpoints
from vtpu_manager.resilience.policy import CircuitBreaker
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

# per-attempt and whole-ladder budgets: the ladder must resolve (fetch
# or give up) well inside the single-flight stale-lease window (300 s)
# so waiters never judge a live fetcher dead mid-download
FETCH_TIMEOUT_S = 10.0
FETCH_TOTAL_BUDGET_S = 30.0
MAX_PEERS_TRIED = 3

# an executable entry larger than this is not one of ours — bound the
# download so a confused/malicious peer cannot balloon tenant memory
MAX_FETCH_BYTES = 1 << 30


class FetchError(Exception):
    """One peer attempt failed (transport, HTTP status, oversize)."""


def fetch_entry(endpoint: str, key: str,
                timeout_s: float = FETCH_TIMEOUT_S,
                token: str | None = None) -> bytes:
    """Download one raw entry (header + payload) from a peer monitor.
    Raises FetchError on any failure; the caller's ladder decides what
    that costs (never more than falling open to a compile)."""
    url = f"http://{endpoint}/cache/entry?key={key}"
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            raw = resp.read(MAX_FETCH_BYTES + 1)
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise FetchError(f"peer {endpoint} fetch failed: {e}") from e
    if len(raw) > MAX_FETCH_BYTES:
        raise FetchError(f"peer {endpoint} entry exceeds "
                         f"{MAX_FETCH_BYTES} bytes")
    return raw


def read_entry_for_serving(root: str, key: str) -> bytes | None:
    """The monitor route's read: raw verified entry bytes (header
    included) or None (absent/corrupt — corrupt is quarantined by the
    one-racer rename, same as a local reader). Never the scrape path;
    never serves bytes that fail the checksum."""
    if not advertise.valid_entry_key(key):
        return None
    path = os.path.join(root, "entries", key)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    if CompileCache._verify(key, raw) is None:
        dst = os.path.join(root, "quarantine", f"{key}.{time.time_ns()}")
        try:
            os.rename(path, dst)
            log.error("compile cache entry %s failed verification at "
                      "serve time; quarantined to %s", key, dst)
        except OSError:
            pass
        return None
    return raw


class ClusterCompileCache(CompileCache):
    """The node cache plus the peer-fetch miss arm and the fingerprint
    markers the advertiser scans. Construction cost over the base class
    is nil (the marker dir is made lazily on first record); with no
    peers file present the fetch arm is one failed open() per miss
    (and misses are compile-scale rare)."""

    def __init__(self, root: str, token: str | None = None,
                 fetch_timeout_s: float = FETCH_TIMEOUT_S,
                 total_budget_s: float = FETCH_TOTAL_BUDGET_S,
                 **kwargs):
        super().__init__(root, **kwargs)
        self.token = token if token is not None else \
            os.environ.get(consts.ENV_CACHE_PEER_TOKEN) or None
        self.fetch_timeout_s = fetch_timeout_s
        self.total_budget_s = total_budget_s
        # per-endpoint breakers: a dead peer must stop costing connect
        # timeouts after a few misses, and recover by probe
        self._breakers: dict[str, CircuitBreaker] = {}

    # -- fingerprint markers -------------------------------------------------

    def get_or_compile(self, key: str, compile_fn,
                       timeout_s: float = 600.0, ctx=None,
                       fingerprint: str = "") -> tuple[bytes, str]:
        payload, outcome = super().get_or_compile(
            key, compile_fn, timeout_s=timeout_s, ctx=ctx)
        if fingerprint and outcome != "timeout":
            # the marker records "this node can seed <fp> via <key>" —
            # a timeout outcome landed nothing, so it advertises nothing
            advertise.record_fingerprint(self.root, fingerprint, key)
        return payload, outcome

    # -- the fetch arm (runs under the population lease) ---------------------

    def _peer_endpoints(self, key: str) -> list[tuple[str, str]]:
        """(node, endpoint) rows advertising exactly this entry key,
        in the advertiser's fan-in order."""
        out = []
        for peer in advertise.read_peers(self.root):
            if not isinstance(peer, dict):
                continue
            keys = peer.get("keys")
            endpoint = peer.get("endpoint", "")
            if endpoint and isinstance(keys, dict) and key in keys:
                out.append((peer.get("node", ""), endpoint))
        return out

    def _breaker(self, endpoint: str) -> CircuitBreaker:
        breaker = self._breakers.get(endpoint)
        if breaker is None:
            breaker = CircuitBreaker(name=f"cache.fetch[{endpoint}]",
                                     failure_threshold=3,
                                     reset_timeout_s=30.0)
            self._breakers[endpoint] = breaker
        return breaker

    def _fetch_remote(self, key: str) -> bytes | None:
        """Resolve peers, download, verify; None = compile locally.
        Every failure shape is absorbed HERE (counted, breaker-fed,
        logged) except CrashFailpoint — which, being process-death
        semantics, must leave the lease exactly as real death would."""
        peers = self._peer_endpoints(key)
        if not peers:
            return None
        deadline = time.monotonic() + self.total_budget_s
        tried = 0
        for node, endpoint in peers:
            if tried >= MAX_PEERS_TRIED or time.monotonic() >= deadline:
                break
            breaker = self._breaker(endpoint)
            if not breaker.allow():
                continue
            tried += 1
            attempt_s = min(self.fetch_timeout_s,
                            max(0.1, deadline - time.monotonic()))
            tmp = os.path.join(
                self.tmp_dir,
                f"{key}.fetch.{os.getpid()}.{secrets.token_hex(4)}")
            try:
                raw = fetch_entry(endpoint, key, timeout_s=attempt_s,
                                  token=self.token)
                # stage + read back: the partial-write failpoint tears
                # the staged bytes exactly where a dropped connection
                # would, and what we VERIFY is what we later put()
                with open(tmp, "wb") as f:
                    f.write(raw)
                failpoints.fire("cache.fetch", key=key, path=tmp,
                                peer=node)
                with open(tmp, "rb") as f:
                    staged = f.read()
                payload = self._verify(key, staged)
                if payload is None:
                    raise FetchError(
                        f"peer {endpoint} served a torn/corrupt entry "
                        f"({len(staged)} bytes)")
            except Exception as e:  # noqa: BLE001 — the ladder's whole
                # contract: ANY failure shape (transport, injected
                # error, torn payload) costs one rung, never the tenant
                # — CrashFailpoint is a BaseException and deliberately
                # NOT caught here: it propagates like real process
                # death, leaving the lease AND the torn staging file
                # exactly as a killed fetcher would (the evictor reaps
                # the temp; a waiter takes the lease over)
                breaker.record_failure()
                self.stats.peer_fetch_failures += 1
                self._flush_stats()
                log.warning("peer fetch of %s from %s failed: %s",
                            key[:16], endpoint, e)
                self._unlink_quiet(tmp)
                continue
            self._unlink_quiet(tmp)
            breaker.record_success()
            self.stats.peer_fetches += 1
            self._flush_stats()
            log.info("compile cache entry %s seeded from peer %s (%s)",
                     key[:16], node, endpoint)
            return payload
        return None

    @staticmethod
    def _unlink_quiet(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass
