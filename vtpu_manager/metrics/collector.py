"""Prometheus metrics collector for the node monitor.

Reference: pkg/metrics/collector/node_gpu.go:77-972 (~25 gauges: physical
device memory/util/health, node vTPU totals/assigned, per-vTPU assignment,
shared-container counts, per-container limits/usage) and
metrics/lister/container_lister.go (container <-> pod mapping).

The per-container usage source is the per-container vtpu.config (mmap'd by
the reference; plainly read here — the files are tiny) joined with the vmem
ledger and the tc_util feed, all node-local.
"""

from __future__ import annotations

import logging
import os
import time

import vtpu_manager
from vtpu_manager.client import pod_resources
from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.config.tc_watcher import TcUtilFile
from vtpu_manager.config.vmem import VmemLedger, fnv64
from vtpu_manager.device.types import ChipSpec
from vtpu_manager.deviceplugin import checkpoint as ckpt
from vtpu_manager.telemetry import TenantStepTelemetry
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

# a tenant's config dir younger than this is unjudgeable rather than a
# mismatch: the kubelet checkpoint write can lag the allocation, so a
# just-started tenant judged against even a FRESH view would publish a
# transient mismatch=1 (ADVICE r4). Env-tunable for tests/operators.
STARTUP_GRACE_S = float(os.environ.get("VTPU_MAP_STARTUP_GRACE_S", "60"))


def _age_seconds(ts_monotonic_ns: int, now_ns: int | None = None) -> float:
    """Age of a monotonic-clock timestamp; negative deltas (pre-reboot
    stamps) read as very stale, not fresh."""
    now = time.monotonic_ns() if now_ns is None else now_ns
    delta = now - ts_monotonic_ns
    return delta / 1e9 if delta >= 0 else float("inf")


class Gauge:
    def __init__(self, name: str, help_text: str, labels: tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.labels = labels
        self.samples: list[tuple[tuple[str, ...], float]] = []

    def set(self, label_values: tuple[str, ...], value: float) -> None:
        self.samples.append((label_values, value))

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        for values, value in self.samples:
            label_str = ",".join(f'{k}="{v}"'
                                 for k, v in zip(self.labels, values))
            lines.append(f"{self.name}{{{label_str}}} {value}")
        return "\n".join(lines)


class NodeCollector:
    """Collects one scrape's worth of node + container gauges."""

    def __init__(self, node_name: str, chips: list[ChipSpec],
                 base_dir: str = consts.MANAGER_BASE_DIR,
                 tc_path: str = consts.TC_UTIL_CONFIG,
                 vmem_path: str = consts.VMEM_NODE_CONFIG,
                 pod_resources_socket: str | None = None,
                 kubelet_checkpoint: str | None = None,
                 utilization_enabled: bool = False,
                 overcommit_enabled: bool = False,
                 spill_dir: str = consts.SPILL_DIR,
                 comm_enabled: bool = False,
                 slo_enabled: bool = False,
                 quota_dir: str | None = None):
        self.node_name = node_name
        self.chips = chips
        self.base_dir = base_dir
        self.tc_path = tc_path
        self.vmem_path = vmem_path
        # container<->pod attribution cross-check endpoints (reference
        # pod_resources.go / container_lister.go: the kubelet, not our own
        # config-dir names, is the authority on which container holds
        # devices). None = use the well-known paths; tests point elsewhere.
        self.pod_resources_socket = (
            pod_resources.POD_RESOURCES_SOCKET
            if pod_resources_socket is None else pod_resources_socket)
        self.kubelet_checkpoint = (
            ckpt.KUBELET_CHECKPOINT
            if kubelet_checkpoint is None else kubelet_checkpoint)
        # peak concurrent tenancy per chip across this monitor's lifetime
        # (reference vGPUPeakSharedContainersNumber)
        self._peak_shared: dict[str, int] = {}
        # kubelet-view TTL cache (ADVICE r3): the List gRPC dials a fresh
        # channel with a 2 s call timeout, synchronously inside collect();
        # a wedged kubelet socket would add that to EVERY scrape. The
        # reference lister polls on its own cadence for the same reason.
        self._kubelet_view_cache: pod_resources.KubeletView | None = None
        self._kubelet_view_ts: float = -float("inf")
        self._kubelet_view_was_cached: bool = False
        self.kubelet_view_ttl_s = float(
            os.environ.get("VTPU_KUBELET_VIEW_TTL_S", "10"))
        # vttel: cursor-tailed step rings folded into cumulative per-pod
        # histograms across scrapes (the collector is the long-lived
        # state holder; the rings only remember RING_CAPACITY steps).
        # vtcomm (CommTelemetry gate): the same fold also accumulates
        # the v3 comm block into the vtpu_tenant_comm_* families; off
        # renders zero comm series — the gate-off contract.
        self.comm_enabled = comm_enabled
        self.telemetry = TenantStepTelemetry(base_dir, comm=comm_enabled)
        # self-observability: per-feed last-scrape-error flags (a wedged
        # config/ledger read must be visible, not silently-stale gauges)
        self._feed_errors: dict[str, float] = {
            "tc_util": 0.0, "vmem": 0.0, "telemetry": 0.0}
        self._last_scrape_s: float = 0.0
        # vtuse (UtilizationLedger gate; off = no ledger object, no new
        # series, no feed label — the gate-off contract): the scrape
        # folds the per-tenant utilization ledger under a time budget so
        # a node with hundreds of rings can never stall this path —
        # budget overruns drop ring folds (counted) and resume next
        # scrape round-robin
        self.util_ledger = None
        self.util_fold_budget_s = float(
            os.environ.get("VTPU_UTIL_FOLD_BUDGET_S", "0.25"))
        # vtovc (HBMOvercommit gate; off = no spill series at all): the
        # node spill signal folds off the SAME ledger's ring tail, so
        # enabling overcommit alone still builds one — the policy
        # engine's measurements and these series must share a fold.
        self.overcommit_enabled = overcommit_enabled
        self.spill_dir = spill_dir
        if utilization_enabled or overcommit_enabled:
            from vtpu_manager.utilization import UtilizationLedger
            self.util_ledger = UtilizationLedger(
                node_name, chips, base_dir=base_dir, tc_path=tc_path)
            if utilization_enabled:
                self._feed_errors["utilization"] = 0.0
        # gate-off contract: UtilizationLedger off must keep rendering
        # ZERO vtuse series even when the ledger object exists for the
        # overcommit fold
        self.utilization_enabled = utilization_enabled
        # vtslo (SLOAttribution gate; off = no ledger object, no
        # vtpu_tenant_goodput_*/vtpu_tenant_overhead_*/vtpu_slo_*
        # series, no history spools, no feed label — the gate-off
        # contract). On, every scrape folds the tenant rings through
        # the attribution plane with the SloLedger's OWN cursors (the
        # market-manager rule: the vtuse ledger's cursors are never
        # raced by a second consumer).
        self.slo_enabled = slo_enabled
        self.slo_ledger = None
        if slo_enabled:
            from vtpu_manager.slo import SloLedger
            self.slo_ledger = SloLedger(
                node_name, base_dir=base_dir,
                quota_dir=quota_dir or base_dir)
            self._feed_errors["slo"] = 0.0

    def _kubelet_view(self, force: bool = False
                      ) -> pod_resources.KubeletView:
        now = time.monotonic()
        if (force or self._kubelet_view_cache is None
                or now - self._kubelet_view_ts >= self.kubelet_view_ttl_s):
            self._kubelet_view_cache = pod_resources.kubelet_view(
                self.pod_resources_socket, self.kubelet_checkpoint)
            self._kubelet_view_ts = now
            self._kubelet_view_was_cached = False
        else:
            self._kubelet_view_was_cached = True
        return self._kubelet_view_cache

    def _container_configs(self) -> list[
            tuple[str, str, vc.VtpuConfig, bool, float]]:
        """(pod_uid_or_claim, container_label, config, is_dra,
        config_mtime — the tenant-age signal for the startup grace).
        One shared walk (config/tenantdirs.py): the vtuse ledger joins
        the same dirs through the same owner-token labeling, and the
        two must never drift."""
        from vtpu_manager.config.tenantdirs import iter_container_configs
        return list(iter_container_configs(self.base_dir))

    def collect(self) -> list[Gauge]:
        gauges: list[Gauge] = []
        chip_by_index = {c.index: c for c in self.chips}

        # ---- physical chip gauges (reference physical_gpu_device_*) ----
        g_mem_total = Gauge("vtpu_device_memory_total_bytes",
                            "Physical HBM per chip",
                            ("node", "uuid", "index"))
        g_mem_used = Gauge("vtpu_device_memory_used_bytes",
                           "HBM in use on the chip across all tenants",
                           ("node", "uuid", "index"))
        g_mem_util = Gauge("vtpu_device_memory_utilization_percent",
                           "Chip HBM utilization (0-100)",
                           ("node", "uuid", "index"))
        g_healthy = Gauge("vtpu_device_healthy",
                          "Chip health (1 healthy)",
                          ("node", "uuid", "index"))
        g_util = Gauge("vtpu_device_utilization_percent",
                       "Chip duty-cycle percent from the node watcher",
                       ("node", "uuid", "index"))
        g_slots_total = Gauge("vtpu_device_slots_total",
                              "Advertised vTPU slots per chip",
                              ("node", "uuid", "index"))
        g_feed_age = Gauge("vtpu_device_feed_age_seconds",
                           "Age of the node watcher's last sample for the "
                           "chip (staleness signal)",
                           ("node", "uuid", "index"))
        for chip in self.chips:
            labels = (self.node_name, chip.uuid, str(chip.index))
            g_mem_total.set(labels, float(chip.memory))
            g_healthy.set(labels, 1.0 if chip.healthy else 0.0)
            g_slots_total.set(labels, float(chip.split_count))
        gauges += [g_mem_total, g_healthy, g_slots_total]

        # node watcher feed: chip duty cycle + per-tenant/per-process
        # attributed shares (the watcher apportions by ledger submit-
        # activity deltas). Keyed per (tenant, chip): ProcUtil.util is
        # percent OF ONE CHIP — summing across chips would exceed 100.
        util_by_token: dict[tuple[int, int], int] = {}
        proc_utils: list[tuple[int, int, int, int]] = []  # token,chip,pid,%
        g_cal_max = Gauge("vtpu_node_obs_excess_max_us",
                          "Max point of the published transport "
                          "span-inflation excess table (absent = "
                          "uncalibrated; 0 = calibrated clean transport)",
                          ("node",))
        g_cal_age = Gauge("vtpu_node_obs_calibration_age_seconds",
                          "Age of the feed's calibration block",
                          ("node",))
        self._feed_errors["tc_util"] = 0.0
        try:
            tc = TcUtilFile(self.tc_path)
            cal_full = tc.read_calibration_full()
            if cal_full is not None:
                cal, cal_ts = cal_full
                g_cal_max.set((self.node_name,),
                              float(max(e for _, e in cal)))
                if cal_ts:
                    g_cal_age.set((self.node_name,), _age_seconds(cal_ts))
            for chip in self.chips:
                rec = tc.read_device(chip.index)
                if rec is not None:
                    labels = (self.node_name, chip.uuid, str(chip.index))
                    g_util.set(labels, float(rec.device_util))
                    if rec.timestamp_ns:
                        g_feed_age.set(labels,
                                       _age_seconds(rec.timestamp_ns))
                    for proc in rec.procs:
                        key = (proc.owner_token, chip.index)
                        util_by_token[key] = \
                            util_by_token.get(key, 0) + proc.util
                        proc_utils.append((proc.owner_token, chip.index,
                                           proc.pid, proc.util))
            tc.close()
        except (OSError, ValueError):
            # absent feed (no TCWatcher on this node) is normal; only a
            # file that EXISTS but cannot be read is a scrape error
            if os.path.exists(self.tc_path):
                self._feed_errors["tc_util"] = 1.0
        gauges += [g_util, g_feed_age, g_cal_max, g_cal_age]

        # ---- vmem ledger: usage + heartbeat ----
        vmem = None
        self._feed_errors["vmem"] = 0.0
        try:
            vmem = VmemLedger(self.vmem_path)
        except (OSError, ValueError):
            if os.path.exists(self.vmem_path):
                self._feed_errors["vmem"] = 1.0
        # per-(tenant, chip) attribution: ledger entries carry the owner
        # token (fnv64 of pod_uid/container) AND the chip, so co-tenants
        # are never conflated and a multi-chip container's rows stay
        # per-device (a token-only sum would double every uuid row)
        usage_by_token: dict[tuple[int, int], int] = {}
        used_by_chip: dict[int, int] = {}
        heartbeat_by_token: dict[int, int] = {}   # newest last_update_ns
        ledger_entries = []
        if vmem is not None:
            ledger_entries = list(vmem.entries())
            vmem.close()
        for entry in ledger_entries:
            key = (entry.owner_token, entry.host_index)
            usage_by_token[key] = usage_by_token.get(key, 0) + entry.bytes
            used_by_chip[entry.host_index] = \
                used_by_chip.get(entry.host_index, 0) + entry.bytes
            heartbeat_by_token[entry.owner_token] = max(
                heartbeat_by_token.get(entry.owner_token, 0),
                entry.last_update_ns)
        # every chip gets a row — an idle chip's explicit 0 keeps "no
        # usage" distinguishable from "exporter broken"
        for chip in self.chips:
            used = used_by_chip.get(chip.index, 0)
            labels = (self.node_name, chip.uuid, str(chip.index))
            g_mem_used.set(labels, float(used))
            if chip.memory:
                g_mem_util.set(labels,
                               round(100.0 * used / chip.memory, 2))
        gauges += [g_mem_used, g_mem_util]

        # ---- per-container assignment + usage ----
        g_climit = Gauge("vtpu_container_core_limit_percent",
                         "Assigned core percent",
                         ("node", "pod_uid", "container", "uuid"))
        g_mlimit = Gauge("vtpu_container_memory_limit_bytes",
                         "Assigned HBM cap (virtual: oversold claims may "
                         "sum past the chip)",
                         ("node", "pod_uid", "container", "uuid"))
        g_mplimit = Gauge("vtpu_container_memory_limit_physical_bytes",
                          "Assigned cap clamped to physical chip HBM",
                          ("node", "pod_uid", "container", "uuid"))
        g_musage = Gauge("vtpu_container_memory_used_bytes",
                         "HBM bytes recorded by the container's processes",
                         ("node", "pod_uid", "container", "uuid"))
        g_mem_pct = Gauge("vtpu_container_memory_utilization_percent",
                          "Used bytes over the container's cap (0-100)",
                          ("node", "pod_uid", "container", "uuid"))
        g_cutil = Gauge("vtpu_container_utilization_percent",
                        "Chip duty-cycle share attributed to the container",
                        ("node", "pod_uid", "container", "uuid"))
        g_heartbeat = Gauge("vtpu_container_heartbeat_age_seconds",
                            "Seconds since the container's processes last "
                            "touched the ledger (staleness signal)",
                            ("node", "pod_uid", "container"))
        g_assigned = Gauge("vtpu_device_assigned_containers",
                           "Containers sharing each chip",
                           ("node", "uuid"))
        g_peak = Gauge("vtpu_device_assigned_containers_peak",
                       "Peak concurrent containers per chip since monitor "
                       "start",
                       ("node", "uuid"))
        g_cores_total = Gauge("vtpu_device_cores_total_percent",
                              "Allocatable core budget per chip (100)",
                              ("node", "uuid", "index"))
        g_cores_assigned = Gauge("vtpu_device_cores_assigned_percent",
                                 "Sum of assigned core percents per chip",
                                 ("node", "uuid", "index"))
        g_dev_assigned_mem = Gauge(
            "vtpu_device_memory_assigned_bytes",
            "Sum of assigned caps per chip (virtual)",
            ("node", "uuid", "index"))
        g_dev_assigned_pmem = Gauge(
            "vtpu_device_memory_assigned_physical_bytes",
            "Sum of physically-clamped assigned caps per chip",
            ("node", "uuid", "index"))
        g_proc_mem = Gauge("vtpu_process_memory_used_bytes",
                           "Per-process HBM bytes from the ledger",
                           ("node", "pod_uid", "container", "uuid", "pid"))
        g_proc_util = Gauge("vtpu_process_utilization_percent",
                            "Per-process duty-cycle share from the feed",
                            ("node", "pod_uid", "container", "uuid", "pid"))
        g_map_mismatch = Gauge(
            "vtpu_container_pod_mapping_mismatch",
            "1 when the kubelet does not corroborate this config-dir's "
            "pod/container attribution (orphaned dir, spoofed name, or "
            "plugin/kubelet disagreement); 0 when corroborated. Rows "
            "appear only for device-plugin tenants while a kubelet "
            "source is reachable",
            ("node", "pod_uid", "container"))
        g_map_source = Gauge(
            "vtpu_node_pod_mapping_source",
            "Attribution cross-check source: 3=socket+checkpoint "
            "(pair-keyed, strongest), 2=pod-resources socket only, "
            "1=kubelet checkpoint only, 0=none reachable",
            ("node",))

        assigned: dict[str, int] = {}
        cores_assigned: dict[int, int] = {}
        mem_assigned: dict[int, int] = {}
        pmem_assigned: dict[int, int] = {}
        tenant_by_token: dict[int, tuple[str, str]] = {}
        now_ns = time.monotonic_ns()
        configs = self._container_configs()
        # dial the kubelet only when there is something it can judge: a
        # DRA-only node (or an empty one) must not pay a gRPC List (up to
        # 2 s) per scrape for a result every tenant would skip
        view = None

        def publish_source(v) -> None:
            g_map_source.set((self.node_name,),
                             {"podresources+checkpoint": 3.0,
                              "podresources": 2.0,
                              "checkpoint": 1.0}.get(v.source, 0.0))

        if any(not is_dra for _, _, _, is_dra, _ in configs):
            view = self._kubelet_view()
            publish_source(view)
        for pod_uid, container, cfg, is_dra, cfg_mtime in configs:
            # DRA tenants flow through the kubelet's DRA path, which the
            # device-plugin-era pod-resources v1alpha1 API does not
            # report — only device-plugin tenants are judgeable
            if not is_dra and view is not None:
                verdict = view.corroborates(pod_uid, container)
                if verdict is False and self._kubelet_view_was_cached:
                    # never alarm off a stale view: a tenant started
                    # after the cached fetch would read as a mismatch
                    # until the TTL expired — refetch once and re-judge
                    view = self._kubelet_view(force=True)
                    verdict = view.corroborates(pod_uid, container)
                    # the gauge must advertise the source the remaining
                    # judgments actually use (ADVICE r4: a forced
                    # refetch can come back from a different source,
                    # e.g. socket dropped to checkpoint-only)
                    publish_source(view)
                if verdict is False and (
                        time.time() - cfg_mtime < STARTUP_GRACE_S):
                    # just-allocated tenant: the checkpoint read can lag
                    # the allocation even on a FRESH view (ADVICE r4),
                    # so a config dir younger than the grace window is
                    # unjudgeable rather than a mismatch
                    verdict = None
                if verdict is not None:
                    g_map_mismatch.set(
                        (self.node_name, pod_uid, container),
                        0.0 if verdict else 1.0)
                    if not verdict:
                        log.warning(
                            "config dir %s_%s not corroborated by kubelet "
                            "%s view", pod_uid, container, view.source)
            token = fnv64(f"{pod_uid}/{container}")
            tenant_by_token[token] = (pod_uid, container)
            for dev in cfg.devices:
                labels = (self.node_name, pod_uid, container, dev.uuid)
                phys_cap = (min(dev.total_memory, dev.real_memory)
                            if dev.real_memory else dev.total_memory)
                used = usage_by_token.get((token, dev.host_index), 0)
                g_climit.set(labels, float(dev.hard_core))
                g_mlimit.set(labels, float(dev.total_memory))
                g_mplimit.set(labels, float(phys_cap))
                g_musage.set(labels, float(used))
                if dev.total_memory:
                    g_mem_pct.set(labels,
                                  round(100.0 * used / dev.total_memory, 2))
                g_cutil.set(labels, float(
                    util_by_token.get((token, dev.host_index), 0)))
                if dev.host_index not in chip_by_index:
                    # stale config naming a removed/undiscovered chip:
                    # keep the container row (it reflects on-disk truth)
                    # but keep it OUT of chip/node aggregates, else
                    # sum(per-device rows) != node totals
                    continue
                assigned[dev.uuid] = assigned.get(dev.uuid, 0) + 1
                cores_assigned[dev.host_index] = \
                    cores_assigned.get(dev.host_index, 0) + dev.hard_core
                mem_assigned[dev.host_index] = \
                    mem_assigned.get(dev.host_index, 0) + dev.total_memory
                pmem_assigned[dev.host_index] = \
                    pmem_assigned.get(dev.host_index, 0) + phys_cap
            ts = heartbeat_by_token.get(token)
            if ts:
                g_heartbeat.set((self.node_name, pod_uid, container),
                                round(_age_seconds(ts, now_ns), 3))

        for chip in self.chips:
            labels = (self.node_name, chip.uuid, str(chip.index))
            g_cores_total.set(labels, 100.0)
            g_cores_assigned.set(
                labels, float(cores_assigned.get(chip.index, 0)))
            g_dev_assigned_mem.set(
                labels, float(mem_assigned.get(chip.index, 0)))
            g_dev_assigned_pmem.set(
                labels, float(pmem_assigned.get(chip.index, 0)))
        for uuid, count in assigned.items():
            g_assigned.set((self.node_name, uuid), float(count))
            self._peak_shared[uuid] = max(self._peak_shared.get(uuid, 0),
                                          count)
        for uuid, peak in self._peak_shared.items():
            g_peak.set((self.node_name, uuid), float(peak))

        # per-process breakdown, attributed through the owner token; rows
        # whose token matches no live container config are skipped (stale
        # tenants are the reaper's business, not the scrape's)
        for entry in ledger_entries:
            tenant = tenant_by_token.get(entry.owner_token)
            chip = chip_by_index.get(entry.host_index)
            if tenant is None or chip is None:
                continue
            g_proc_mem.set((self.node_name, tenant[0], tenant[1],
                            chip.uuid, str(entry.pid)), float(entry.bytes))
        for token, index, pid, util in proc_utils:
            tenant = tenant_by_token.get(token)
            chip = chip_by_index.get(index)
            if tenant is None or chip is None:
                continue
            g_proc_util.set((self.node_name, tenant[0], tenant[1],
                             chip.uuid, str(pid)), float(util))

        gauges += [g_climit, g_mlimit, g_mplimit, g_musage, g_mem_pct,
                   g_cutil, g_heartbeat, g_assigned, g_peak, g_cores_total,
                   g_cores_assigned, g_dev_assigned_mem, g_dev_assigned_pmem,
                   g_proc_mem, g_proc_util, g_map_mismatch, g_map_source]

        # ---- node aggregates + info ----
        g_total = Gauge("vtpu_node_slots_total", "Node vTPU slot capacity",
                        ("node",))
        g_used = Gauge("vtpu_node_slots_assigned", "Assigned vTPU slots",
                       ("node",))
        g_node_mem = Gauge("vtpu_node_memory_total_bytes",
                           "Physical HBM across the node's chips", ("node",))
        g_node_assigned_mem = Gauge(
            "vtpu_node_memory_assigned_bytes",
            "Assigned caps across the node (virtual)", ("node",))
        g_node_assigned_pmem = Gauge(
            "vtpu_node_memory_assigned_physical_bytes",
            "Physically-clamped assigned caps across the node", ("node",))
        g_info = Gauge("vtpu_node_info",
                       "Static node/manager build info (value always 1)",
                       ("node", "version", "resource_domain",
                        "annotation_domain", "chips"))
        g_total.set((self.node_name,),
                    float(sum(c.split_count for c in self.chips)))
        g_used.set((self.node_name,), float(sum(assigned.values())))
        g_node_mem.set((self.node_name,),
                       float(sum(c.memory for c in self.chips)))
        g_node_assigned_mem.set((self.node_name,),
                                float(sum(mem_assigned.values())))
        g_node_assigned_pmem.set((self.node_name,),
                                 float(sum(pmem_assigned.values())))
        g_info.set((self.node_name, vtpu_manager.__version__,
                    consts.resource_domain(), consts.annotation_domain(),
                    str(len(self.chips))), 1.0)
        gauges += [g_total, g_used, g_node_mem, g_node_assigned_mem,
                   g_node_assigned_pmem, g_info]
        return gauges

    def render(self) -> str:
        t0 = time.perf_counter()
        text = "\n".join(g.render() for g in self.collect() if g.samples
                         or True) + "\n"
        # vttel: tail the step rings and append the per-pod histograms +
        # node pressure rollup. No rings (gate off / no tenants) renders
        # headers only — zero vttel series, matching the gate-off
        # contract, while the families stay discoverable.
        self._feed_errors["telemetry"] = 0.0
        try:
            if self.telemetry.scan():
                # rings that EXIST but won't read: their tenants' series
                # are being served stale — same posture as a wedged
                # tc_util/vmem file
                self._feed_errors["telemetry"] = 1.0
        except OSError:
            self._feed_errors["telemetry"] = 1.0
            log.warning("step-telemetry scan failed", exc_info=True)
        text += self.telemetry.render(self.node_name)
        text += self.telemetry.render_pressure(
            self.node_name, sum(c.memory for c in self.chips))
        # vtcc: node compile-cache counters (summed across every tenant
        # client's stats file + the dead-process aggregate) and the
        # entries/size gauges. Absent root (gate off) renders headers
        # only — zero series, matching the gate-off contract.
        from vtpu_manager.compilecache.cache import render_node_metrics
        text += render_node_metrics(
            os.path.join(self.base_dir, consts.COMPILE_CACHE_SUBDIR),
            self.node_name)
        # vtuse: the budgeted ledger fold + the utilization/headroom
        # series (gate on only — gate off has no ledger object and this
        # block is one None check). A failed or torn fold flags the
        # utilization feed error and keeps serving: the ledger's own
        # confidence decay is what prevents stale claims, never a
        # blocked scrape.
        if self.util_ledger is not None:
            if self.utilization_enabled:
                self._feed_errors["utilization"] = 0.0
            try:
                if self.util_ledger.fold(
                        budget_s=self.util_fold_budget_s) \
                        and self.utilization_enabled:
                    self._feed_errors["utilization"] = 1.0
            except Exception:  # noqa: BLE001 — any fold failure
                # (including an injected util.fold error) must cost the
                # feed flag, never the scrape
                if self.utilization_enabled:
                    self._feed_errors["utilization"] = 1.0
                log.warning("utilization ledger fold failed",
                            exc_info=True)
            if self.utilization_enabled:
                text += self.util_ledger.render()
        # vtovc: node spill series (HBMOvercommit on only — gate off
        # renders none of these families): the step rings' spill signal
        # plus the pool directory's ground truth, so thrash
        # (spill_frac), footprint (ring gauge vs pool bytes) and
        # lifetime churn (the counters) are all scrapeable.
        if self.overcommit_enabled and self.util_ledger is not None:
            from vtpu_manager.overcommit.spill import pool_totals
            frac, ring_bytes = self.util_ledger.node_spill_signal()
            pool_files, pool_bytes = pool_totals(self.spill_dir)
            lines = [
                "# HELP vtpu_node_spill_step_fraction Fraction of "
                "recent steps that paid a host-tier spill or fill",
                "# TYPE vtpu_node_spill_step_fraction gauge",
                f'vtpu_node_spill_step_fraction{{node="'
                f'{self.node_name}"}} {round(frac, 4):g}',
                "# HELP vtpu_node_spilled_bytes Live host-pool "
                "footprint reported by tenant step rings",
                "# TYPE vtpu_node_spilled_bytes gauge",
                f'vtpu_node_spilled_bytes{{node="{self.node_name}"}} '
                f"{ring_bytes}",
                "# HELP vtpu_node_spill_pool_bytes Bytes currently in "
                "the node's spill pool directory",
                "# TYPE vtpu_node_spill_pool_bytes gauge",
                f'vtpu_node_spill_pool_bytes{{node="{self.node_name}"}} '
                f"{pool_bytes}",
                "# HELP vtpu_node_spill_pool_files Files currently in "
                "the node's spill pool directory",
                "# TYPE vtpu_node_spill_pool_files gauge",
                f'vtpu_node_spill_pool_files{{node="{self.node_name}"}} '
                f"{pool_files}",
                "# HELP vtpu_node_spill_events_total HBM->host "
                "demotions observed across tenant step rings",
                "# TYPE vtpu_node_spill_events_total counter",
                f'vtpu_node_spill_events_total{{node="'
                f'{self.node_name}"}} '
                f"{self.util_ledger.spill_events_total}",
                "# HELP vtpu_node_fill_events_total host->HBM "
                "promotions observed across tenant step rings",
                "# TYPE vtpu_node_fill_events_total counter",
                f'vtpu_node_fill_events_total{{node="'
                f'{self.node_name}"}} '
                f"{self.util_ledger.fill_events_total}",
            ]
            text += "\n".join(lines) + "\n"
        # vtslo: the attribution fold + goodput/overhead/regression
        # series (SLOAttribution on only — gate off has no ledger
        # object and this block is one None check). A failed fold flags
        # the slo feed error and keeps serving; the detectors' own
        # staleness rule is what prevents stale claims.
        if self.slo_ledger is not None:
            self._feed_errors["slo"] = 0.0
            try:
                if self.slo_ledger.fold():
                    self._feed_errors["slo"] = 1.0
            except Exception:  # noqa: BLE001 — any fold failure must
                # cost the feed flag, never the scrape
                self._feed_errors["slo"] = 1.0
                log.warning("slo ledger fold failed", exc_info=True)
            text += self.slo_ledger.render()
        # self-observability: the scrape's own duration and per-feed
        # last-error flags, rendered last so a wedged feed still reports
        self._last_scrape_s = time.perf_counter() - t0
        g_dur = Gauge("vtpu_node_scrape_duration_seconds",
                      "Wall time of this collector scrape (gauges + "
                      "telemetry fold)", ("node",))
        g_dur.set((self.node_name,), round(self._last_scrape_s, 6))
        g_err = Gauge("vtpu_node_scrape_last_error",
                      "1 when the feed's last read failed (stale gauges "
                      "are being served)", ("node", "feed"))
        for feed in sorted(self._feed_errors):
            g_err.set((self.node_name, feed), self._feed_errors[feed])
        return text + g_dur.render() + "\n" + g_err.render() + "\n"
