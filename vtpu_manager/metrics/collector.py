"""Prometheus metrics collector for the node monitor.

Reference: pkg/metrics/collector/node_gpu.go:77-972 (~25 gauges: physical
device memory/util/health, node vTPU totals/assigned, per-vTPU assignment,
shared-container counts, per-container limits/usage) and
metrics/lister/container_lister.go (container <-> pod mapping).

The per-container usage source is the per-container vtpu.config (mmap'd by
the reference; plainly read here — the files are tiny) joined with the vmem
ledger and the tc_util feed, all node-local.
"""

from __future__ import annotations

import logging
import os

from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.config.tc_watcher import TcUtilFile
from vtpu_manager.config.vmem import VmemLedger, fnv64
from vtpu_manager.device.types import ChipSpec
from vtpu_manager.util import consts

log = logging.getLogger(__name__)


class Gauge:
    def __init__(self, name: str, help_text: str, labels: tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.labels = labels
        self.samples: list[tuple[tuple[str, ...], float]] = []

    def set(self, label_values: tuple[str, ...], value: float) -> None:
        self.samples.append((label_values, value))

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        for values, value in self.samples:
            label_str = ",".join(f'{k}="{v}"'
                                 for k, v in zip(self.labels, values))
            lines.append(f"{self.name}{{{label_str}}} {value}")
        return "\n".join(lines)


class NodeCollector:
    """Collects one scrape's worth of node + container gauges."""

    def __init__(self, node_name: str, chips: list[ChipSpec],
                 base_dir: str = consts.MANAGER_BASE_DIR,
                 tc_path: str = consts.TC_UTIL_CONFIG,
                 vmem_path: str = consts.VMEM_NODE_CONFIG):
        self.node_name = node_name
        self.chips = chips
        self.base_dir = base_dir
        self.tc_path = tc_path
        self.vmem_path = vmem_path

    def _container_configs(self) -> list[tuple[str, str, vc.VtpuConfig]]:
        out = []
        if not os.path.isdir(self.base_dir):
            return out
        for entry in sorted(os.listdir(self.base_dir)):
            cfg_path = os.path.join(self.base_dir, entry, "config",
                                    "vtpu.config")
            if not os.path.exists(cfg_path):
                continue
            pod_uid, _, container = entry.partition("_")
            try:
                out.append((pod_uid, container, vc.read_config(cfg_path)))
            except (OSError, ValueError):
                continue
        return out

    def collect(self) -> list[Gauge]:
        gauges: list[Gauge] = []

        g_mem_total = Gauge("vtpu_device_memory_total_bytes",
                            "Physical HBM per chip",
                            ("node", "uuid", "index"))
        g_healthy = Gauge("vtpu_device_healthy",
                          "Chip health (1 healthy)",
                          ("node", "uuid", "index"))
        g_util = Gauge("vtpu_device_utilization_percent",
                       "Chip duty-cycle percent from the node watcher",
                       ("node", "uuid", "index"))
        g_slots_total = Gauge("vtpu_device_slots_total",
                              "Advertised vTPU slots per chip",
                              ("node", "uuid", "index"))
        for chip in self.chips:
            labels = (self.node_name, chip.uuid, str(chip.index))
            g_mem_total.set(labels, float(chip.memory))
            g_healthy.set(labels, 1.0 if chip.healthy else 0.0)
            g_slots_total.set(labels, float(chip.split_count))
        gauges += [g_mem_total, g_healthy, g_slots_total]

        # node watcher feed: chip duty cycle + per-tenant attributed
        # shares (the watcher apportions by ledger submit-activity
        # deltas). Keyed per (tenant, chip): ProcUtil.util is percent OF
        # ONE CHIP — summing across chips would exceed 100.
        util_by_token: dict[tuple[int, int], int] = {}
        try:
            tc = TcUtilFile(self.tc_path)
            for chip in self.chips:
                rec = tc.read_device(chip.index)
                if rec is not None:
                    g_util.set((self.node_name, chip.uuid, str(chip.index)),
                               float(rec.device_util))
                    for proc in rec.procs:
                        key = (proc.owner_token, chip.index)
                        util_by_token[key] = \
                            util_by_token.get(key, 0) + proc.util
            tc.close()
        except (OSError, ValueError):
            pass
        gauges.append(g_util)

        # per-container assignment + usage
        g_climit = Gauge("vtpu_container_core_limit_percent",
                         "Assigned core percent",
                         ("node", "pod_uid", "container", "uuid"))
        g_mlimit = Gauge("vtpu_container_memory_limit_bytes",
                         "Assigned HBM cap",
                         ("node", "pod_uid", "container", "uuid"))
        g_musage = Gauge("vtpu_container_memory_used_bytes",
                         "HBM bytes recorded by the container's processes",
                         ("node", "pod_uid", "container", "uuid"))
        g_cutil = Gauge("vtpu_container_utilization_percent",
                        "Chip duty-cycle share attributed to the container",
                        ("node", "pod_uid", "container", "uuid"))
        g_assigned = Gauge("vtpu_device_assigned_containers",
                           "Containers sharing each chip",
                           ("node", "uuid"))
        assigned: dict[str, int] = {}
        vmem = None
        try:
            vmem = VmemLedger(self.vmem_path)
        except (OSError, ValueError):
            pass
        # per-(tenant, chip) attribution: ledger entries carry the owner
        # token (fnv64 of pod_uid/container) AND the chip, so co-tenants
        # are never conflated and a multi-chip container's rows stay
        # per-device (a token-only sum would double every uuid row)
        usage_by_token: dict[tuple[int, int], int] = {}
        if vmem is not None:
            for entry in vmem.entries():
                key = (entry.owner_token, entry.host_index)
                usage_by_token[key] = \
                    usage_by_token.get(key, 0) + entry.bytes
        for pod_uid, container, cfg in self._container_configs():
            token = fnv64(f"{pod_uid}/{container}")
            for dev in cfg.devices:
                labels = (self.node_name, pod_uid, container, dev.uuid)
                g_climit.set(labels, float(dev.hard_core))
                g_mlimit.set(labels, float(dev.total_memory))
                g_musage.set(labels, float(
                    usage_by_token.get((token, dev.host_index), 0)))
                g_cutil.set(labels, float(
                    util_by_token.get((token, dev.host_index), 0)))
                assigned[dev.uuid] = assigned.get(dev.uuid, 0) + 1
        if vmem is not None:
            vmem.close()
        for uuid, count in assigned.items():
            g_assigned.set((self.node_name, uuid), float(count))
        gauges += [g_climit, g_mlimit, g_musage, g_cutil, g_assigned]

        # node aggregates
        g_total = Gauge("vtpu_node_slots_total", "Node vTPU slot capacity",
                        ("node",))
        g_used = Gauge("vtpu_node_slots_assigned", "Assigned vTPU slots",
                       ("node",))
        g_total.set((self.node_name,),
                    float(sum(c.split_count for c in self.chips)))
        g_used.set((self.node_name,), float(sum(assigned.values())))
        gauges += [g_total, g_used]
        return gauges

    def render(self) -> str:
        return "\n".join(g.render() for g in self.collect() if g.samples
                         or True) + "\n"
