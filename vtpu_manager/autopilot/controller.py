"""vtpilot controller: the elected verdict-to-action loop.

One instance fleet-wide leads (ShardLease on the ``autopilot`` shard —
the exact vtha election/fencing machinery, not a parallel one);
followers tick cheaply and take over on lease expiry. The leader
consumes vtslo verdicts (the monitor's /slo fan-in, injected as a
callable so tests and the bench drive it directly), pushes each through
three independent guards — hysteresis, cooldown, token buckets per
tenant AND per node — and dispatches the survivors to the per-cause
action registry (actions.py). Every action carries the lease's fencing
token; every action and every suppression lands in the vtexplain spool
(``kind=autopilot``) and the on-disk JSONL action ledger.

All ``vtpu_autopilot_*`` / ``vtpu_migration_*`` series literals live in
THIS module (the metrics one-home rule); migration counts are folded in
from the migrator by :func:`render_autopilot_metrics`.
"""

from __future__ import annotations

import json
import logging
import os
import time

from vtpu_manager.resilience import failpoints
from vtpu_manager.scheduler.lease import LeaseLostError, ShardLease
from vtpu_manager.util.flock import FileLock

log = logging.getLogger(__name__)

# the one fleet-wide election unit; vtha shard names are node pools,
# this one names a control loop — same lease object shape either way
AUTOPILOT_SHARD = "autopilot"

# the sibling coordination lease: elects ONE reschedule controller
# fleet-wide to pay the cluster-scan LIST (device plugins compete for
# this one; the monitor-side remediation loop competes for
# AUTOPILOT_SHARD — two elections, one machinery)
COORDINATION_SHARD = "autopilot-coord"

ACTION_LEDGER_NAME = "autopilot_actions.jsonl"

# a cause must be emitted by this many DISTINCT detector episodes
# (distinct episode_onset_ts) before the controller acts — one episode
# is a spike, two is a pattern. Paired with detect.py's one-verdict-
# per-episode rule this bounds the controller's reaction rate to the
# detector's episode rate, not its window rate.
HYSTERESIS_EPISODES = 2

# no second action on the same tenant within this many seconds of the
# last, whatever the cause — remediations need time to show up in the
# detector's windows before the controller may conclude they failed
ACTION_COOLDOWN_S = 180.0

# token buckets: burst capacity + steady refill, per tenant and per
# node. The node bucket is the wider one — a node-wide incident (bad
# link, thrashing neighbor) surfaces as several tenants' verdicts and
# must not turn into an action storm on one box.
TENANT_BUCKET_CAPACITY = 2
TENANT_BUCKET_REFILL_S = 300.0     # one token per 5 min
NODE_BUCKET_CAPACITY = 4
NODE_BUCKET_REFILL_S = 150.0

# suppression reasons (ledger + metrics label vocabulary)
SUPPRESS_HYSTERESIS = "hysteresis"
SUPPRESS_COOLDOWN = "cooldown"
SUPPRESS_TENANT_BUCKET = "rate-limit-tenant"
SUPPRESS_NODE_BUCKET = "rate-limit-node"
SUPPRESS_NO_ACTION = "no-action"
SUPPRESS_REASONS = (SUPPRESS_HYSTERESIS, SUPPRESS_COOLDOWN,
                    SUPPRESS_TENANT_BUCKET, SUPPRESS_NODE_BUCKET,
                    SUPPRESS_NO_ACTION)

# bound on remembered episode onsets per (tenant, kind) — hysteresis
# needs "at least N distinct", never the full history
_MAX_EPISODES_KEPT = 8


class ActionLedger:
    """Append-only JSONL record of every action taken — the durable
    half of the audit trail (vtexplain is the queryable half; this file
    survives monitor restarts and feeds the bench's flap assertions).
    Same crash discipline as the quota ledger: writes under a FileLock
    on a sibling ``.flock``, reads tolerate a torn final line."""

    def __init__(self, base_dir: str, clock=time.time):
        self.path = os.path.join(base_dir, ACTION_LEDGER_NAME)
        self.clock = clock

    def record(self, action: dict) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        line = json.dumps(action, separators=(",", ":"))
        with FileLock(f"{self.path}.flock"):
            with open(self.path, "a") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())

    def actions(self, since: float = 0.0) -> list[dict]:
        """Recorded actions with ts >= since; a torn trailing line (a
        writer's crash window) reads as absent, never as an error."""
        try:
            with open(self.path) as f:
                raw = f.read()
        except OSError:
            return []
        out = []
        for line in raw.splitlines():
            try:
                rec = json.loads(line)
            except (ValueError, TypeError):
                continue
            if isinstance(rec, dict) and \
                    float(rec.get("ts", 0.0)) >= since:
                out.append(rec)
        return out


class TokenBucket:
    """Keyed token buckets with continuous refill — the rate limiter
    both per-tenant and per-node guards share. ``peek`` and ``take``
    are split so the controller can require BOTH buckets before
    consuming from either (no tenant token burned on a node refusal)."""

    def __init__(self, capacity: int, refill_s: float, clock=time.time):
        self.capacity = float(capacity)
        self.refill_s = float(refill_s)
        self.clock = clock
        self._level: dict[str, tuple[float, float]] = {}  # key -> (tokens, ts)

    def _refreshed(self, key: str, now: float) -> float:
        tokens, ts = self._level.get(key, (self.capacity, now))
        if now > ts:
            tokens = min(self.capacity,
                         tokens + (now - ts) / self.refill_s)
        return tokens

    def peek(self, key: str, now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        return self._refreshed(key, now) >= 1.0

    def take(self, key: str, now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        tokens = self._refreshed(key, now)
        if tokens < 1.0:
            self._level[key] = (tokens, now)
            return False
        self._level[key] = (tokens - 1.0, now)
        return True


def acquire_or_confirm(lease: ShardLease) -> bool:
    """One election step, shared by both loops: renew if leading,
    otherwise try to take over an expired lease. Never blocks on a
    live foreign lease; False means follow this tick."""
    try:
        if lease.held_fresh():
            lease.confirm()
            return True
        return lease.try_acquire()
    except LeaseLostError:
        return False


def coordination_scan_probe(client, holder: str,
                            namespace: str | None = None):
    """``cluster_scan_leader`` factory for RescheduleController: the
    controller whose probe wins the COORDINATION_SHARD lease pays the
    cluster LIST; everyone else keeps node-scoped passes. A probe that
    raises falls back to scanning inside the controller (reschedule.py)
    — duplicate LISTs cost load, a never-reaped crash window costs
    correctness."""
    kwargs = {} if namespace is None else {"namespace": namespace}
    lease = ShardLease(client, COORDINATION_SHARD, holder, **kwargs)
    return lambda: acquire_or_confirm(lease)


class _CauseState:
    """Hysteresis memory for one (tenant, kind)."""

    __slots__ = ("onsets", "last_action_ts")

    def __init__(self):
        self.onsets: list[float] = []   # distinct episode onsets seen
        self.last_action_ts = 0.0


class AutopilotController:
    """The elected loop. ``verdict_feed()`` returns the current batch
    of verdict wire dicts (each at least kind/tenant/episode_onset_ts,
    plus node when the fan-in knows it); ``actions`` maps verdict kind
    to ``fn(verdict, fence) -> outcome dict`` (actions.default_actions).
    """

    def __init__(self, client, holder: str, base_dir: str,
                 verdict_feed, actions: dict,
                 ttl_s: float = 15.0,
                 cooldown_s: float = ACTION_COOLDOWN_S,
                 hysteresis_episodes: int = HYSTERESIS_EPISODES,
                 lease: ShardLease | None = None,
                 clock=time.time):
        self.holder = holder
        self.verdict_feed = verdict_feed
        self.actions = actions
        self.cooldown_s = cooldown_s
        self.hysteresis_episodes = hysteresis_episodes
        self.clock = clock
        self.lease = lease if lease is not None else ShardLease(
            client, AUTOPILOT_SHARD, holder, ttl_s=ttl_s)
        self.ledger = ActionLedger(base_dir, clock=clock)
        self.tenant_bucket = TokenBucket(TENANT_BUCKET_CAPACITY,
                                         TENANT_BUCKET_REFILL_S, clock)
        self.node_bucket = TokenBucket(NODE_BUCKET_CAPACITY,
                                       NODE_BUCKET_REFILL_S, clock)
        self._causes: dict[tuple[str, str], _CauseState] = {}
        # counters read by render_autopilot_metrics (one home)
        self.verdicts_total = 0
        self.actions_total: dict[str, int] = {}
        self.suppressed_total: dict[str, int] = {}
        self.action_failures_total = 0

    # -- leadership ----------------------------------------------------------

    def is_leader(self) -> bool:
        return self.lease.held_fresh()

    def _lead(self) -> bool:
        """Acquire-or-renew; False demotes this tick to a follower.
        A fresh takeover's first duty is reaping the predecessor's
        stale migration intents — its lease token outranks theirs."""
        was_leader = self.lease.held_fresh()
        leading = acquire_or_confirm(self.lease)
        if leading and not was_leader:
            self._on_takeover()
        return leading

    def _on_takeover(self) -> None:
        """Hook point (wired by the daemon host to
        migrate.reap_stale_migrations); a bare controller does nothing.
        """
        if getattr(self, "on_takeover", None) is not None:
            try:
                self.on_takeover()
            except Exception as exc:
                log.warning("autopilot takeover hook failed: %s", exc)

    # -- the loop body -------------------------------------------------------

    def tick(self, now: float | None = None) -> list[dict]:
        """One pass: elect, consume verdicts, guard, act. Returns the
        actions taken (empty for followers and all-suppressed passes).
        """
        now = self.clock() if now is None else now
        if not self._lead():
            return []
        taken = []
        for verdict in self.verdict_feed() or []:
            self.verdicts_total += 1
            decision = self._consider(verdict, now)
            if decision is not None:
                taken.append(decision)
        return taken

    def _consider(self, verdict: dict, now: float) -> dict | None:
        tenant = str(verdict.get("tenant", ""))
        kind = str(verdict.get("kind", ""))
        node = str(verdict.get("node", ""))
        state = self._causes.setdefault((tenant, kind), _CauseState())
        onset = float(verdict.get("episode_onset_ts", 0.0))
        if onset and onset not in state.onsets:
            state.onsets.append(onset)
            del state.onsets[:-_MAX_EPISODES_KEPT]
        if len(state.onsets) < self.hysteresis_episodes:
            return self._suppress(SUPPRESS_HYSTERESIS, verdict, now)
        if now - state.last_action_ts < self.cooldown_s:
            return self._suppress(SUPPRESS_COOLDOWN, verdict, now)
        fn = self.actions.get(kind)
        if fn is None:
            return self._suppress(SUPPRESS_NO_ACTION, verdict, now)
        # both-or-neither: require both buckets before consuming either,
        # so a node-limited verdict doesn't silently drain tenant tokens
        if not self.tenant_bucket.peek(tenant, now):
            return self._suppress(SUPPRESS_TENANT_BUCKET, verdict, now)
        if node and not self.node_bucket.peek(node, now):
            return self._suppress(SUPPRESS_NODE_BUCKET, verdict, now)
        self.tenant_bucket.take(tenant, now)
        if node:
            self.node_bucket.take(node, now)
        return self._act(fn, verdict, tenant, kind, state, now)

    def _act(self, fn, verdict: dict, tenant: str, kind: str,
             state: _CauseState, now: float) -> dict | None:
        # the fence is read through fence_annotations() — a freshness-
        # checked read, so a deposed leader cannot stamp a stale token
        try:
            fence = next(iter(
                self.lease.fence_annotations().values()))
        except LeaseLostError:
            return None
        try:
            failpoints.fire("autopilot.act", tenant=tenant, kind=kind)
            outcome = fn(verdict, fence)
        except Exception as exc:    # CrashFailpoint (BaseException) flies
            log.warning("autopilot action %s for %s failed: %s",
                        kind, tenant, exc)
            self.action_failures_total += 1
            outcome = {"action": kind, "ok": False, "error": str(exc)}
        # the action landed (or measurably failed): start the cooldown
        # and demand fresh episodes either way — retrying a failed
        # remediation every tick is exactly the flap the guards exist
        # to prevent
        state.last_action_ts = now
        state.onsets.clear()
        self.actions_total[kind] = self.actions_total.get(kind, 0) + 1
        record = {
            "kind": "autopilot", "ts": round(now, 3),
            "holder": self.holder, "fence": fence,
            "tenant": tenant, "verdict": dict(verdict),
            "action": outcome,
        }
        self.ledger.record(record)
        self._explain(record)
        return record

    def _suppress(self, reason: str, verdict: dict,
                  now: float) -> None:
        self.suppressed_total[reason] = \
            self.suppressed_total.get(reason, 0) + 1
        # suppressions are decisions too — auditable, but only in the
        # in-memory vtexplain ring (a per-window ledger line per
        # suppressed verdict would grow the file with steady noise)
        self._explain({
            "kind": "autopilot", "ts": round(now, 3),
            "holder": self.holder,
            "tenant": str(verdict.get("tenant", "")),
            "verdict": dict(verdict),
            "action": {"action": "suppressed", "reason": reason},
        })
        return None

    @staticmethod
    def _explain(record: dict) -> None:
        from vtpu_manager import explain
        explain.record_raw(record)


def render_autopilot_metrics(controller: "AutopilotController | None",
                             migrator=None) -> str:
    """Prometheus text for the autopilot plane; empty when no
    controller exists (the gate-off contract: zero new series). The ONE
    home of every vtpu_autopilot_* / vtpu_migration_* literal —
    migration counts are attributes on the migrator, rendered here."""
    if controller is None:
        return ""
    lines = [
        "# HELP vtpu_autopilot_leader 1 when this process holds the "
        "fleet autopilot lease",
        "# TYPE vtpu_autopilot_leader gauge",
        f'vtpu_autopilot_leader{{holder="{controller.holder}"}} '
        f"{1 if controller.is_leader() else 0}",
        "# HELP vtpu_autopilot_verdicts_total SLO verdicts consumed by "
        "the leader loop",
        "# TYPE vtpu_autopilot_verdicts_total counter",
        f"vtpu_autopilot_verdicts_total {controller.verdicts_total}",
        "# HELP vtpu_autopilot_actions_total Remediations dispatched, "
        "by verdict kind",
        "# TYPE vtpu_autopilot_actions_total counter",
    ]
    for kind in sorted(controller.actions_total):
        lines.append(f'vtpu_autopilot_actions_total{{action="{kind}"}} '
                     f"{controller.actions_total[kind]}")
    lines += [
        "# HELP vtpu_autopilot_suppressed_total Verdicts the guards "
        "held back (hysteresis, cooldown, rate limits)",
        "# TYPE vtpu_autopilot_suppressed_total counter",
    ]
    for reason in SUPPRESS_REASONS:
        if reason in controller.suppressed_total:
            lines.append(
                f'vtpu_autopilot_suppressed_total{{reason="{reason}"}} '
                f"{controller.suppressed_total[reason]}")
    lines += [
        "# HELP vtpu_autopilot_action_failures_total Dispatched "
        "remediations that raised",
        "# TYPE vtpu_autopilot_action_failures_total counter",
        "vtpu_autopilot_action_failures_total "
        f"{controller.action_failures_total}",
    ]
    if migrator is not None:
        lines += [
            "# HELP vtpu_migration_total Live gang migrations "
            "completed end to end",
            "# TYPE vtpu_migration_total counter",
            f"vtpu_migration_total {migrator.migrations_total}",
            "# HELP vtpu_migration_failures_total Migrations that "
            "failed or were abandoned mid-flight",
            "# TYPE vtpu_migration_failures_total counter",
            "vtpu_migration_failures_total "
            f"{migrator.migration_failures_total}",
            "# HELP vtpu_migration_reaped_total Stale migration "
            "intents unfrozen by a successor or the age-out reaper",
            "# TYPE vtpu_migration_reaped_total counter",
            f"vtpu_migration_reaped_total {migrator.reaped_total}",
            "# HELP vtpu_migration_last_freeze_ms Wall milliseconds "
            "the last migration held its tenant frozen",
            "# TYPE vtpu_migration_last_freeze_ms gauge",
            f"vtpu_migration_last_freeze_ms "
            f"{migrator.last_freeze_ms:.1f}",
        ]
    return "\n".join(lines) + "\n"
