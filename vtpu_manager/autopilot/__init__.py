"""vtpilot — SLO autopilot: elected remediation + live gang migration.

The closed-loop layer above vtslo: the detector plane names a cause
("throttle-spike coincides with lease q42-0-3 revoke"), this plane acts
on it through the planes that already own the levers — quota leases for
throttle, the overcommit annotation for spill thrash, the vtici
link-load scores for comm inflation — never through a side channel.

Gate contract (``SLOAutopilot``, default off = byte-identical): no
``autopilot`` lease is created or read, no controller loop runs, no
action is ever taken (placement untouched in BOTH scheduler modes), no
action ledger exists under the base dir, no ``vtpu_autopilot_*`` /
``vtpu_migration_*`` series render, the monitor registers no
``/autopilot`` route, configs carry ``migration_freeze=0`` /
``freeze_epoch=0`` (the v5 wire bytes), and vtpu-smi / ``--why-slow``
output is byte-identical.

Why ELECTED: every remediation here is a cluster-visible mutation
(annotation patches, quota grants, a rebind). Two autopilots acting on
the same verdict stream would fight — migrate the same gang twice,
double-clamp a node — so exactly one instance leads, behind the same
ShardLease machinery vtha schedulers use (shard name ``autopilot``),
and every action it takes is stamped with the lease's monotone fencing
token. A deposed leader's in-flight migration is recognizable by its
stale token and reaped by the successor (migrate.py).

Why BOUNDED: a controller that reacts to every verdict instantly will
chase noise and amplify it (act on a spike, the migration itself costs
a window, the detector flags the migration...). Three independent
guards, all of which must pass: hysteresis (a cause must persist across
>= 2 distinct detector episodes), cooldown (no action on a tenant
within ACTION_COOLDOWN_S of its last), and token buckets per tenant AND
per node. Every action AND every suppression is auditable (vtexplain
``kind=autopilot`` + the on-disk action ledger).
"""

from vtpu_manager.autopilot.controller import (ACTION_COOLDOWN_S,
                                               AUTOPILOT_SHARD,
                                               COORDINATION_SHARD,
                                               HYSTERESIS_EPISODES,
                                               ActionLedger,
                                               AutopilotController,
                                               TokenBucket,
                                               coordination_scan_probe,
                                               render_autopilot_metrics)
from vtpu_manager.autopilot.actions import ActionContext, default_actions
from vtpu_manager.autopilot.migrate import (GangMigrator,
                                            reap_stale_migrations)

__all__ = [
    "ACTION_COOLDOWN_S", "AUTOPILOT_SHARD", "COORDINATION_SHARD",
    "HYSTERESIS_EPISODES", "ActionContext", "ActionLedger",
    "AutopilotController", "GangMigrator", "TokenBucket",
    "coordination_scan_probe", "default_actions",
    "reap_stale_migrations", "render_autopilot_metrics",
]
