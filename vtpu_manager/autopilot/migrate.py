"""vtpilot live gang migration: freeze -> drain -> spill -> rebind -> refill.

The primitive rides three existing planes instead of inventing one:

- **freeze** is a v6 config rewrite (``migration_freeze=1``,
  ``freeze_epoch`` + ``quota_epoch`` bumped) — the SAME benign-race
  adoption channel quota leases use, so the C++ shim parks dispatch at
  the token-wait entry within one tick quantum and in-flight Executes
  drain naturally (enforce.cc FreezePark). The shim's
  ``VTPU_FREEZE_MAX_S`` fail-open bounds the worst case where every
  software layer below dies.
- **spill demotion** goes through the vtovc SpillPool — budget-guarded
  by its ledger, with the caller-wired per-chip invariant check run
  before every commit, so a migration can never overdraw the host pool
  or double-account a chip.
- **rebind** goes through the normal scheduler bind shape: allocating
  status + bind-intent + fence annotations in one patch, then the
  Binding POST — so the reschedule controller's existing reapers
  understand a migration's crash window without new rules.

Crash model: the fence-stamped migration-intent annotation is written
BEFORE anything is frozen. A migrator that dies mid-flight (chaos:
CrashFailpoint at ``migrate.freeze`` / ``migrate.refill``) leaves the
intent + possibly-frozen configs; :func:`reap_stale_migrations` —
run by the successor leader and by node reconcile passes — unfreezes
any tenant whose intent token predates the current ``autopilot`` lease
incarnation or whose intent aged out. Frozen tenants always unfreeze;
no pod ends double-owned.
"""

from __future__ import annotations

import logging
import time

from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.config.tenantdirs import iter_container_config_paths
from vtpu_manager.overcommit.spill import SpillBudgetError
from vtpu_manager.resilience import failpoints, recovery
from vtpu_manager.scheduler.lease import parse_fence, read_lease_state
from vtpu_manager.util import consts, stalecodec

log = logging.getLogger(__name__)

# a migration intent older than this is reaped on age alone (the
# token-aware rule reaps deposed leaders' intents sooner); kept BELOW
# the shim's 120 s freeze fail-open so software unfreezes first and
# the shim backstop never normally fires
MIGRATION_INTENT_TTL_S = 60.0

# drain polling bounds — attempt-bounded (not just wall-bounded) so an
# injected clock that never advances cannot spin the loop forever
DRAIN_TIMEOUT_S = 30.0
DRAIN_POLL_S = 0.05


def encode_migration_intent(source: str, target: str, fence: str,
                            ts: float | None = None) -> str:
    """``<source>|<target>|<fence>@<wall-seconds>`` — ``|`` because the
    fence itself carries a ``:``. The SOURCE rides the intent because
    the rebind step rewrites the pod's nodeName to the target: a reaper
    arriving after a refill-crash would otherwise resolve "source" to
    the target node and leave the true source's configs frozen until
    the shim's fail-open."""
    return stalecodec.stamp(f"{source}|{target}|{fence}",
                            ts if ts is not None else time.time())


def parse_migration_intent(value: str | None
                           ) -> tuple[str, str, str, float] | None:
    """(source, target, fence, ts) or None — malformed reads as absent,
    the reap-never-off-garbage posture of parse_bind_intent."""
    split = stalecodec.split_stamp(value)
    if split is None:
        return None
    body, ts = split
    parts = body.split("|", 2)
    if len(parts) != 3 or not parts[1]:
        return None
    source, target, fence = parts
    return source, target, fence, ts


def set_tenant_freeze(base_dir: str | None, uid: str,
                      frozen: bool) -> int:
    """Rewrite every config of ``uid`` under ``base_dir`` with the
    freeze flag; returns configs touched. Bumps freeze_epoch AND
    quota_epoch — the latter is the shim's re-read trigger, the former
    is what its park/release logs name."""
    if not base_dir:
        return 0
    touched = 0
    for cfg_uid, _label, path, _dra in \
            iter_container_config_paths(base_dir):
        if cfg_uid != uid:
            continue
        try:
            cfg = vc.read_config(path)
        except (OSError, ValueError):
            continue    # writer's crash window; the next pass retries
        flag = 1 if frozen else 0
        if cfg.migration_freeze == flag:
            continue    # idempotent: a reaper re-run must not bump epochs
        cfg.migration_freeze = flag
        cfg.freeze_epoch += 1
        cfg.quota_epoch += 1
        vc.write_config(path, cfg)
        touched += 1
    return touched


class GangMigrator:
    """One migration at a time, fence-stamped, intent-trail protected.

    ``base_dir_for_node(node)`` resolves tenant config dirs;
    ``spill_pool_for_node(node)`` / ``resident_buffers(pod, node)`` /
    ``invariant_check()`` wire the vtovc demotion step (all optional —
    a gang with nothing resident migrates without touching the pool);
    ``drain_check(pod)`` reports whether in-flight Executes finished
    (None = trust the shim's natural drain)."""

    def __init__(self, client, base_dir_for_node, clock=time.time,
                 spill_pool_for_node=None, resident_buffers=None,
                 invariant_check=None, drain_check=None,
                 drain_timeout_s: float = DRAIN_TIMEOUT_S,
                 drain_poll_s: float = DRAIN_POLL_S, sleep=time.sleep):
        self.client = client
        self.base_dir_for_node = base_dir_for_node
        self.clock = clock
        self.spill_pool_for_node = spill_pool_for_node
        self.resident_buffers = resident_buffers
        self.invariant_check = invariant_check
        self.drain_check = drain_check
        self.drain_timeout_s = drain_timeout_s
        self.drain_poll_s = drain_poll_s
        self.sleep = sleep
        # counters rendered by controller.render_autopilot_metrics
        self.migrations_total = 0
        self.migration_failures_total = 0
        self.reaped_total = 0
        self.last_freeze_ms = 0.0

    # -- the timeline --------------------------------------------------------

    def migrate(self, pod: dict, target: str, fence: str) -> dict:
        meta = pod.get("metadata", {})
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        uid = meta.get("uid", "")
        source = pod.get("spec", {}).get("nodeName") or \
            (meta.get("annotations", {}) or {}).get(
                consts.predicate_node_annotation(), "")
        t0 = self.clock()
        # (1) the crash trail lands before anything freezes: from here
        # on, a dead migrator is a reapable record, not a stuck tenant
        self.client.patch_pod_annotations(ns, name, {
            consts.migration_intent_annotation():
                encode_migration_intent(source, target, fence, t0)})
        freeze_t = 0.0
        try:
            # (2) freeze: the shim parks at token-wait entry next quantum
            failpoints.fire("migrate.freeze", pod=name, node=source)
            frozen = set_tenant_freeze(
                self.base_dir_for_node(source), uid, True)
            freeze_t = self.clock()
            # (3) drain in-flight Executes
            drained = self._drain(pod)
            # (4) demote resident HBM to the host spill tier
            demoted = self._demote(pod, source)
            # (5) rebind through the normal path: the same one-patch
            # commit a scheduler bind makes, then the Binding POST
            self.client.patch_pod_annotations(ns, name, {
                consts.allocation_status_annotation():
                    consts.ALLOC_STATUS_ALLOCATING,
                consts.bind_intent_annotation():
                    recovery.encode_bind_intent(target, self.clock()),
                consts.shard_fence_annotation(): fence,
                consts.predicate_node_annotation(): target,
            })
            self.client.bind_pod(ns, name, target)
            # (6) refill: unfreeze so the target's shim admits dispatch;
            # the source unfreezes too (its shim drains out, and a
            # frozen orphan config must never outlive the migration)
            failpoints.fire("migrate.refill", pod=name, node=target)
            set_tenant_freeze(self.base_dir_for_node(source), uid,
                              False)
            set_tenant_freeze(self.base_dir_for_node(target), uid,
                              False)
            # (7) close the trail
            self.client.patch_pod_annotations(ns, name, {
                consts.migration_intent_annotation(): None,
                consts.allocation_status_annotation():
                    consts.ALLOC_STATUS_SUCCEED,
            })
        except Exception as exc:
            # a FAILED migration (not a crashed one — CrashFailpoint is
            # a BaseException and flies past this) rolls back in-place:
            # unfreeze the source and close the trail, leaving the gang
            # where it was
            log.warning("migration of %s/%s to %s failed: %s; "
                        "unfreezing in place", ns, name, target, exc)
            self.migration_failures_total += 1
            self._abandon(ns, name, source, uid)
            return {"ok": False, "error": str(exc), "pod": name,
                    "source": source, "target": target}
        self.migrations_total += 1
        if freeze_t:
            self.last_freeze_ms = max(self.clock() - freeze_t, 0.0) \
                * 1000.0
        return {"ok": True, "pod": name, "source": source,
                "target": target, "configs_frozen": frozen,
                "drained": drained, "spilled": demoted,
                "freeze_ms": round(self.last_freeze_ms, 1),
                "total_ms": round((self.clock() - t0) * 1000.0, 1)}

    def _abandon(self, ns: str, name: str, source: str,
                 uid: str) -> None:
        try:
            set_tenant_freeze(self.base_dir_for_node(source), uid,
                              False)
            self.client.patch_pod_annotations(ns, name, {
                consts.migration_intent_annotation(): None})
        except Exception as exc:
            # rollback itself failed (node gone, apiserver down): the
            # intent is still on the pod, so the reaper finishes this
            log.warning("migration rollback for %s/%s incomplete (%s); "
                        "leaving the intent trail for the reaper",
                        ns, name, exc)

    def _drain(self, pod: dict) -> bool:
        if self.drain_check is None:
            return True
        deadline = self.clock() + self.drain_timeout_s
        attempts = max(int(self.drain_timeout_s / self.drain_poll_s), 1)
        for _ in range(attempts):
            if self.drain_check(pod):
                return True
            if self.clock() >= deadline:
                break
            self.sleep(self.drain_poll_s)
        return False    # proceed anyway: the freeze holds new dispatch

    def _demote(self, pod: dict, source: str) -> dict:
        if self.spill_pool_for_node is None or \
                self.resident_buffers is None:
            return {"buffers": 0, "bytes": 0}
        pool = self.spill_pool_for_node(source)
        if pool is None:
            return {"buffers": 0, "bytes": 0}
        buffers = 0
        total = 0
        for host_index, buf_id, payload in \
                self.resident_buffers(pod, source):
            # per-chip + budget invariants re-proved before EVERY
            # commit — a demotion must never be the write that breaks
            # the node's accounting
            if self.invariant_check is not None:
                self.invariant_check()
            try:
                pool.spill(host_index, buf_id, payload)
            except SpillBudgetError:
                # budget exhausted: stop demoting — what stays resident
                # just migrates as a cold refill later
                break
            buffers += 1
            total += len(payload)
        return {"buffers": buffers, "bytes": total}


# -- the convergence half -----------------------------------------------------

def reap_stale_migrations(client, base_dir_for_node,
                          now: float | None = None,
                          intent_ttl_s: float = MIGRATION_INTENT_TTL_S,
                          lease_probe=None,
                          migrator: GangMigrator | None = None
                          ) -> list[str]:
    """Unfreeze tenants whose migration intent is provably dead; the
    successor leader's first duty and part of every node reconcile.

    Two independent staleness rules, either suffices:

    - **token**: the intent's fence token predates the live
      ``autopilot`` lease incarnation — its stamping leader is deposed,
      so whatever it was mid-way through will never finish;
    - **age**: the intent outlived MIGRATION_INTENT_TTL_S — covers the
      no-lease and lease-unreadable shapes by wall clock alone.

    An intent stamped by the CURRENT incarnation and inside its TTL is
    a live migration and is left alone. Returns reaped pod names."""
    now = time.time() if now is None else now
    if lease_probe is None:
        from vtpu_manager.autopilot.controller import AUTOPILOT_SHARD
        lease_probe = lambda: read_lease_state(client, AUTOPILOT_SHARD)
    lease = None
    lease_read = False
    reaped = []
    for pod in client.list_pods():
        meta = pod.get("metadata", {})
        anns = meta.get("annotations", {}) or {}
        parsed = parse_migration_intent(
            anns.get(consts.migration_intent_annotation()))
        if parsed is None:
            continue
        source, target, fence_raw, ts = parsed
        stale = now - ts > intent_ttl_s
        if not stale:
            pf = parse_fence(fence_raw)
            if pf is not None:
                if not lease_read:
                    lease = lease_probe()
                    lease_read = True
                if lease is not None and lease.token > pf[1]:
                    stale = True
        if not stale:
            continue
        uid = meta.get("uid", "")
        # unfreeze wherever the dead migration may have left the flag:
        # the intent's source (NOT the pod's nodeName — a refill-crash
        # happens after the rebind already points that at the target),
        # the intended target, and wherever the pod sits now
        landed = pod.get("spec", {}).get("nodeName") or \
            anns.get(consts.predicate_node_annotation(), "")
        for node in {source, target, landed} - {""}:
            set_tenant_freeze(base_dir_for_node(node), uid, False)
        client.patch_pod_annotations(
            meta.get("namespace", "default"), meta.get("name", ""),
            {consts.migration_intent_annotation(): None})
        if migrator is not None:
            migrator.reaped_total += 1
        reaped.append(meta.get("name", ""))
    return reaped
