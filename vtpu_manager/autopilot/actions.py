"""vtpilot remediations: one bounded executor per named cause.

Each action goes through the plane that already owns the lever — no
side channels, so every mutation is visible to that plane's own audit
and reclaim machinery:

- **throttle-spike -> retune quota**: grant the tenant a bounded,
  TTL'd quota lease through the vtqm ledger (lender ``autopilot``) and
  rewrite its config's ``lease_core``/``quota_epoch`` — the SAME
  adoption channel the market manager uses, so the C++ shim picks the
  raise up in its token-wait re-read and the lease expires on its own
  if the autopilot dies.
- **spill-thrash -> clamp overcommit, or migrate**: shrink every class
  ratio in the node's overcommit annotation one step (the scheduler
  stops admitting against the phantom capacity immediately). When the
  node is already at ratio 1.0 the clamp has nothing left to give, so
  the action escalates to migrating the thrashing tenant off the box.
  The clamp holds until the node's own policy publisher re-rolls; the
  action cooldown covers that window, and a re-offending node just
  gets clamped again.
- **comm-inflation -> re-place the gang**: score candidate nodes by
  their published vtici link-load (worst contended link, the exact
  signal the scheduler's link_term reads), pick the quietest, and
  live-migrate the gang there (migrate.py). Submesh-level placement on
  the target is the scheduler's job at bind — the autopilot only picks
  the box.

Every executor returns an outcome dict (never raises for policy
outcomes — "nothing to clamp" is an outcome, not an error) and the
controller records it verbatim.
"""

from __future__ import annotations

import time

from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.config.tenantdirs import iter_container_config_paths
from vtpu_manager.overcommit.ratio import NodeOvercommit, parse_overcommit
from vtpu_manager.quota.ledger import QuotaLeaseLedger
from vtpu_manager.quota.market import scaled_grant_step
from vtpu_manager.topology.linkload import load_map, parse_link_load
from vtpu_manager.util import consts

# quota-retune bounds (the market's own vocabulary: a step is a lease
# pct, the TTL makes every grant self-expiring)
GRANT_STEP_PCT = 10
MAX_BORROW_PCT = 40
LEASE_TTL_S = 60.0

# one overcommit clamp step: every class ratio shrinks by this much,
# floored at 1.0 (no oversubscription)
CLAMP_STEP = 0.25


class ActionContext:
    """Everything the executors need, injectable for tests/bench.

    ``base_dir_for_node(node)`` resolves a node name to its tenant
    config base dir (the bench maps each fake node to a tmp dir; a
    real deployment resolves the node's hostPath). ``pod_for_tenant``
    finds the pod object to migrate; the default scans the client by
    pod UID (the tenant key's first segment)."""

    def __init__(self, client, base_dir_for_node, migrator=None,
                 candidate_nodes=None, pod_for_tenant=None,
                 clock=time.time):
        self.client = client
        self.base_dir_for_node = base_dir_for_node
        self.migrator = migrator
        self.candidate_nodes = candidate_nodes or \
            (lambda: sorted(getattr(client, "nodes", {}) or {}))
        self.pod_for_tenant = pod_for_tenant or self._pod_by_uid
        self.clock = clock

    def _pod_by_uid(self, tenant: str):
        uid = tenant.partition("/")[0]
        for pod in self.client.list_pods():
            meta = pod.get("metadata", {})
            if meta.get("uid") == uid:
                return pod
        return None


def default_actions(ctx: ActionContext) -> dict:
    """kind -> executor registry for AutopilotController."""
    return {
        "throttle-spike": lambda v, fence: retune_quota(ctx, v, fence),
        "spill-thrash": lambda v, fence: relieve_spill(ctx, v, fence),
        "comm-inflation": lambda v, fence: replace_gang(ctx, v, fence),
        "chip-failure": lambda v, fence: rescue_gang(ctx, v, fence),
    }


# -- throttle-spike ----------------------------------------------------------

def retune_quota(ctx: ActionContext, verdict: dict,
                 fence: str) -> dict:
    node = str(verdict.get("node", ""))
    tenant = str(verdict.get("tenant", ""))
    base = ctx.base_dir_for_node(node)
    if not base:
        return {"action": "retune-quota", "ok": False,
                "reason": "no-base-dir", "node": node}
    uid = tenant.partition("/")[0]
    targets = [(label, path) for cfg_uid, label, path, _dra in
               iter_container_config_paths(base) if cfg_uid == uid]
    if not targets:
        return {"action": "retune-quota", "ok": False,
                "reason": "no-config", "tenant": tenant}
    now = ctx.clock()
    # no live utilization verdict plumbed here => scaled_grant_step
    # resets to the base step with full TTL; the market's own feedback
    # leg takes over sizing on subsequent passes
    step, ttl_factor = scaled_grant_step(
        GRANT_STEP_PCT, GRANT_STEP_PCT, MAX_BORROW_PCT,
        None, None, None)
    ledger = QuotaLeaseLedger(base, clock=ctx.clock)
    granted = []
    epoch = 0
    for label, path in targets:
        try:
            cfg = vc.read_config(path)
        except (OSError, ValueError):
            continue    # a writer's crash window; next episode retries
        for dev in cfg.devices:
            lease, epoch = ledger.grant(
                dev.host_index, "autopilot", uid, step,
                LEASE_TTL_S * ttl_factor, now=now)
            dev.lease_core += step
            granted.append({"lease_id": lease["id"],
                            "chip": dev.host_index, "pct": step})
        cfg.quota_epoch = epoch
        vc.write_config(path, cfg)
    if not granted:
        return {"action": "retune-quota", "ok": False,
                "reason": "no-config", "tenant": tenant}
    return {"action": "retune-quota", "ok": True, "tenant": tenant,
            "node": node, "fence": fence, "epoch": epoch,
            "ttl_s": LEASE_TTL_S * ttl_factor, "grants": granted}


# -- spill-thrash ------------------------------------------------------------

def relieve_spill(ctx: ActionContext, verdict: dict,
                  fence: str) -> dict:
    node = str(verdict.get("node", ""))
    now = ctx.clock()
    raw = None
    if node:
        node_obj = ctx.client.get_node(node) or {}
        raw = (node_obj.get("metadata", {}).get("annotations", {})
               or {}).get(consts.node_overcommit_annotation())
    oc = parse_overcommit(raw, now=now)
    if oc is not None and oc.max_ratio() > 1.0:
        clamped = {k: max(1.0, round(r - CLAMP_STEP, 2))
                   for k, r in oc.ratios.items()}
        patched = NodeOvercommit(ratios=clamped,
                                 spill_frac=oc.spill_frac,
                                 spilled_bytes=oc.spilled_bytes,
                                 ts=now)
        ctx.client.patch_node_annotations(node, {
            consts.node_overcommit_annotation(): patched.encode()})
        return {"action": "clamp-overcommit", "ok": True, "node": node,
                "fence": fence, "ratios_before": dict(oc.ratios),
                "ratios_after": clamped}
    # nothing left to clamp (ratio already 1.0, or no fresh policy
    # signal): the node is thrashing at physical capacity, so move the
    # thrashing tenant instead of starving it further
    return _migrate_tenant(ctx, verdict, fence,
                           action="migrate-thrashing",
                           exclude=(node,))


# -- comm-inflation ----------------------------------------------------------

def quietest_node(ctx: ActionContext, exclude=(),
                  now: float | None = None):
    """(node, worst_link) with the LOWEST worst-link contention among
    candidates publishing fresh link-load; a node with no fresh signal
    scores 0.0 (an idle mesh and an unmeasured one look the same here —
    the scheduler's link_term applies the same no-signal identity)."""
    now = ctx.clock() if now is None else now
    best = None
    for name in ctx.candidate_nodes():
        if name in exclude:
            continue
        node_obj = ctx.client.get_node(name) or {}
        raw = (node_obj.get("metadata", {}).get("annotations", {})
               or {}).get(consts.node_ici_link_load_annotation())
        lm = load_map(parse_link_load(raw, now=now), now=now)
        worst = max(lm.values()) if lm else 0.0
        if best is None or worst < best[1]:
            best = (name, worst)
    return best


def replace_gang(ctx: ActionContext, verdict: dict,
                 fence: str) -> dict:
    return _migrate_tenant(ctx, verdict, fence, action="replace-gang",
                           exclude=(str(verdict.get("node", "")),))


# -- chip-failure (vtheal) ---------------------------------------------------

def rescue_gang(ctx: ActionContext, verdict: dict, fence: str) -> dict:
    """Drain one gang off a failed chip through the SAME migration
    timeline as replace-gang — freeze, SpillPool demotion when the
    target is tight, fenced rebind, reaped intent trail — with two
    health-specific legs: the target set excludes every node the
    health plane itself is cordoning (never rescue INTO a draining
    box), and "no target" degrades to a bounded park-and-retry outcome
    instead of a failure (the cooldown + fresh-episode guards bound
    the retry rate; the gang stays schedulable the moment capacity or
    the cordon's decay frees a box)."""
    from vtpu_manager.health import metrics as health_metrics
    from vtpu_manager.health.rescue import unhealthy_nodes
    from vtpu_manager.resilience import failpoints
    tenant = str(verdict.get("tenant", ""))
    node = str(verdict.get("node", ""))
    failpoints.fire("health.rescue", tenant=tenant, node=node)
    if ctx.migrator is None:
        health_metrics.bump_rescue("failed")
        return {"action": "rescue-gang", "ok": False,
                "reason": "no-migrator", "tenant": tenant}
    pod = ctx.pod_for_tenant(tenant)
    if pod is None:
        health_metrics.bump_rescue("failed")
        return {"action": "rescue-gang", "ok": False,
                "reason": "no-pod", "tenant": tenant}
    exclude = {node} | unhealthy_nodes(ctx.client, now=ctx.clock())
    choice = quietest_node(ctx, exclude=exclude)
    if choice is None:
        # bounded park-and-retry: an OUTCOME, not an error — recorded,
        # cooldown started, retried on the next eligible episode
        health_metrics.bump_rescue("parked")
        return {"action": "rescue-gang", "ok": True, "parked": True,
                "reason": "no-target-node", "tenant": tenant,
                "node": node}
    target, worst = choice
    outcome = ctx.migrator.migrate(pod, target, fence)
    ok = bool(outcome.get("ok"))
    health_metrics.bump_rescue("migrated" if ok else "failed")
    return {"action": "rescue-gang", "ok": ok, "tenant": tenant,
            "node": node, "target": target,
            "target_worst_link": round(worst, 3),
            "migration": outcome}


def _migrate_tenant(ctx: ActionContext, verdict: dict, fence: str,
                    action: str, exclude=()) -> dict:
    tenant = str(verdict.get("tenant", ""))
    if ctx.migrator is None:
        return {"action": action, "ok": False, "reason": "no-migrator"}
    pod = ctx.pod_for_tenant(tenant)
    if pod is None:
        return {"action": action, "ok": False, "reason": "no-pod",
                "tenant": tenant}
    choice = quietest_node(ctx, exclude=exclude)
    if choice is None:
        return {"action": action, "ok": False,
                "reason": "no-target-node", "tenant": tenant}
    target, worst = choice
    outcome = ctx.migrator.migrate(pod, target, fence)
    return {"action": action, "ok": bool(outcome.get("ok")),
            "tenant": tenant, "target": target,
            "target_worst_link": round(worst, 3),
            "migration": outcome}
