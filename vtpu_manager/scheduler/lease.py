"""vtha shard leases: leader election with fencing tokens.

Each scheduler shard (a node-pool partition of the cluster,
scheduler/shard.py) is led by at most one scheduler process at a time.
Leadership rests on one Kubernetes Lease object per shard whose
*annotations* carry the whole protocol state — holder identity, a
monotonically increasing **fencing token**, the renew wall-stamp, and the
TTL — and whose ``metadata.resourceVersion`` provides the CAS: every
acquisition and every renewal is a full-object PUT with the expected
resourceVersion, so the apiserver's optimistic concurrency (409 Conflict
on a stale writer) is the single serialization point. No sidecar
consensus service, no extra dependency: the same machinery client-go's
leaderelection package uses.

Three clocks, three jobs:

- the **wall clock** stamps ``renew`` into the lease annotations, because
  expiry must be judged by *other* processes (a standby decides "the
  leader is dead" by comparing its own wall clock to the stamp);
- the **monotonic clock** bounds how long this process may believe its
  own leadership without a confirmed renewal (``held_fresh``). This is
  the paused-process defense: CLOCK_MONOTONIC keeps advancing while a
  process is SIGSTOPped or descheduled, so a leader resumed after a long
  pause observes its own staleness *locally, before any I/O* and refuses
  to stamp new commitments;
- the **fencing token** closes the residual window neither clock can:
  a commitment written just before a pause carries the token of the
  incarnation that wrote it, the takeover bumps the token, and everything
  downstream (the reschedule controller's committed-unbound reaper, the
  new leader's takeover replay) treats an older token as stale by
  definition — no wall-clock guessing about a peer that might merely be
  slow.

``held_fresh`` uses a margin of LEASE_FRESH_FRACTION: the local view of
leadership expires strictly before the takeover threshold other
processes apply, so the old leader stops writing before a new leader can
start — the same renewDeadline < leaseDuration contract as client-go.

Commit-time rejection (split-brain-proof binding): the bind path calls
``confirm()`` between the intent patch and the Binding POST. confirm()
is a CAS renew through the apiserver — a paused-then-resumed ex-leader
whose shard was taken over gets 409 (the new leader's acquisition bumped
the resourceVersion) and the bind aborts *before* the Binding lands. The
already-written intent annotation is exactly the crash trail PR 4 built:
the new leader's takeover replay reaps it by token, never double-places.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable

from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.resilience import failpoints
from vtpu_manager.resilience.policy import RetryPolicy
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

DEFAULT_LEASE_TTL_S = 15.0
DEFAULT_LEASE_NAMESPACE = "vtpu-system"
# held_fresh expires at this fraction of the TTL: the local leadership
# view must die strictly before a standby's takeover threshold (full TTL)
LEASE_FRESH_FRACTION = 0.8

# Lease annotation keys (the protocol state lives in annotations; the
# object's resourceVersion is the CAS handle)
HOLDER_ANN = "vtpu-manager.io/lease-holder"
TOKEN_ANN = "vtpu-manager.io/lease-token"
RENEW_ANN = "vtpu-manager.io/lease-renew"
TTL_ANN = "vtpu-manager.io/lease-ttl"


class LeaseLostError(RuntimeError):
    """This process does not (or can no longer prove it does) hold the
    shard lease. Raised by the fencing checks; every raiser carries the
    shard so operators can grep one line."""


def lease_object_name(shard: str) -> str:
    return f"vtpu-scheduler-{shard}"


def encode_fence(shard: str, token: int, epoch: int = 0) -> str:
    """The pod-annotation stamp: ``<shard>:<token>`` — or, when the
    commitment was made under a vtscale shard *plan* (ScalePipeline gate,
    scheduler/plan.py), ``<shard>:<token>+<epoch>``. Epoch 0 (gate off,
    or no plan published) emits the exact historical two-field form, so
    the gate-off wire bytes are unchanged. This module is the ONLY
    encoder/decoder of the fence wire form — reapers and routers must go
    through parse_fence/parse_fence_epoch, never ad-hoc splits (the
    stalecodec lint rule enforces this)."""
    if epoch:
        return f"{shard}:{token}+{epoch}"
    return f"{shard}:{token}"


def parse_fence(value: str | None) -> tuple[str, int] | None:
    """(shard, token) or None for absent/malformed — garbage reads as
    absent, same posture as parse_bind_intent (a reaper must never act
    on a stamp it cannot interpret). Epoch-suffixed stamps parse to the
    same (shard, token) pair: consumers that predate plans keep working
    and judge staleness by token alone."""
    full = parse_fence_epoch(value)
    if full is None:
        return None
    return full[0], full[1]


def parse_fence_epoch(value: str | None) -> tuple[str, int, int] | None:
    """(shard, token, epoch) or None. Stamps without an epoch suffix —
    every stamp written before vtscale, and every stamp written with the
    gate off — read as epoch 0, which no plan ever rejects (plan epochs
    start at 1)."""
    if not value:
        return None
    shard, sep, raw = value.rpartition(":")
    if not sep or not shard:
        return None
    raw, plus, raw_epoch = raw.partition("+")
    try:
        token = int(raw)
        epoch = int(raw_epoch) if plus else 0
    except ValueError:
        return None
    if epoch < 0:
        return None
    return shard, token, epoch


@dataclass
class LeaseState:
    """Decoded view of one shard lease, as any process reads it."""

    shard: str
    holder: str
    token: int
    renew_wall: float
    ttl_s: float

    def live(self, now_wall: float) -> bool:
        """Whether the stamped holder must still be assumed alive.
        Judged against the TTL *the lease carries* (the writers agree on
        it), never the reader's local default."""
        return (now_wall - self.renew_wall) <= self.ttl_s


def decode_lease_state(shard: str, lease: dict | None) -> LeaseState | None:
    """LeaseState from a lease object; None when the object is absent or
    its annotations are garbage (an undecodable lease is treated as
    expired — acquisition overwrites it with a bumped token)."""
    if lease is None:
        return None
    anns = (lease.get("metadata") or {}).get("annotations") or {}
    holder = anns.get(HOLDER_ANN, "")
    try:
        token = int(anns.get(TOKEN_ANN, ""))
        renew = float(anns.get(RENEW_ANN, ""))
        ttl = float(anns.get(TTL_ANN, ""))
    except (TypeError, ValueError):
        return None
    if not holder or token < 0:
        return None
    return LeaseState(shard=shard, holder=holder, token=token,
                      renew_wall=renew, ttl_s=ttl)


def read_lease_state(client: KubeClient, shard: str,
                     namespace: str = DEFAULT_LEASE_NAMESPACE
                     ) -> LeaseState | None:
    """One-shot probe used by non-scheduler consumers (the reschedule
    controller's token-aware reaper). None means "no usable signal" —
    lease absent, undecodable, or the read failed transiently — and the
    caller falls back to the wall-clock rule."""
    try:
        lease = client.get_lease(namespace, lease_object_name(shard))
    except KubeError as e:
        if e.status != 404:
            log.warning("lease probe for shard %s failed (%s); falling "
                        "back to wall-clock reaping", shard, e)
        return None
    return decode_lease_state(shard, lease)


class ShardLease:
    """One shard's leader lease, from one scheduler process's viewpoint.

    Thread model: the maintenance tick (renew/acquire) and the request
    paths (``fence_annotations``/``confirm`` during filter/bind) may run
    concurrently; ``_cas_lock`` serializes the GET→PUT sequences so two
    of our own threads cannot interleave a CAS and misread a self-induced
    409 as a takeover. ``held``/``token`` reads outside the lock are
    GIL-atomic attribute loads of immutable values.
    """

    def __init__(self, client: KubeClient, shard: str, holder: str,
                 ttl_s: float = DEFAULT_LEASE_TTL_S,
                 namespace: str = DEFAULT_LEASE_NAMESPACE,
                 policy: RetryPolicy | None = None,
                 monotonic: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 object_name: str | None = None):
        self.client = client
        self.shard = shard
        self.holder = holder
        self.ttl_s = ttl_s
        self.namespace = namespace
        # the apiserver Lease object backing this election. Defaults to
        # the per-shard scheduler name; the webhook HA election reuses
        # this class with its own object (WebhookHA gate).
        self.object_name = object_name or lease_object_name(shard)
        # plan epoch folded into fence stamps (vtscale). 0 = no plan:
        # fence_annotations emits the historical two-field form. The
        # ShardedScheduler sets this when a shard plan is adopted.
        self.epoch = 0
        # lease traffic is light (one renew per ttl/3 per shard) but must
        # absorb throttling blips; conflicts (409) are terminal for the
        # policy and classified here
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            base_delay_s=0.05,
                                            deadline_s=5.0)
        self._mono = monotonic
        self._wall = wall
        self._cas_lock = threading.Lock()
        self.held = False
        self.token = 0
        self._version = ""            # resourceVersion of our last write
        self._renewed_mono = 0.0
        # last foreign state observed by a failed acquire (diagnostics +
        # the "led by <holder>" routing error)
        self.observed: LeaseState | None = None
        # counters rendered by shard.py's /metrics block
        self.renewals = 0
        self.conflicts = 0

    # -- local fencing checks (no I/O) --------------------------------------

    def held_fresh(self) -> bool:
        """Leadership this process may still act on: held AND the last
        confirmed renewal is younger than the fresh fraction of the TTL
        on the MONOTONIC clock. A paused-then-resumed process fails this
        before it can touch the network."""
        if not self.held:
            return False
        age = self._mono() - self._renewed_mono
        return age < self.ttl_s * LEASE_FRESH_FRACTION

    def fence_annotations(self) -> dict:
        """The pod-patch stamp for a commitment made under this lease.
        Raises LeaseLostError when leadership cannot be locally proven —
        the caller must fail the pass, not commit unstamped."""
        if not self.held_fresh():
            raise LeaseLostError(
                f"shard {self.shard}: lease not held fresh "
                f"(held={self.held})")
        return {consts.shard_fence_annotation():
                encode_fence(self.shard, self.token, self.epoch)}

    # -- acquisition / renewal (CAS through the apiserver) ------------------

    def _annotations(self, token: int) -> dict:
        return {HOLDER_ANN: self.holder, TOKEN_ANN: str(token),
                RENEW_ANN: repr(self._wall()), TTL_ANN: repr(self.ttl_s)}

    def _adopt(self, lease: dict, token: int) -> None:
        self.held = True
        self.token = token
        self._version = (lease.get("metadata") or {}).get(
            "resourceVersion", "")
        self._renewed_mono = self._mono()

    def _lose(self, why: str) -> None:
        if self.held:
            log.warning("shard %s: lease lost (%s)", self.shard, why)
        self.held = False

    def _read(self) -> tuple[LeaseState | None, str]:
        try:
            lease = self.policy.run(
                lambda: self.client.get_lease(
                    self.namespace, self.object_name),
                op="lease.get")
        except KubeError as e:
            if e.status == 404:
                return None, ""
            raise
        return (decode_lease_state(self.shard, lease),
                (lease.get("metadata") or {}).get("resourceVersion", ""))

    def try_acquire(self) -> bool:
        """Attempt to become (or remain) this shard's leader. Returns
        True when the lease is held after the call. Never blocks on a
        live foreign lease — active-active means standing by, not
        spinning."""
        failpoints.fire("lease.acquire", shard=self.shard)
        with self._cas_lock:
            # vtlint: disable=lock-discipline — the CAS sequence IS the
            # serialized critical section (same posture as bind's serial
            # section); only this lease's own threads contend on it
            return self._try_acquire_locked()

    def _try_acquire_locked(self) -> bool:
        try:
            state, version = self._read()
        except KubeError as e:
            log.warning("shard %s: lease read failed during acquire: %s",
                        self.shard, e)
            return self.held_fresh()
        if state is None and not version:
            # no lease object yet: first writer wins the create
            try:
                created = self.policy.run(
                    lambda: self.client.create_lease(
                        self.namespace, self.object_name,
                        self._annotations(1)),
                    op="lease.create")
            except KubeError as e:
                if e.status == 409:
                    self.conflicts += 1
                    return False        # lost the create race
                log.warning("shard %s: lease create failed: %s",
                            self.shard, e)
                return False
            self._adopt(created, 1)
            log.info("shard %s: lease created and acquired (token=1) "
                     "by %s", self.shard, self.holder)
            return True
        now = self._wall()
        if state is not None and state.holder == self.holder \
                and state.live(now):
            if self.token == state.token:
                # our own live lease (renewal path re-entered via
                # acquire): refresh the stamp, keep the token — same
                # incarnation
                return self._cas(self.token, version, takeover=False)
            # same holder IDENTITY, different incarnation: a process
            # restarted with a stable --scheduler-id inside the TTL
            # window. This MUST take over with a bumped token — adopting
            # the dead incarnation's token would shield its interrupted
            # bind intents from every reaper (replay skips token >= ours,
            # the controller sees token-current + lease-live and defers
            # forever).
            return self._cas(state.token + 1, version, takeover=True)
        if state is None or not state.live(now):
            # expired (or undecodable) lease: take over with a bumped
            # fencing token — THE line that makes every commitment of the
            # previous holder provably stale
            new_token = (state.token if state is not None else 0) + 1
            return self._cas(new_token, version, takeover=True)
        self.observed = state
        self._lose(f"held live by {state.holder} (token={state.token})")
        return False

    def _cas(self, token: int, version: str, takeover: bool) -> bool:
        try:
            updated = self.policy.run(
                lambda: self.client.update_lease(
                    self.namespace, self.object_name,
                    self._annotations(token), version),
                op="lease.cas")
        except KubeError as e:
            if e.status == 409:
                self.conflicts += 1
                self._lose("CAS conflict: another scheduler wrote first")
                return False
            log.warning("shard %s: lease CAS failed: %s", self.shard, e)
            return False
        self._adopt(updated, token)
        if takeover:
            log.info("shard %s: lease ACQUIRED by %s (token=%d)",
                     self.shard, self.holder, token)
        return True

    def renew(self) -> None:
        """Refresh the renew stamp via CAS, keeping the token. Raises
        LeaseLostError on definitive loss (a foreign writer moved the
        lease) and re-raises KubeError on transient failure — a blip must
        NOT drop leadership (held_fresh decays it honestly instead)."""
        failpoints.fire("lease.renew", shard=self.shard)
        with self._cas_lock:
            # vtlint: disable=lock-discipline — see try_acquire
            self._renew_locked()

    def _renew_locked(self) -> None:
        if not self.held:
            raise LeaseLostError(f"shard {self.shard}: not held")
        for attempt in (0, 1):
            try:
                updated = self.policy.run(
                    lambda: self.client.update_lease(
                        self.namespace, self.object_name,
                        self._annotations(self.token), self._version),
                    op="lease.renew")
            except KubeError as e:
                if e.status != 409:
                    raise          # transient: leadership decays locally
                self.conflicts += 1
                # conflict: someone wrote since our version. If that
                # someone was US (a concurrent renew's response got
                # lost), re-sync and retry once; anyone else took over.
                state, version = self._read()
                if attempt == 0 and state is not None \
                        and state.holder == self.holder \
                        and state.token == self.token:
                    self._version = version
                    continue
                holder = state.holder if state is not None else "?"
                token = state.token if state is not None else -1
                self._lose(f"taken over by {holder} (token={token})")
                raise LeaseLostError(
                    f"shard {self.shard}: lease taken over by {holder} "
                    f"(token={token} > {self.token})") from e
            self._adopt(updated, self.token)
            self.renewals += 1
            return

    def confirm(self) -> None:
        """Commit-time fence: prove leadership *through the apiserver*
        immediately before a side-effecting commit (the Binding POST).
        Local staleness, a takeover, or any inability to prove ownership
        all read as LeaseLostError — when in doubt, the commit must not
        happen."""
        if not self.held_fresh():
            raise LeaseLostError(
                f"shard {self.shard}: lease expired locally "
                "(paused or renewals failing)")
        try:
            self.renew()
        except LeaseLostError:
            raise
        except KubeError as e:
            raise LeaseLostError(
                f"shard {self.shard}: cannot confirm lease: {e}") from e

    def release(self) -> None:
        """Best-effort graceful handoff: stamp the lease expired so a
        standby can take over without waiting out the TTL. Failure is
        fine — the TTL path covers it."""
        with self._cas_lock:
            # vtlint: disable=lock-discipline — see try_acquire
            if not self.held:
                return
            anns = self._annotations(self.token)
            anns[RENEW_ANN] = "0"
            try:
                self.policy.run(
                    lambda: self.client.update_lease(
                        self.namespace, self.object_name,
                        anns, self._version),
                    op="lease.release")
            except KubeError as e:
                log.warning("shard %s: lease release failed (%s); TTL "
                            "expiry will cover it", self.shard, e)
            self.held = False
