"""Structured allocation-failure reasons, aggregated for events.

Reference: pkg/scheduler/reason/reason.go:1-387 — per-device and per-node
failure reasons are counted, bucketed, and collapsed into one human-readable k8s
event so a 5000-node rejection doesn't produce 5000 events.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field

# Device-level reasons
NO_FREE_SLOTS = "NoFreeSlots"
INSUFFICIENT_CORES = "InsufficientCores"
INSUFFICIENT_MEMORY = "InsufficientMemory"
TYPE_EXCLUDED = "TypeExcluded"
UUID_EXCLUDED = "UuidExcluded"
UNHEALTHY = "Unhealthy"

# vtheal cordon reasons (HealthPlane gate): the health plane marked a
# chip degraded/failed (hard admission gate, capacity-shaped) or a
# required ICI link failed so no submesh box avoids it. Device- and
# node-level respectively — the doctor renders both as cordons.
UNHEALTHY_CHIP = "UnhealthyChip"
DEGRADED_LINK = "DegradedLink"

# Node-level reasons
NODE_NO_DEVICES = "NodeNoDevices"
NODE_INSUFFICIENT_CAPACITY = "NodeInsufficientCapacity"
NODE_LABEL_MISMATCH = "NodeLabelMismatch"
NODE_TOPOLOGY_UNSATISFIED = "TopologyUnsatisfied"
NODE_GANG_UNALIGNED = "GangUnaligned"
NODE_OUTSIDE_SHARD = "NodeOutsideShard"

# Pod-level reasons (vtexplain decision records: rejections that hit the
# whole pass, not one node)
POD_SHARD_NOT_LED = "ShardNotLed"
POD_LEASE_LOST = "LeaseLost"


@dataclass
class FailureReasons:
    """Counter of reasons across devices/nodes for one pod's filter pass."""

    counts: Counter = field(default_factory=Counter)
    samples: dict[str, str] = field(default_factory=dict)  # reason -> example

    def add(self, reason: str, subject: str = "") -> None:
        self.counts[reason] += 1
        if subject and reason not in self.samples:
            self.samples[reason] = subject

    def merge(self, other: "FailureReasons") -> None:
        self.counts.update(other.counts)
        for k, v in other.samples.items():
            self.samples.setdefault(k, v)

    def is_empty(self) -> bool:
        return not self.counts

    def summary(self) -> str:
        """One aggregated message, most-frequent first (event text)."""
        if not self.counts:
            return ""
        parts = []
        for reason, count in self.counts.most_common():
            sample = self.samples.get(reason)
            parts.append(f"{reason} x{count}" +
                         (f" (e.g. {sample})" if sample else ""))
        return "; ".join(parts)
