"""Cross-pod gang mesh alignment.

Reference: cross-pod NVLink gang rail alignment (allocator.go:379-660, gang
sibling domain resolution filter_predicate.go:475-539, design doc
docs/cross_pod_nvlink_topology_design.md): pods of one gang landing on
different nodes should occupy *aligned* device positions so the inter-node
fabric (there NVLink rails, here inter-host ICI on a multi-host slice) lines
up neighbor-to-neighbor.

TPU design: the first gang member to schedule records its mesh-window origin
in a gang-origin annotation; later members prefer the same origin on their
own hosts. On a v5e/v5p multi-host slice, equal per-host origins mean the
gang's chips occupy congruent sub-meshes, so cross-host ICI neighbors align.
"""

from __future__ import annotations

from vtpu_manager.device.types import NodeInfo
from vtpu_manager.util import consts


def gang_origin_annotation() -> str:
    return f"{consts.annotation_domain()}/gang-origin"


def encode_origin(origin: tuple[int, int]) -> str:
    return f"{origin[0]},{origin[1]}"


def decode_origin(raw: str | None) -> tuple[int, int] | None:
    if not raw:
        return None
    try:
        x, _, y = raw.partition(",")
        return (int(x), int(y))
    except ValueError:
        return None


def _is_sibling(pod: dict, gang_name: str, namespace: str) -> bool:
    """Same gang = same resolved name AND same namespace. Members may
    carry the name in DIFFERENT dialects (one via Volcano markup, one
    via ours) — resolve, don't compare raw annotations. PodGroup names
    are namespace-scoped in every ecosystem dialect, so two tenants both
    calling their gang 'train' must never merge."""
    from vtpu_manager.util.gangname import resolve_gang_name
    meta = pod.get("metadata") or {}
    return (resolve_gang_name(pod)[0] == gang_name
            and meta.get("namespace", "default") == namespace)


def resolve_gang_origin(gang_name: str, all_pods: list[dict],
                        namespace: str = "default"
                        ) -> tuple[int, int] | None:
    """Find the origin already chosen by any sibling of the gang."""
    if not gang_name:
        return None
    for pod in all_pods:
        if not _is_sibling(pod, gang_name, namespace):
            continue
        anns = (pod.get("metadata") or {}).get("annotations") or {}
        origin = decode_origin(anns.get(gang_origin_annotation()))
        if origin is not None:
            return origin
    return None


def chosen_origin(info: NodeInfo, claims) -> tuple[int, int] | None:
    """Derive the mesh origin (min coords) of a claim set on a node."""
    coords = []
    for claim in claims.all_claims():
        usage = info.devices.get(claim.uuid)
        if usage is not None:
            coords.append(usage.spec.coords)
    if not coords:
        return None
    return (min(c[0] for c in coords), min(c[1] for c in coords))


def live_siblings(gang_name: str, self_uid: str,
                  all_pods: list[dict],
                  namespace: str = "default") -> list[dict]:
    """Gang members that still COUNT: same gang annotation, not the pod
    being scheduled itself (a re-filtered committed pod must not anchor
    to its own stale pre-allocation), and alive by the same
    should_count_pod rule capacity accounting uses (a Failed member's
    lingering annotations must not pull the replacement to its old
    slice). Resolved ONCE per filter pass — the per-node helpers below
    take this small list, not the cluster pod list."""
    if not gang_name:
        return []
    from vtpu_manager.device.types import should_count_pod
    out = []
    for pod in all_pods:
        meta = pod.get("metadata") or {}
        if meta.get("uid", "") == self_uid:
            continue
        if not _is_sibling(pod, gang_name, namespace):
            continue
        if not should_count_pod(pod):
            continue
        out.append(pod)
    return out


def live_siblings_indexed(members: list[dict],
                          self_uid: str) -> list[dict]:
    """live_siblings() over a pre-resolved same-gang member list (the
    cluster snapshot's gang index, already keyed by resolved name and
    namespace) — O(gang) instead of O(cluster). The liveness rule is the
    same: drop the pod being scheduled and members that no longer count
    by should_count_pod (the time-dependent part, so it is evaluated at
    use time, never cached in the index)."""
    from vtpu_manager.device.types import should_count_pod
    return [pod for pod in members
            if (pod.get("metadata") or {}).get("uid", "") != self_uid
            and should_count_pod(pod)]


def sibling_node_names(siblings: list[dict]) -> set[str]:
    """Nodes hosting (or committed to host) members of the gang
    (`siblings` is a pre-resolved live_siblings() list)."""
    out = set()
    for pod in siblings:
        anns = (pod.get("metadata") or {}).get("annotations") or {}
        node = ((pod.get("spec") or {}).get("nodeName")
                or anns.get(consts.predicate_node_annotation()))
        if node:
            out.add(node)
    return out


def sibling_domains(siblings: list[dict],
                    domain_by_node: dict[str, str]) -> set[str]:
    """ICI mesh domains the gang already occupies — the L2 cross-node
    affinity signal (reference multinode_topology_aware_scheduling
    _analysis.md: after L0 intra-node adjacency, cluster gang members
    onto one multi-host slice; members split across domains pay DCN for
    every collective). domain_by_node: node -> mesh_domain ('' = none)."""
    return {d for d in (domain_by_node.get(n, "")
                        for n in sibling_node_names(siblings))
            if d}


def sibling_anchor_cells(node_name: str, siblings: list[dict],
                         registry) -> set | None:
    """Mesh cells held by same-gang siblings already placed on THIS node —
    the anchor for same-node cross-pod adjacency (reference
    cross_pod_nvlink_topology_design.md L0: a sibling pair split across
    NVLink components loses the fabric; the torus analogue is landing the
    next sibling's window edge-adjacent so gang collectives stay on ICI).

    Placement is attributed by spec.nodeName OR the predicate-node
    annotation: during a gang burst the siblings that matter most are
    committed (annotations patched) but not yet bound — nodeName alone
    would miss exactly them and the anchor would never fire. `siblings`
    is the pre-resolved live_siblings() list.
    """
    from vtpu_manager.device.types import get_pod_device_claims
    by_uuid = registry.chip_by_uuid()
    cells = set()
    for pod in siblings:
        anns = (pod.get("metadata") or {}).get("annotations") or {}
        on_node = ((pod.get("spec") or {}).get("nodeName") == node_name
                   or anns.get(consts.predicate_node_annotation())
                   == node_name)
        if not on_node:
            continue
        claims = get_pod_device_claims(pod)
        if claims is None:
            continue
        for claim in claims.all_claims():
            chip = by_uuid.get(claim.uuid)
            if chip is not None:
                cells.add(chip.coords)
    return cells or None
