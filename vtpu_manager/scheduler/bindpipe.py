"""vtscale pipelined bind commit: one lease CAS amortized over a wave.

The serial bind path (scheduler/bind.py) spends 3–4 sequential apiserver
round-trips per pod: GET pod, PATCH allocating+intent+fence, the lease
``confirm()`` CAS, POST Binding. Under load those round-trips — not
CPU — are the bind ceiling. This module batches the hot path per shard:
concurrent extender bind calls coalesce into a **wave** executed by one
leader thread in three stages:

- **Stage A (concurrent, per pod)**: GET + the exact serial-path checks
  (``BindPredicate.validate_commitment``) + the exact serial-path
  allocating+intent+fence patch (``BindPredicate.commit_patch``) —
  byte-identical patch bytes, issued across the wave by a small thread
  pool instead of one at a time.
- **Stage B (once per wave)**: a single ``fence.confirm()`` — the CAS
  lease renew — for the whole wave.
- **Stage C (concurrent, per pod)**: the Binding POSTs.

Safety is the PR 6 fencing argument unchanged: every pod's intent+fence
patch is on the apiserver BEFORE the wave's confirm, and no Binding is
posted unless that confirm succeeds. A crash anywhere in the window
leaves per-pod intent trails (never per-wave state) that the PR 4
reapers and the takeover replay converge pod by pod — a torn wave is
just N torn serial binds. The ``bind.batch`` failpoint fires inside
stage A, after each pod's patch, to prove exactly that in chaos runs.

Degradation discipline: any per-pod *fault* (apiserver error, injected
error, unexpected exception) degrades THAT pod to the serial path after
the wave's serial sections release — never the wave. Deterministic
rejections (no pre-allocation, wrong node, expired commitment) return
the serial path's exact error strings directly. A failed wave confirm
fails every pod in the wave with the serial path's fence-rejection
error — their intents are the same reapable trail a serial fence
rejection leaves.

Same-pod ordering: the wave enters the bind SerialLocker section of
every pod it carries for the full patch→confirm→bind span (one global
section when SerialBindNode serializes everything), and a pod appearing
twice in one wave keeps only its first occurrence — the duplicate
degrades to the serial path, which queues on the pod's section behind
the wave.

Gate story (ScalePipeline, default off): this module is never
constructed; binds run scheduler/bind.py unchanged.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from vtpu_manager import explain
from vtpu_manager.client.kube import KubeError
from vtpu_manager.resilience import failpoints
from vtpu_manager.scheduler.bind import BindPredicate, BindResult
from vtpu_manager.scheduler.lease import LeaseLostError
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

DEFAULT_MAX_WAVE = 32
DEFAULT_MAX_WAIT_S = 0.002
DEFAULT_WORKERS = 8
# a follower gives up on its wave leader (crashed mid-wave, chaos) and
# converges through the serial path on its own
FOLLOWER_PATIENCE_S = 5.0


class _Waiter:
    __slots__ = ("ns", "name", "node", "event", "result")

    def __init__(self, ns: str, name: str, node: str):
        self.ns = ns
        self.name = name
        self.node = node
        self.event = threading.Event()
        self.result: BindResult | None = None

    @property
    def key(self) -> str:
        return f"{self.ns}/{self.name}"

    def finish(self, result: BindResult) -> None:
        self.result = result
        self.event.set()


class BindCommitPipeline:
    """Wave-batching front of one shard's BindPredicate.

    Exposes the same ``bind(args) -> BindResult`` surface; callers block
    until their pod's commit completes (the extender contract is
    synchronous), but across callers the apiserver traffic is pipelined.
    """

    def __init__(self, serial: BindPredicate,
                 max_wave: int = DEFAULT_MAX_WAVE,
                 max_wait_s: float = DEFAULT_MAX_WAIT_S,
                 workers: int = DEFAULT_WORKERS,
                 patience_s: float = FOLLOWER_PATIENCE_S):
        self.serial = serial
        self.max_wave = max(1, int(max_wave))
        self.max_wait_s = max(0.0, float(max_wait_s))
        self.patience_s = float(patience_s)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(workers)),
            thread_name_prefix="vtpu-bindwave")
        self._queue: list[_Waiter] = []
        self._cond = threading.Condition()
        self._leader = threading.Lock()
        # counters rendered by render_pipeline_metrics (one home for the
        # vtpu_bind_wave_* series — metrics-registry rule)
        self.waves = 0
        self.wave_pods = 0
        self.degraded = 0
        self.confirm_failures = 0

    # -- public surface ------------------------------------------------------

    def bind(self, args: dict) -> BindResult:
        ns = args.get("PodNamespace") or args.get("podNamespace") or "default"
        name = args.get("PodName") or args.get("podName") or ""
        node = args.get("Node") or args.get("node") or ""
        w = _Waiter(ns, name, node)
        with self._cond:
            self._queue.append(w)
            self._cond.notify_all()
        deadline = time.monotonic() + self.patience_s
        while True:
            if self._leader.acquire(blocking=False):
                try:
                    if not w.event.is_set():
                        self._lead_wave()
                finally:
                    self._leader.release()
            if w.event.wait(0.05):
                return w.result if w.result is not None else BindResult(
                    error="bind wave produced no result")
            if time.monotonic() > deadline:
                # wave leader died (chaos crash) with our pod possibly
                # half-committed: the serial path re-patches the same
                # bytes and converges, exactly like a bind retry
                self._forget(w)
                self.degraded += 1
                return self._serial_bind(w)

    def stats(self) -> dict:
        return {"waves": self.waves, "wave_pods": self.wave_pods,
                "degraded": self.degraded,
                "confirm_failures": self.confirm_failures}

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)

    # -- wave machinery ------------------------------------------------------

    def _forget(self, w: _Waiter) -> None:
        with self._cond:
            if w in self._queue:
                self._queue.remove(w)

    def _serial_bind(self, w: _Waiter) -> BindResult:
        return self.serial.bind({"PodNamespace": w.ns, "PodName": w.name,
                                 "Node": w.node})

    def _drain(self) -> list[_Waiter]:
        """Wait briefly for the wave to fill, then take it."""
        deadline = time.monotonic() + self.max_wait_s
        with self._cond:
            while len(self._queue) < self.max_wave:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cond.wait(timeout=left):
                    break
            wave, self._queue = (self._queue[:self.max_wave],
                                 self._queue[self.max_wave:])
            return wave

    def _lead_wave(self) -> None:
        wave = self._drain()
        if not wave:
            return
        self.waves += 1
        self.wave_pods += len(wave)
        batch_seq = self.waves
        fence = self.serial.fence
        shard = getattr(fence, "shard", "") if fence is not None else ""
        epoch = getattr(fence, "epoch", 0) if fence is not None else 0
        batch_id = f"{shard or 'solo'}:w{batch_seq}"

        # first occurrence per pod rides the wave; duplicates degrade to
        # the serial path, which queues behind the wave's section
        seen: set[str] = set()
        unique: list[_Waiter] = []
        degrade: list[_Waiter] = []
        for w in wave:
            if w.key in seen:
                degrade.append(w)
            else:
                seen.add(w.key)
                unique.append(w)

        done: dict[str, tuple[BindResult, dict | None]] = {}
        with contextlib.ExitStack() as stack:
            locker = self.serial.locker
            if getattr(locker, "_serialize_all", False):
                # SerialBindNode: one global section covers the wave —
                # entering it per pod would self-deadlock, and the
                # gate's semantics (no concurrent bind I/O) still hold
                stack.enter_context(locker.section())
            else:
                for w in unique:
                    stack.enter_context(locker.section(w.key))

            staged: list[tuple[_Waiter, dict | None]] = []
            futures = {w: self._pool.submit(self._stage_patch, w)
                       for w in unique}
            for w, fut in futures.items():
                try:
                    verdict, pod = fut.result()
                except Exception as e:
                    # any per-pod fault — apiserver error, lost local
                    # lease freshness, an injected error — degrades THAT
                    # pod to the serial path; CrashFailpoint is a
                    # BaseException and tears the whole wave like a real
                    # process death would
                    log.debug("wave %s: pod %s degrades to serial (%s)",
                              batch_id, w.key, e)
                    degrade.append(w)
                    continue
                if verdict is not None:
                    done[w.key] = (verdict, pod)       # deterministic
                else:
                    staged.append((w, pod))

            confirm_err = ""
            if fence is not None and staged:
                try:
                    # ONE CAS renew fences the whole wave: every staged
                    # pod's intent+fence patch is already on the
                    # apiserver, and no Binding below is posted unless
                    # this succeeds — the serial safety window, amortized
                    fence.confirm()
                except LeaseLostError as e:
                    self.confirm_failures += 1
                    confirm_err = (f"bind rejected at commit "
                                   f"(lease fence): {e}")

            if confirm_err:
                for w, pod in staged:
                    done[w.key] = (BindResult(error=confirm_err), pod)
            else:
                binds = {w: self._pool.submit(self._stage_binding, w)
                         for w, _pod in staged}
                pods = dict(staged)
                for w, fut in binds.items():
                    try:
                        fut.result()
                    except KubeError as e:
                        done[w.key] = (BindResult(error=f"bind failed: "
                                                        f"{e}"), pods[w])
                        continue
                    done[w.key] = (BindResult(), pods[w])

        for w in unique:
            if w.key not in done:
                continue
            result, pod = done[w.key]
            self._explain(w, result, pod, batch_id, epoch, shard)
            w.finish(result)
        self.degraded += len(degrade)
        for w in degrade:
            w.finish(self._serial_bind(w))

    def _stage_patch(self, w: _Waiter
                     ) -> tuple[BindResult | None, dict | None]:
        """(deterministic verdict | None, pod). Raises on faults — the
        caller degrades the pod to the serial path then."""
        pod = self.serial.policy.run(
            lambda: self.serial.client.get_pod(w.ns, w.name),
            op="bind.get_pod")
        invalid = self.serial.validate_commitment(pod, w.node)
        if invalid:
            return BindResult(error=invalid), pod
        patch = self.serial.commit_patch(pod, w.node)
        if patch is not None:
            self.serial.policy.run(
                lambda: self.serial.client.patch_pod_annotations(
                    w.ns, w.name, patch),
                op="bind.patch")
        uid = (pod.get("metadata") or {}).get("uid", "")
        failpoints.fire("bind.batch", pod_uid=uid, node=w.node)
        return None, pod

    def _stage_binding(self, w: _Waiter) -> None:
        self.serial.policy.run(
            lambda: self.serial.client.bind_pod(w.ns, w.name, w.node),
            op="bind.binding")

    def _explain(self, w: _Waiter, result: BindResult, pod: dict | None,
                 batch_id: str, epoch: int, shard: str) -> None:
        if not explain.is_enabled():
            return
        meta = (pod or {}).get("metadata") or {}
        anns = meta.get("annotations") or {}
        explain.bind_outcome(
            w.ns, w.name, w.node, pod_uid=meta.get("uid", ""),
            trace_id=anns.get(consts.trace_id_annotation(), ""),
            error=result.error, shard=shard, batch=batch_id,
            plan_epoch=epoch)


def render_pipeline_metrics(pipelines: list[BindCommitPipeline]) -> str:
    """The vtpu_bind_wave_* exposition block; "" with no pipelines so
    the gate-off scrape stays byte-identical."""
    if not pipelines:
        return ""
    waves = sum(p.waves for p in pipelines)
    pods = sum(p.wave_pods for p in pipelines)
    degraded = sum(p.degraded for p in pipelines)
    confirm = sum(p.confirm_failures for p in pipelines)
    return (
        "# TYPE vtpu_bind_waves_total counter\n"
        f"vtpu_bind_waves_total {waves}\n"
        "# TYPE vtpu_bind_wave_pods_total counter\n"
        f"vtpu_bind_wave_pods_total {pods}\n"
        "# TYPE vtpu_bind_wave_degraded_total counter\n"
        f"vtpu_bind_wave_degraded_total {degraded}\n"
        "# TYPE vtpu_bind_wave_confirm_failures_total counter\n"
        f"vtpu_bind_wave_confirm_failures_total {confirm}\n")
