"""Watch-driven incremental cluster snapshot for the scheduler hot path.

Reference: the Go extender reads nodes and resident pods from client-go
informers (filter_predicate.go:541-866) — decode and indexing happen once
per *change*, not once per *decision*. The TTL-LIST caches this replaces
(filter.py) re-decode every node registry and every resident claim set on
each refresh even when nothing changed: O(nodes + resident pods) JSON per
filter pass. This module is the informer analogue: one versioned LIST
seeds the state, a WATCH streams ADDED/MODIFIED/DELETED/BOOKMARK events,
and each event updates only the structures it touches — the decoded
registry, the partitioned resident-pod list, the counted-claims
aggregates, and the ``fast_free_totals`` triple per node, plus a gang
index keyed by resolved group name. A filter pass over an unchanged
5000-node cluster decodes zero JSON (asserted via
``device.types.DECODE_COUNTERS`` in test_snapshot.py).

Consistency model, in line with the reference informer semantics:

- Every mutation swaps a whole immutable-by-convention ``NodeEntry`` into
  ``_entries`` under ``_lock``; a filter pass reads the live dict (no
  copy). CPython dict value replacement is safe against concurrent
  iteration, and node add/remove (the only structural mutations) rebuild
  the dict object so in-flight iterations keep a coherent older view.
- Watch I/O and all JSON decode happen OUTSIDE ``_lock`` (vtlint
  lock-discipline is load-bearing here): events are *prepared* — claims
  classified, registries decoded — on the pumping thread, and only the
  dict swaps run under the lock.
- Relist-on-410: when the watch's resourceVersion has been compacted
  away the whole state is rebuilt from a fresh versioned LIST, exactly
  the client-go reflector contract.

Time-dependent counting (should_count_pod's stuck grace) is folded in by
classifying each pod once at apply time into *unconditional* (counts
until an event changes it) or *conditional* (counts until a wall-clock
expiry — pre-allocated but not yet confirmed). The per-node
``base_free`` covers unconditional claims; passes fold the handful of
live conditionals (and the filter's assumed overlay) arithmetically,
with zero decode.
"""

from __future__ import annotations

import bisect
import logging
import threading
import time

from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.clustercache import advertise as cc_advertise
from vtpu_manager.compilecache import antistorm
from vtpu_manager.fragmentation import codec as frag_codec
from vtpu_manager.health import codec as health_codec
from vtpu_manager.quota import victimcost as vc_mod
from vtpu_manager.device import types as dt
from vtpu_manager.device.claims import container_kinds, effective_claims
from vtpu_manager.resilience import failpoints
from vtpu_manager.resilience.policy import (CircuitBreaker,
                                            CircuitOpenError, RetryPolicy)
from vtpu_manager.overcommit import ratio as oc_mod
from vtpu_manager.telemetry import pressure as tel_pressure
from vtpu_manager.topology import linkload as tl_mod
from vtpu_manager.util import stalecodec
from vtpu_manager.util import consts
from vtpu_manager.util.gangname import resolve_gang_name
from vtpu_manager.utilization import headroom as util_headroom

log = logging.getLogger(__name__)

_EMPTY_FREE = (0, 0, 0)


class NodeEntry:
    """One node's precomputed scheduling view. Instances are never mutated
    after publication — every change swaps a fresh entry into the
    snapshot, so a pass holding a reference sees one coherent state."""

    __slots__ = ("name", "node", "labels", "registry", "resident",
                 "counted", "conditional", "base_free", "rank_key",
                 "generation", "pressure", "fp_recent", "headroom",
                 "overcommit", "warm", "victim_costs", "linkload",
                 "chiphealth", "frag")

    def __init__(self, name: str, node: dict, labels: dict, registry,
                 resident: dict, counted: list, conditional: list,
                 base_free: tuple, rank_key: int, generation: int,
                 pressure=None, fp_recent=(), headroom=None,
                 overcommit=None, warm=None, victim_costs=None,
                 linkload=None, chiphealth=None, frag=None):
        self.name = name
        self.node = node                  # raw node object (shared ref)
        self.labels = labels
        self.registry = registry          # decoded NodeDeviceRegistry | None
        self.resident = resident          # uid -> pod (scheduled here)
        self.counted = counted            # [(uid, claims)] unconditional
        self.conditional = conditional    # [(uid, claims, expiry_wall_s)]
        self.base_free = base_free        # free totals over `counted` only
        self.pressure = pressure          # vttel NodePressure | None
        # vtuse reclaimable-headroom rollup (NodeHeadroom | None),
        # decoded at event apply/relist like pressure; observe-only
        # this PR (logged + counted, never scored) and staleness is
        # re-judged at use time so a dead publisher decays
        self.headroom = headroom
        # vtovc overcommit policy rollup (NodeOvercommit | None),
        # decoded at event apply/relist like pressure; the filter
        # re-judges staleness + class at every visit, so a dead policy
        # publisher decays to the physical admission gate
        self.overcommit = overcommit
        # vtcs warm-keys advertisement (NodeWarmKeys | None), decoded at
        # event apply/relist like pressure; warm_term re-judges
        # staleness at score time so a dead advertiser's phantom warmth
        # decays instead of attracting pods forever
        self.warm = warm
        # victim-cost rollup (NodeVictimCosts | None), decoded at event
        # apply/relist; the preempt path re-judges freshness at use
        # time, degrading the victim sort to priority-only
        self.victim_costs = victim_costs
        # vtici link-load rollup (NodeLinkLoad | None), decoded at
        # event apply/relist like pressure; the filter re-judges
        # staleness at every visit (load_map), so a dead publisher
        # decays to no link signal instead of steering on a ghost
        self.linkload = linkload
        # vtheal chip-health rollup (NodeChipHealth | None), decoded at
        # event apply/relist like pressure; cordon_mask/dead_links
        # re-judge staleness at every visit, so a dead publisher
        # UN-cordons (the legacy registry healthy flip is the
        # non-decaying backstop for a truly dead chip)
        self.chiphealth = chiphealth
        # vtfrag node-published fragmentation rollup (NodeFrag | None),
        # decoded at event apply/relist like pressure; observe-only —
        # the rollup/smi surfaces re-judge staleness at report time
        # (frag_is_fresh), so a dead publisher's node drops to
        # no-signal instead of pinning its last placeability claim
        self.frag = frag
        # vtcc anti-storm: residents' (program_fingerprint, placed_ts)
        # pairs inside the storm window at build time; decay is
        # re-judged at penalty time (a quiet node emits no events)
        self.fp_recent = fp_recent
        # capacity-rank key over free totals INCLUDING build-time-live
        # conditionals — same formula the filter's TTL path sorts on
        # (free_cores + (free_memory >> 24) + free_number). A grace
        # expiry between events makes it pessimistic (node ranked as
        # less free than it is) until the lazy prune republishes; exact
        # totals are always recomputed at visit time.
        self.rank_key = rank_key
        self.generation = generation


class SnapshotStats:
    """Pump/apply counters, exported as Prometheus counters by routes.py
    and asserted by the O(changed) tests. GIL-atomic int adds."""

    __slots__ = ("events_applied", "pod_events", "node_events", "bookmarks",
                 "relists", "watch_errors", "reconnects",
                 "registry_decodes", "claims_decodes", "breaker_open",
                 "filtered_nodes")

    def __init__(self) -> None:
        self.events_applied = 0
        self.pod_events = 0
        self.node_events = 0
        self.bookmarks = 0
        self.relists = 0
        self.watch_errors = 0
        self.reconnects = 0            # background-loop recovery cycles
        self.registry_decodes = 0      # decodes performed at apply time
        self.claims_decodes = 0
        self.breaker_open = 0          # LIST/watch rejected by open breaker
        self.filtered_nodes = 0        # node events outside this shard

    def as_dict(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in self.__slots__}


def _classify_pod(pod: dict, stuck_grace_s: float,
                  stats: SnapshotStats | None = None):
    """(claims, expiry) mirroring should_count_pod + counted_claims:
    claims is the phase-peak effective set if the pod can count, else
    None; expiry None means it counts until an event changes it, else it
    counts while now <= expiry (wall clock — predicate_time crosses
    processes). Countedness only *decreases* with time between events
    (grace expiry); every increase arrives as a watch event."""
    if (pod.get("status") or {}).get("phase", "") in ("Succeeded", "Failed"):
        return None, None
    anns = (pod.get("metadata") or {}).get("annotations") or {}
    real = anns.get(consts.real_allocated_annotation())
    pre = anns.get(consts.pre_allocated_annotation())
    if not real and not pre:
        return None, None
    if stats is not None:
        stats.claims_decodes += 1
    claims = dt.get_pod_device_claims(pod)
    if claims is None:
        return None, None
    kinds, init_order = container_kinds(pod.get("spec") or {})
    claims = effective_claims(claims, kinds, init_order)
    if real:
        return claims, None
    ts = consts.parse_predicate_time(anns)
    if ts is None:
        # absent/garbage stamp: count forever (never free capacity on a
        # parse failure — same posture as should_count_pod)
        return claims, None
    grace = stuck_grace_s
    override = anns.get(consts.scheduler_stuck_grace_annotation())
    if override:
        try:
            grace = float(override)
        except ValueError:
            pass
    return claims, ts + grace


def entry_counted(entry: NodeEntry, now: float) -> list:
    """Merged (uid, claims) pairs that count at ``now`` — identical
    membership to counted_claims() over the node's residents, from
    pre-decoded state."""
    if not entry.conditional:
        return entry.counted
    return entry.counted + [(uid, claims)
                            for uid, claims, expiry in entry.conditional
                            if now <= expiry]


def entry_free_totals(entry: NodeEntry, extra_claims: list,
                      now: float) -> tuple[int, int, int]:
    """Free totals with the pass's extra (assumed) claim sets folded in.
    The steady state — no conditionals, no assumed — returns the
    precomputed triple untouched; otherwise one fast_free_totals over
    already-decoded claims (per-chip clamping is non-linear, so partial
    sums cannot simply be subtracted)."""
    if entry.registry is None:
        return _EMPTY_FREE
    if not entry.conditional and not extra_claims:
        return entry.base_free
    sets = [claims for _, claims in entry_counted(entry, now)]
    sets.extend(extra_claims)
    return dt.fast_free_totals(entry.registry, sets)


class ClusterSnapshot:
    """Incremental list+watch view of nodes and pods for the scheduler.

    Two pump modes share one implementation: tests and the perf harness
    call ``ensure_fresh()`` at pass start (the fake client's watch
    returns immediately), while a real deployment runs
    ``start_background()`` so a daemon thread consumes the streaming
    watch and passes observe an always-fresh snapshot.
    """

    def __init__(self, client: KubeClient,
                 stuck_grace_s: float = consts.DEFAULT_STUCK_GRACE_S,
                 watch_timeout_s: float = 0.0,
                 retry_policy: RetryPolicy | None = None,
                 node_selector=None,
                 list_breaker: CircuitBreaker | None = None,
                 watch_breaker: CircuitBreaker | None = None):
        self.client = client
        self.stuck_grace_s = stuck_grace_s
        self.watch_timeout_s = watch_timeout_s
        # shapes the background loop's failure backoff only (the loop
        # drives its own retries — watch streams are not one-shot calls)
        self.retry_policy = retry_policy or RetryPolicy(
            base_delay_s=0.5, max_delay_s=30.0)
        # vtha shard scoping: nodes failing the predicate are invisible
        # to this snapshot (their events count as filtered_nodes); pods
        # stay global — pending pods carry gang signals and resident
        # pods of foreign nodes are inert without a NodeEntry.
        self._node_selector = node_selector
        # vtfault: one breaker per verb-family. A sustained LIST or
        # watch-open failure opens its breaker so the pump stops queueing
        # doomed requests against a down apiserver; breaker_open in
        # SnapshotStats (and vtpu_circuit_state on /metrics) make the
        # rejection visible. Thresholds are deliberately forgiving — a
        # relist storm during an apiserver rollout should degrade to
        # stale-but-coherent serving, not flap.
        self.list_breaker = list_breaker or CircuitBreaker(
            name="snapshot.list", failure_threshold=5,
            reset_timeout_s=10.0)
        self.watch_breaker = watch_breaker or CircuitBreaker(
            name="snapshot.watch", failure_threshold=5,
            reset_timeout_s=10.0)
        self.stats = SnapshotStats()
        self.generation = 0
        # _lock guards every structure below; only dict/list swaps happen
        # under it (decode + I/O run on the pumping thread outside).
        self._lock = threading.Lock()
        self._entries: dict[str, NodeEntry] = {}
        self._node_pressure: dict[str, object] = {}   # name -> NodePressure
        self._node_headroom: dict[str, object] = {}   # name -> NodeHeadroom
        self._node_overcommit: dict[str, object] = {}  # -> NodeOvercommit
        self._node_warm: dict[str, object] = {}       # -> NodeWarmKeys
        self._node_victim_costs: dict[str, object] = {}  # -> NodeVictimCosts
        self._node_linkload: dict[str, object] = {}   # -> NodeLinkLoad
        self._node_chiphealth: dict[str, object] = {}  # -> NodeChipHealth
        self._node_frag: dict[str, object] = {}       # -> NodeFrag
        # vtcs warm index: fingerprint -> (node, ...) for every node
        # advertising that fp. Copy-on-write tuples (the unbound-fp
        # pattern) so passes/tools read lock-free; maintained at node
        # event apply + relist, retired when a node's advertisement
        # drops the fp, goes stale-garbage, or the node is deleted.
        self._warm_fp_nodes: dict[str, tuple] = {}
        self._pods: dict[str, dict] = {}              # uid -> pod (ALL pods)
        self._pod_node: dict[str, str] = {}           # uid -> nodeName | ""
        self._pod_class: dict[str, tuple] = {}        # uid -> (claims, expiry)
        self._pod_gang: dict[str, tuple | None] = {}  # uid -> (ns, gang)
        self._gangs: dict[tuple, dict[str, dict]] = {}
        self._node_pod_uids: dict[str, set[str]] = {}
        # vtcc anti-storm over committed-but-unbound pods (the TTL path
        # reads the same signal via antistorm.unbound_recent_from_pods):
        # node -> ((uid, fingerprint, commit_ts), ...) for pods carrying
        # the predicate-node + program-fingerprint stamps but no
        # nodeName yet. Per-node tuples are copy-on-write so a pass
        # reads them lock-free; entries retire when the pod binds
        # (nodeName arrives as MODIFIED and the resident scan takes
        # over), is reaped, or is deleted — and storm_penalty re-judges
        # the window at use time, so a stale entry decays to zero even
        # between events.
        self._pod_unbound: dict[str, str] = {}        # uid -> node
        self._unbound_fp_nodes: dict[str, tuple] = {}
        # incrementally maintained capacity rank: ascending (rank_key,
        # name) for every node with a decoded registry. The filter's
        # TTL path sorts all nodes per pass (O(n log n) per decision);
        # here a pass just walks the head — rank once on change, not
        # once per decision. Structure (vtscale, 50k-node fix): a
        # compacted immutable **main** list plus a small sorted
        # **overlay** of post-compaction updates and a tombstone count.
        # One event is an O(log overlay) insort + O(1) bookkeeping —
        # the previous copy-on-write list paid an O(n) copy PER EVENT,
        # which at 50k nodes is ~400KB of allocator churn per pod
        # update. Readers lazily merge main+overlay, skipping items
        # that no longer match _rank_of (the per-name truth); when
        # overlay+tombstones exceed n/8 the rank compacts (amortized
        # O(log n) per event). main is only ever REPLACED, overlay only
        # ever grows in place between compactions, so a lock-free
        # walker capturing both refs stays safe mid-compaction.
        self._rank: list[tuple[int, str]] = []
        self._rank_of: dict[str, tuple[int, str]] = {}
        self._rank_overlay: list[tuple[int, str]] = []
        self._rank_dead = 0          # stale slots across main+overlay
        self._rank_version = 0
        self._rank_cache: list[tuple[int, str]] | None = None
        self._rank_cache_version = -1
        # incremental capacity digest: sum of every ranked node's
        # rank_key, O(1) to read — the cross-shard gang-spill digest
        self._rank_key_sum = 0
        self._all_pods_cache: list[dict] | None = None
        self._pods_rv = ""
        self._nodes_rv = ""
        # _pump_lock serializes watch consumers (direct pumps vs the
        # background loop); watch I/O deliberately happens while holding
        # it — it guards no pass-visible state and is never taken under
        # _lock (lock order is strictly _pump_lock -> _lock).
        self._pump_lock = threading.Lock()
        self._background = False
        self._stop = threading.Event()
        self._last_pump_monotonic = 0.0
        self._started = False
        # whether the most recent pump drained every kind cleanly — the
        # background loop's backoff signal (pump() itself degrades to
        # the last coherent state instead of raising, by design) — and
        # the server's pacing hint from the absorbed failure, if any
        self.last_pump_ok = True
        self.last_pump_retry_after: float | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Seed the snapshot with one versioned LIST of nodes and pods."""
        with self._pump_lock:
            # vtlint: disable=lock-discipline — see _pump_lock comment
            self._relist()
        self._started = True

    def start_background(self, poll_s: float = 1.0) -> None:
        """Continuous watch consumption on a daemon thread (production
        mode: passes never pay watch latency; apply-lag <= poll_s plus
        stream delivery)."""
        if not self._started:
            self.start()
        self._background = True
        threading.Thread(target=self._background_loop, args=(poll_s,),
                         daemon=True, name="vtpu-snapshot-watch").start()

    def stop_background(self) -> None:
        self._background = False
        self._stop.set()

    def _background_loop(self, poll_s: float) -> None:
        consecutive_failures = 0
        while self._background:
            failure: BaseException | None = None
            try:
                self.pump(timeout_s=poll_s)
                ok = self.last_pump_ok
            except Exception as e:
                # a wedged watch must degrade to a stale-but-coherent
                # snapshot, never take the scheduler down (KubeErrors
                # are already absorbed inside pump; this is the
                # everything-else backstop)
                log.warning("snapshot watch pump failed; serving the "
                            "last coherent state", exc_info=True)
                self.stats.watch_errors += 1
                failure = e
                ok = False
            if ok:
                consecutive_failures = 0
                # pacing: poll-style watches (the fake) return
                # immediately, streaming watches already spent up to
                # poll_s on the wire — either way the extra wait bounds
                # apply-lag at ~2*poll_s
                self._stop.wait(poll_s)
                continue
            # the old bare fixed-interval retry hammered a throttling
            # apiserver at exactly the wrong moment. Jittered
            # exponential backoff (Retry-After honored when the failure
            # carried one), reset on the first clean pump; staleness_s
            # keeps growing the whole time, so the exported gauge tells
            # the truth about how old served state can be.
            consecutive_failures += 1
            self.stats.reconnects += 1
            # the pacing hint survives both failure shapes: an escaped
            # exception carries it directly, an absorbed watch KubeError
            # left it on last_pump_retry_after
            retry_after = getattr(failure, "retry_after", None)
            if retry_after is None:
                retry_after = self.last_pump_retry_after
            wait = max(poll_s, self.retry_policy.backoff_s(
                consecutive_failures, retry_after))
            log.warning("snapshot watch pump failing (failure #%d); "
                        "retrying in %.2fs", consecutive_failures, wait)
            self._stop.wait(wait)

    # -- pumping ------------------------------------------------------------

    def ensure_fresh(self) -> tuple[int, bool]:
        """Apply whatever the watch has pending; (events_applied,
        relisted). With a background consumer running this is a no-op —
        the snapshot is already current within the poll interval."""
        if self._background:
            return 0, False
        return self.pump(timeout_s=self.watch_timeout_s)

    def pump(self, timeout_s: float = 0.0) -> tuple[int, bool]:
        with self._pump_lock:
            # vtlint: disable=lock-discipline — see _pump_lock comment
            return self._pump_locked(timeout_s)

    def _pump_locked(self, timeout_s: float) -> tuple[int, bool]:
        applied = 0
        relisted = False
        ok = True
        retry_after = None
        for kind in ("nodes", "pods"):
            try:
                applied += self._drain(kind, timeout_s)
                self.watch_breaker.record_success()
            except CircuitOpenError as e:
                # the watch breaker is open: no request was issued —
                # serve the last coherent state, staleness keeps growing
                log.warning("snapshot %s watch rejected: %s", kind, e)
                ok = False
            except KubeError as e:
                if e.status == 410:
                    # our resourceVersion was compacted away: the watch
                    # window is gone, rebuild from a fresh LIST (not a
                    # dependency failure — the breaker doesn't count it)
                    self._relist()
                    relisted = True
                else:
                    self.watch_breaker.record_failure()
                    log.warning("snapshot %s watch failed (%s); serving "
                                "the last coherent state", kind, e)
                    self.stats.watch_errors += 1
                    ok = False
                    if e.retry_after is not None:
                        retry_after = max(retry_after or 0.0,
                                          e.retry_after)
        if ok:
            # only a fully successful pump resets the freshness clock:
            # staleness_s is the exported how-old-can-my-state-be gauge,
            # and a failing watch must make it GROW, not read ~0
            self._last_pump_monotonic = time.monotonic()
        self.last_pump_ok = ok
        self.last_pump_retry_after = retry_after
        return applied, relisted

    def _drain(self, kind: str, timeout_s: float) -> int:
        if not self.watch_breaker.allow():
            self.stats.breaker_open += 1
            raise CircuitOpenError(
                f"snapshot watch circuit open; skipping {kind} drain")
        if kind == "nodes":
            events = self.client.watch_nodes(self._nodes_rv,
                                             timeout_s=timeout_s)
        else:
            events = self.client.watch_pods(self._pods_rv,
                                            timeout_s=timeout_s)
        applied = 0
        for event in events:
            self.apply_event(kind, event)
            applied += 1
        return applied

    def breakers(self) -> list[CircuitBreaker]:
        """The LIST/watch verb-family breakers, for /metrics
        (vtpu_circuit_state{name=...})."""
        return [self.list_breaker, self.watch_breaker]

    def staleness_s(self) -> float:
        """Seconds since the last fully successful pump (0 before the
        first). Grows monotonically while the watch is failing."""
        if self._last_pump_monotonic == 0.0:
            return 0.0
        return max(0.0, time.monotonic() - self._last_pump_monotonic)

    # -- event application --------------------------------------------------

    def apply_event(self, kind: str, event: dict) -> None:
        """Apply one watch event. Public so failure-mode tests can inject
        crafted sequences (duplicates, reordering) directly. Decode and
        classification run before the lock is taken."""
        failpoints.fire("snapshot.apply", kind=kind,
                        type=event.get("type", ""))
        type_ = event.get("type", "")
        obj = event.get("object") or {}
        rv = (event.get("resourceVersion")
              or (obj.get("metadata") or {}).get("resourceVersion") or "")
        if type_ == "BOOKMARK":
            self.stats.bookmarks += 1
            self._advance_rv(kind, rv)
            return
        if type_ not in ("ADDED", "MODIFIED", "DELETED"):
            log.warning("snapshot: ignoring unknown %s watch event %r",
                        kind, type_)
            return
        if kind == "nodes":
            self._apply_node(type_, obj)
            self.stats.node_events += 1
        else:
            self._apply_pod(type_, obj)
            self.stats.pod_events += 1
        self.stats.events_applied += 1
        self._advance_rv(kind, rv)

    def _advance_rv(self, kind: str, rv: str) -> None:
        if not rv:
            return
        if kind == "nodes":
            self._nodes_rv = rv
        else:
            self._pods_rv = rv

    def _apply_node(self, type_: str, node: dict) -> None:
        meta = node.get("metadata") or {}
        name = meta.get("name", "")
        if not name:
            return
        if self._node_selector is not None and type_ != "DELETED" \
                and not self._node_selector(node):
            # out of shard scope. A pool-label move OFF this shard
            # arrives as MODIFIED, so an existing entry must go the same
            # way a deletion would.
            self.stats.filtered_nodes += 1
            if name in self._entries:
                type_ = "DELETED"
            else:
                return
        if type_ == "DELETED":
            with self._lock:
                if name in self._entries:
                    entries = dict(self._entries)
                    del entries[name]
                    self._entries = entries
                    self._node_pressure.pop(name, None)
                    self._node_headroom.pop(name, None)
                    self._node_overcommit.pop(name, None)
                    self._node_victim_costs.pop(name, None)
                    self._node_linkload.pop(name, None)
                    self._node_chiphealth.pop(name, None)
                    self._node_frag.pop(name, None)
                    self._set_warm_locked(name, None)
                    self._publish_rank_locked(name, None)
                    self.generation += 1
            return
        # decode outside the lock — the one potentially-large JSON parse
        # on the node path (the vttel pressure annotation parses here
        # for the same reason, staleness judged at ingest)
        self.stats.registry_decodes += 1
        anns = meta.get("annotations") or {}
        registry = dt.decode_registry(
            anns.get(consts.node_device_register_annotation()))
        node_pressure = tel_pressure.parse_pressure(
            anns.get(consts.node_pressure_annotation()))
        node_headroom = util_headroom.parse_headroom(
            anns.get(consts.node_reclaimable_headroom_annotation()))
        node_overcommit = oc_mod.parse_overcommit(
            anns.get(consts.node_overcommit_annotation()))
        node_warm = cc_advertise.parse_warm_keys(
            anns.get(consts.node_cache_keys_annotation()))
        node_victim_costs = vc_mod.parse_victim_costs(
            anns.get(consts.node_victim_cost_annotation()))
        node_linkload = tl_mod.parse_link_load(
            anns.get(consts.node_ici_link_load_annotation()))
        node_chiphealth = health_codec.parse_chip_health(
            anns.get(consts.node_chip_health_annotation()))
        node_frag = frag_codec.parse_frag(
            anns.get(consts.node_frag_annotation()))
        labels = meta.get("labels") or {}
        with self._lock:
            self._node_pressure[name] = node_pressure
            self._node_headroom[name] = node_headroom
            self._node_overcommit[name] = node_overcommit
            self._node_victim_costs[name] = node_victim_costs
            self._node_linkload[name] = node_linkload
            self._node_chiphealth[name] = node_chiphealth
            self._node_frag[name] = node_frag
            self._set_warm_locked(name, node_warm)
            self.generation += 1
            entry = self._build_entry_locked(name, node, labels, registry)
            if name in self._entries:
                self._entries[name] = entry       # value swap: safe
            else:
                self._entries = {**self._entries, name: entry}
            self._publish_rank_locked(name, entry)

    def _apply_pod(self, type_: str, pod: dict) -> None:
        meta = pod.get("metadata") or {}
        uid = meta.get("uid", "")
        if not uid:
            return
        if type_ == "DELETED":
            with self._lock:
                self.generation += 1
                self._all_pods_cache = None
                self._pods.pop(uid, None)
                self._pod_class.pop(uid, None)
                self._unlink_gang_locked(uid)
                self._set_unbound_fp_locked(uid, None)
                old_node = self._pod_node.pop(uid, "")
                if old_node:
                    self._node_pod_uids.get(old_node, set()).discard(uid)
                    self._refresh_entry_locked(old_node)
            return
        # classification (claims decode + phase-peak fold) outside the lock
        cls = _classify_pod(pod, self.stuck_grace_s, self.stats)
        node_name = (pod.get("spec") or {}).get("nodeName") or ""
        gang_key = self._gang_key(pod)
        unbound_fp = self._classify_unbound_fp(pod)
        with self._lock:
            self.generation += 1
            self._all_pods_cache = None
            self._pods[uid] = pod
            self._pod_class[uid] = cls
            self._relink_gang_locked(uid, gang_key, pod)
            self._set_unbound_fp_locked(uid, unbound_fp)
            old_node = self._pod_node.get(uid, "")
            self._pod_node[uid] = node_name
            if old_node and old_node != node_name:
                self._node_pod_uids.get(old_node, set()).discard(uid)
                self._refresh_entry_locked(old_node)
            if node_name:
                self._node_pod_uids.setdefault(node_name, set()).add(uid)
                self._refresh_entry_locked(node_name)

    @staticmethod
    def _classify_unbound_fp(pod: dict) -> tuple[str, str, float] | None:
        """(predicate_node, fingerprint, commit_ts) when the pod is a
        committed-but-unbound anti-storm signal source, else None. Runs
        outside the lock (annotation parses). Entries older than the
        storm window at ingest are skipped; ones ingested fresh are
        retired by the events that end the unbound state, and
        storm_penalty ignores the expired tail at use time."""
        if (pod.get("spec") or {}).get("nodeName"):
            return None
        anns = (pod.get("metadata") or {}).get("annotations") or {}
        node = anns.get(consts.predicate_node_annotation())
        if not node:
            return None
        raw = anns.get(consts.program_fingerprint_annotation())
        if not raw:
            return None
        ts = consts.parse_predicate_time(anns)
        # skew_s=0: a committed-but-unbound signal from the FUTURE is not
        # a storm yet (same zero future tolerance as before the codec)
        if ts is None or not stalecodec.is_fresh(
                ts, max_age_s=antistorm.STORM_WINDOW_S, skew_s=0.0):
            return None
        fp = antistorm.sanitize_fingerprint(raw)
        if not fp:
            return None
        return node, fp, ts

    def _set_unbound_fp_locked(self, uid: str,
                               unb: tuple[str, str, float] | None) -> None:
        """Maintain the per-node unbound-fingerprint tuples under _lock;
        each mutated node publishes a fresh tuple (copy-on-write, same
        contract as the rank list) so passes read lock-free."""
        old_node = self._pod_unbound.get(uid)
        new_node = unb[0] if unb is not None else None
        if old_node is not None and old_node != new_node:
            kept = tuple(e for e in self._unbound_fp_nodes.get(
                old_node, ()) if e[0] != uid)
            if kept:
                self._unbound_fp_nodes[old_node] = kept
            else:
                self._unbound_fp_nodes.pop(old_node, None)
            del self._pod_unbound[uid]
        if unb is not None:
            node, fp, ts = unb
            kept = tuple(e for e in self._unbound_fp_nodes.get(node, ())
                         if e[0] != uid)
            self._unbound_fp_nodes[node] = kept + ((uid, fp, ts),)
            self._pod_unbound[uid] = node

    def _set_warm_locked(self, name: str, warm) -> None:
        """Maintain the per-fingerprint warm-node tuples under _lock;
        each mutated fingerprint publishes a fresh tuple (copy-on-
        write, the unbound-fp contract) so readers never see a tuple
        shrink mid-iteration. Both callers parse with the default max
        age, so a stale-at-ingest or garbage advertisement arrives
        here as None and clears the node's fps (no-signal); an entry
        that indexed fresh and aged SINCE (dead advertiser, no further
        events) stays indexed — warm_term re-judges the ts that
        travels with NodeEntry.warm at every score."""
        old = self._node_warm.get(name)
        old_fps = old.fps if old is not None else frozenset()
        new_fps = warm.fps if warm is not None else frozenset()
        for fp in old_fps - new_fps:
            kept = tuple(n for n in self._warm_fp_nodes.get(fp, ())
                         if n != name)
            if kept:
                self._warm_fp_nodes[fp] = kept
            else:
                self._warm_fp_nodes.pop(fp, None)
        for fp in new_fps - old_fps:
            have = self._warm_fp_nodes.get(fp, ())
            if name not in have:
                self._warm_fp_nodes[fp] = have + (name,)
        if warm is None:
            self._node_warm.pop(name, None)
        else:
            self._node_warm[name] = warm

    def warm_nodes(self, fingerprint: str) -> tuple:
        """Nodes currently advertising ``fingerprint`` in their warm-
        keys annotation — the vtcs key→nodes index, lock-free read of a
        copy-on-write tuple. Callers judging warmth must still re-check
        freshness on the node's NodeEntry.warm (this index trades a
        little staleness for O(1) reverse lookup)."""
        return self._warm_fp_nodes.get(fingerprint, ())

    def unbound_fp(self, name: str) -> tuple:
        """((uid, fingerprint, commit_ts), ...) of committed-but-unbound
        pods targeting this node — the snapshot-path twin of the TTL
        path's unbound_recent_from_pods scan. Lock-free read of a
        copy-on-write tuple."""
        return self._unbound_fp_nodes.get(name, ())

    @staticmethod
    def _gang_key(pod: dict) -> tuple | None:
        name, _ = resolve_gang_name(pod)
        if not name:
            return None
        ns = (pod.get("metadata") or {}).get("namespace", "default")
        return (ns, name)

    def _relink_gang_locked(self, uid: str, key: tuple | None,
                            pod: dict) -> None:
        # member dicts are copy-on-write: gang_members() hands the live
        # dict to lock-free readers, so a mutation must publish a fresh
        # one (gangs are small; the copy is O(gang))
        old = self._pod_gang.get(uid)
        if old is not None and old != key:
            self._gang_remove_locked(old, uid)
        self._pod_gang[uid] = key
        if key is not None:
            self._gangs[key] = {**self._gangs.get(key, {}), uid: pod}

    def _unlink_gang_locked(self, uid: str) -> None:
        key = self._pod_gang.pop(uid, None)
        if key is not None:
            self._gang_remove_locked(key, uid)

    def _gang_remove_locked(self, key: tuple, uid: str) -> None:
        members = self._gangs.get(key)
        if members is None or uid not in members:
            return
        members = {u: p for u, p in members.items() if u != uid}
        if members:
            self._gangs[key] = members
        else:
            del self._gangs[key]

    def _refresh_entry_locked(self, name: str) -> None:
        old = self._entries.get(name)
        if old is None:
            return      # pods on a node we have not seen yet: tracked
        entry = self._build_entry_locked(name, old.node, old.labels,
                                         old.registry)
        self._entries[name] = entry
        self._publish_rank_locked(name, entry)

    def _publish_rank_locked(self, name: str,
                             entry: NodeEntry | None) -> None:
        """Keep the capacity rank in sync with one entry swap: retire
        the old item by tombstone (readers validate every item against
        _rank_of, so the stale copy in main/overlay is simply skipped),
        insort the new item into the small overlay, and compact when
        the garbage fraction crosses n/8. O(log n) amortized per event,
        zero per-event copies. Entries without a registry never rank
        (the filter gate fails them)."""
        old = self._rank_of.pop(name, None)
        if old is not None:
            self._rank_dead += 1
            self._rank_key_sum -= old[0]
        if entry is not None and entry.registry is not None:
            item = (entry.rank_key, name)
            bisect.insort(self._rank_overlay, item)
            self._rank_of[name] = item
            self._rank_key_sum += item[0]
        self._rank_version += 1
        if (len(self._rank_overlay) + self._rank_dead
                > max(64, len(self._rank_of) // 8)):
            self._compact_rank_locked()

    def _compact_rank_locked(self) -> None:
        """Fold overlay + tombstones back into one sorted main list.
        O(n log n), amortized over the >= n/8 events that triggered it
        — O(log n) per event. Replaces main wholesale (never mutates),
        so in-flight walkers finish on the generation they captured."""
        self._rank = sorted(self._rank_of.values())
        self._rank_overlay = []
        self._rank_dead = 0

    def _build_entry_locked(self, name: str, node: dict, labels: dict,
                            registry) -> NodeEntry:
        """Recompute one node's aggregates from cached per-pod
        classifications — pure arithmetic, no decode, O(residents)."""
        resident: dict[str, dict] = {}
        counted: list = []
        conditional: list = []
        for uid in self._node_pod_uids.get(name, ()):
            pod = self._pods.get(uid)
            if pod is None:
                continue
            resident[uid] = pod
            claims, expiry = self._pod_class.get(uid, (None, None))
            if claims is None:
                continue
            if expiry is None:
                counted.append((uid, claims))
            else:
                conditional.append((uid, claims, expiry))
        if registry is None:
            base_free = _EMPTY_FREE
            rank_key = 0
        else:
            claim_sets = [c for _, c in counted]
            base_free = dt.fast_free_totals(registry, claim_sets)
            if conditional:
                now = time.time()
                live = [c for _, c, exp in conditional if now <= exp]
                free = (dt.fast_free_totals(registry, claim_sets + live)
                        if live else base_free)
            else:
                free = base_free
            rank_key = free[1] + (free[2] >> 24) + free[0]
        return NodeEntry(name, node, labels, registry, resident, counted,
                         conditional, base_free, rank_key,
                         self.generation,
                         pressure=self._node_pressure.get(name),
                         fp_recent=tuple(antistorm.recent_from_pods(
                             resident.values(), time.time())),
                         headroom=self._node_headroom.get(name),
                         overcommit=self._node_overcommit.get(name),
                         warm=self._node_warm.get(name),
                         victim_costs=self._node_victim_costs.get(name),
                         linkload=self._node_linkload.get(name),
                         chiphealth=self._node_chiphealth.get(name),
                         frag=self._node_frag.get(name))

    # -- relist (seed + 410 recovery) ---------------------------------------

    def _relist(self) -> None:
        """Full rebuild from fresh versioned LISTs. All decode happens
        before the final swap; readers keep the previous coherent view
        until the atomic publication at the end."""
        if not self.list_breaker.allow():
            self.stats.breaker_open += 1
            raise CircuitOpenError(
                "snapshot list circuit open; relist rejected")
        self.stats.relists += 1
        try:
            nodes, nodes_rv = self.client.list_nodes_with_version()
            pods, pods_rv = self.client.list_pods_with_version()
        except KubeError:
            self.list_breaker.record_failure()
            raise
        self.list_breaker.record_success()
        if self._node_selector is not None:
            kept = [n for n in nodes if self._node_selector(n)]
            self.stats.filtered_nodes += len(nodes) - len(kept)
            nodes = kept
        pod_map: dict[str, dict] = {}
        pod_node: dict[str, str] = {}
        pod_class: dict[str, tuple] = {}
        pod_gang: dict[str, tuple | None] = {}
        gangs: dict[tuple, dict[str, dict]] = {}
        node_pod_uids: dict[str, set[str]] = {}
        pod_unbound: dict[str, str] = {}
        unbound_fp_nodes: dict[str, tuple] = {}
        for pod in pods:
            uid = (pod.get("metadata") or {}).get("uid", "")
            if not uid:
                continue
            pod_map[uid] = pod
            pod_class[uid] = _classify_pod(pod, self.stuck_grace_s,
                                           self.stats)
            node_name = (pod.get("spec") or {}).get("nodeName") or ""
            pod_node[uid] = node_name
            if node_name:
                node_pod_uids.setdefault(node_name, set()).add(uid)
            key = self._gang_key(pod)
            pod_gang[uid] = key
            if key is not None:
                gangs.setdefault(key, {})[uid] = pod
            unb = self._classify_unbound_fp(pod)
            if unb is not None:
                pod_unbound[uid] = unb[0]
                unbound_fp_nodes[unb[0]] = \
                    unbound_fp_nodes.get(unb[0], ()) + ((uid,) + unb[1:],)
        with self._lock:
            self.generation += 1
            self._pods = pod_map
            self._pod_node = pod_node
            self._pod_class = pod_class
            self._pod_gang = pod_gang
            self._gangs = gangs
            self._node_pod_uids = node_pod_uids
            self._pod_unbound = pod_unbound
            self._unbound_fp_nodes = unbound_fp_nodes
            self._all_pods_cache = None
            self._node_pressure = {}
            self._node_headroom = {}
            self._node_overcommit = {}
            self._node_warm = {}
            self._node_victim_costs = {}
            self._node_linkload = {}
            self._node_chiphealth = {}
            self._node_frag = {}
            self._warm_fp_nodes = {}
            entries: dict[str, NodeEntry] = {}
            for node in nodes:
                meta = node.get("metadata") or {}
                name = meta.get("name", "")
                if not name:
                    continue
                self.stats.registry_decodes += 1
                anns = meta.get("annotations") or {}
                registry = dt.decode_registry(
                    anns.get(consts.node_device_register_annotation()))
                self._node_pressure[name] = tel_pressure.parse_pressure(
                    anns.get(consts.node_pressure_annotation()))
                self._node_headroom[name] = util_headroom.parse_headroom(
                    anns.get(consts.node_reclaimable_headroom_annotation()))
                self._node_overcommit[name] = oc_mod.parse_overcommit(
                    anns.get(consts.node_overcommit_annotation()))
                self._node_victim_costs[name] = vc_mod.parse_victim_costs(
                    anns.get(consts.node_victim_cost_annotation()))
                self._node_linkload[name] = tl_mod.parse_link_load(
                    anns.get(consts.node_ici_link_load_annotation()))
                self._node_chiphealth[name] = \
                    health_codec.parse_chip_health(
                        anns.get(consts.node_chip_health_annotation()))
                self._node_frag[name] = frag_codec.parse_frag(
                    anns.get(consts.node_frag_annotation()))
                self._set_warm_locked(name, cc_advertise.parse_warm_keys(
                    anns.get(consts.node_cache_keys_annotation())))
                entries[name] = self._build_entry_locked(
                    name, node, meta.get("labels") or {}, registry)
            self._entries = entries
            self._rank_of = {name: (entry.rank_key, name)
                             for name, entry in entries.items()
                             if entry.registry is not None}
            self._rank = sorted(self._rank_of.values())
            self._rank_overlay = []
            self._rank_dead = 0
            self._rank_version += 1
            self._rank_key_sum = sum(k for k, _ in self._rank)
            self._nodes_rv = nodes_rv
            self._pods_rv = pods_rv

    # -- pass-facing reads (no copy) ----------------------------------------

    def entries(self) -> dict[str, NodeEntry]:
        """The live name -> NodeEntry mapping. Safe to iterate: values are
        swapped in place and structural changes publish a new dict."""
        return self._entries

    def entry(self, name: str) -> NodeEntry | None:
        return self._entries.get(name)

    def all_pods(self) -> list[dict]:
        """Every pod in the cluster including pending (the gang paths need
        unbound burst siblings); list rebuilt lazily after changes."""
        cached = self._all_pods_cache
        if cached is not None:
            return cached
        with self._lock:
            if self._all_pods_cache is None:
                self._all_pods_cache = list(self._pods.values())
            return self._all_pods_cache

    def gang_members(self, namespace: str, gang_name: str) -> list[dict]:
        """Pods of one resolved gang — O(gang), replacing the full-list
        sibling scan (O(cluster)) on the snapshot path."""
        members = self._gangs.get((namespace, gang_name))
        if not members:
            return []
        return list(members.values())

    def rank_items(self) -> list[tuple[int, str]]:
        """The ascending (rank_key, name) capacity rank, materialized.
        Cached until the next rank-changing event, so repeated reads of
        an unchanged cluster are O(1); after a change the first caller
        pays one O(n) merge. Passes that only walk the head should use
        ``rank_walk`` instead — it never materializes."""
        version = self._rank_version
        cache = self._rank_cache
        if cache is not None and self._rank_cache_version == version:
            return cache
        items = list(self.rank_walk())
        self._rank_cache = items
        self._rank_cache_version = version
        return items

    def rank_walk(self, reverse: bool = False):
        """Lazily walk the capacity rank in order (ascending, or
        descending with ``reverse``): an on-the-fly merge of the
        compacted main list and the update overlay, yielding only items
        that still match the per-name truth (_rank_of). Lock-free and
        safe against concurrent events: main is replaced never mutated,
        the overlay is captured by copy (small — bounded by the n/8
        compaction threshold), a node updated mid-walk simply stops
        matching, and the seen-set drops the duplicate items an
        update-then-revert can leave across generations. A head-limited
        pass therefore costs O(head · log) plus that small copy — it no
        longer rides on materializing all n items."""
        main = self._rank
        overlay = list(self._rank_overlay)   # small: bounded by n/8
        rank_of = self._rank_of
        seen: set[str] = set()
        if reverse:
            i, j = len(main) - 1, len(overlay) - 1
            while i >= 0 or j >= 0:
                if j < 0 or (i >= 0 and main[i] >= overlay[j]):
                    item = main[i]
                    i -= 1
                else:
                    item = overlay[j]
                    j -= 1
                name = item[1]
                if name not in seen and rank_of.get(name) == item:
                    seen.add(name)
                    yield item
        else:
            i, j = 0, 0
            while i < len(main) or j < len(overlay):
                if j >= len(overlay) or (i < len(main)
                                         and main[i] <= overlay[j]):
                    item = main[i]
                    i += 1
                else:
                    item = overlay[j]
                    j += 1
                name = item[1]
                if name not in seen and rank_of.get(name) == item:
                    seen.add(name)
                    yield item

    def capacity_digest(self) -> tuple[int, int]:
        """(ranked_nodes, rank_key_sum): the O(1) free-capacity digest
        the vtscale cross-shard gang spill compares across shards. The
        rank_key is already the filter's free-capacity ordering scalar;
        its sum over a shard's snapshot is a cheap, monotone-enough
        proxy for "how much room this shard has" — the spill pass
        re-validates real capacity on the target shard's entries, so
        the digest only has to pick a *plausible* neighbor, never a
        provably correct one."""
        return len(self._rank_of), self._rank_key_sum

    def prune_expired(self, name: str, now: float) -> None:
        """Drop conditionals whose grace expired (no watch event marks
        that moment). They can never count again — a real allocation or
        new predicate stamp arrives as MODIFIED and reclassifies — so
        membership-only pruning is safe and base_free is untouched."""
        entry = self._entries.get(name)
        if entry is None or not entry.conditional:
            return
        live = [c for c in entry.conditional if now <= c[2]]
        if len(live) == len(entry.conditional):
            return
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                return
            live = [c for c in entry.conditional if now <= c[2]]
            if entry.registry is not None:
                free = (dt.fast_free_totals(
                            entry.registry,
                            [c for _, c in entry.counted]
                            + [c for _, c, _e in live])
                        if live else entry.base_free)
                rank_key = free[1] + (free[2] >> 24) + free[0]
            else:
                rank_key = 0
            pruned = NodeEntry(
                entry.name, entry.node, entry.labels, entry.registry,
                entry.resident, entry.counted, live, entry.base_free,
                rank_key, self.generation, pressure=entry.pressure,
                fp_recent=entry.fp_recent, headroom=entry.headroom,
                overcommit=entry.overcommit, warm=entry.warm,
                victim_costs=entry.victim_costs,
                linkload=entry.linkload, chiphealth=entry.chiphealth,
                frag=entry.frag)
            self._entries[name] = pruned
            self._publish_rank_locked(name, pruned)
