"""vtha: shard-scoped scheduling units behind per-shard leader leases.

The cluster is partitioned by **node pool** (the ``node_pool_label()``
node label; unlabeled nodes form the unnamed default pool) into shards.
Every scheduler process is built with the same ``--shard-pools`` plan and
runs one :class:`ShardUnit` per shard — its own shard-scoped
``ClusterSnapshot`` (watch stream, staleness, generation all per shard),
its own filter/gang/preempt/bind state — but *leads* only the shards
whose lease (scheduler/lease.py) it holds. For the rest it is a hot
standby: the snapshot stays warm, so taking over an expired shard is one
lease CAS plus a bind-intent replay, bounded by one lease TTL.

Pod ownership is deterministic so exactly one leader owns any pod:

- a pod whose ``nodeSelector`` pins the node-pool label belongs to the
  shard owning that pool;
- everything else (no-pool pods, gangs spanning pools) routes through a
  stable home-shard hash — fnv64 of the gang identity when the pod is a
  gang member (all members of a gang land in ONE shard, preserving gang
  semantics) or of the pod uid otherwise.

A request for a shard this process does not lead fails fast with the
observed holder in the error; kube-scheduler's retry lands it on the
leading replica (every replica serves the same extender endpoints).

Failover safety rides PR 4's machinery: a freshly acquired shard first
**replays the bind-intent trail** — commitments stamped with an older
fencing token for this shard are reaped (cleared if unbound; bound ones
are left to the reschedule controller's allocating-stuck eviction, which
respects PDBs) — before the shard accepts work, so an interrupted bind
is reaped, never double-placed. Fencing tokens are stamped into the same
patches as the pre-allocation and the allocating-status, and the bind
path CAS-confirms the lease between the intent patch and the Binding
POST, so a paused-then-resumed ex-leader's stale bind is rejected at
commit time (lease.py docstring walks the window arithmetic).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from vtpu_manager import explain
from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.config.vmem import fnv64
from vtpu_manager.resilience import failpoints, recovery
from vtpu_manager.resilience.policy import RetryPolicy
from vtpu_manager.scheduler import lease as lease_mod
from vtpu_manager.scheduler import plan as plan_mod
from vtpu_manager.scheduler.bind import BindPredicate, BindResult
from vtpu_manager.scheduler.bindpipe import (BindCommitPipeline,
                                             render_pipeline_metrics)
from vtpu_manager.scheduler.filter import FilterPredicate, FilterResult
from vtpu_manager.scheduler.lease import LeaseLostError, ShardLease
from vtpu_manager.scheduler.preempt import PreemptPredicate, PreemptResult
from vtpu_manager.scheduler.snapshot import ClusterSnapshot
from vtpu_manager.util import consts
from vtpu_manager.util.gangname import resolve_gang_name

log = logging.getLogger(__name__)

CATCH_ALL = "*"


def node_pool(node: dict) -> str:
    """A node's pool, from the node-pool label ('' = default pool)."""
    labels = (node.get("metadata") or {}).get("labels") or {}
    return labels.get(consts.node_pool_label(), "")


@dataclass(frozen=True)
class ShardSpec:
    """One shard of the plan: a set of named pools, or the catch-all
    (which owns every pool no other shard names, including '')."""

    index: int
    name: str
    pools: frozenset
    catch_all: bool

    def owns_labels(self, labels: dict, named_pools: frozenset) -> bool:
        pool = (labels or {}).get(consts.node_pool_label(), "")
        if self.catch_all:
            return pool not in named_pools
        return pool in self.pools


class ShardPlan:
    """The shared cluster partition. Every scheduler replica MUST be
    started with the same ``--shard-pools`` value — the plan defines
    lease names and the home-shard hash, and replicas with diverging
    plans would disagree about pod ownership (documented operator
    contract, docs/ha.md)."""

    def __init__(self, shards: list[ShardSpec]):
        if not shards:
            raise ValueError("shard plan needs at least one shard")
        if sum(1 for s in shards if s.catch_all) != 1:
            raise ValueError("shard plan needs exactly one catch-all shard")
        self.shards = shards
        self.named_pools = frozenset(
            p for s in shards for p in s.pools)
        self._by_pool = {p: s for s in shards for p in s.pools}
        self._catch_all = next(s for s in shards if s.catch_all)

    @classmethod
    def parse(cls, spec: str) -> "ShardPlan":
        """``poolA,poolB;poolC;*`` — semicolon-separated shards, each a
        comma-list of pool names; ``*`` alone is the catch-all shard
        (appended automatically when absent). Empty spec = one catch-all
        shard (sharding degenerates to a single HA leader for the whole
        cluster)."""
        shards: list[ShardSpec] = []
        seen: set[str] = set()
        parts = [p.strip() for p in (spec or "").split(";") if p.strip()]
        for i, part in enumerate(parts):
            if part == CATCH_ALL:
                shards.append(ShardSpec(i, f"shard{i}", frozenset(), True))
                continue
            pools = frozenset(x.strip() for x in part.split(",")
                              if x.strip())
            if not pools:
                raise ValueError(f"empty shard in --shard-pools {spec!r}")
            dup = pools & seen
            if dup:
                raise ValueError(
                    f"pool(s) {sorted(dup)} named by two shards")
            seen |= pools
            shards.append(ShardSpec(i, f"shard{i}", pools, False))
        if not any(s.catch_all for s in shards):
            shards.append(ShardSpec(len(shards), f"shard{len(shards)}",
                                    frozenset(), True))
        return cls(shards)

    def shard_for_pool(self, pool: str) -> ShardSpec:
        return self._by_pool.get(pool, self._catch_all)

    def home_shard(self, pod: dict) -> ShardSpec:
        """Deterministic owner of a pod — identical from every replica.
        Pool-pinned pods go to their pool's shard; gang members hash by
        gang identity (one shard owns the WHOLE gang); everything else
        hashes by pod uid (falling back to ns/name for uid-less test
        pods)."""
        spec = pod.get("spec") or {}
        pinned = (spec.get("nodeSelector") or {}).get(
            consts.node_pool_label())
        if pinned is not None:
            return self.shard_for_pool(pinned)
        meta = pod.get("metadata") or {}
        gang, _ = resolve_gang_name(pod)
        if gang:
            key = f"gang/{meta.get('namespace', 'default')}/{gang}"
        else:
            key = meta.get("uid") or (f"{meta.get('namespace', 'default')}"
                                      f"/{meta.get('name', '')}")
        return self.shards[fnv64(key) % len(self.shards)]


class ShardUnit:
    """One shard's full scheduling state inside one process."""

    def __init__(self, spec: ShardSpec, lease: ShardLease,
                 snapshot: ClusterSnapshot | None,
                 filter_pred: FilterPredicate, bind_pred: BindPredicate,
                 preempt_pred: PreemptPredicate,
                 pipeline: BindCommitPipeline | None = None):
        self.spec = spec
        self.lease = lease
        self.snapshot = snapshot
        self.filter_pred = filter_pred
        self.bind_pred = bind_pred
        self.preempt_pred = preempt_pred
        # vtscale (ScalePipeline gate; None = serial binds, byte-
        # identical): the shard's wave-batched commit pipeline
        self.pipeline = pipeline
        # takeover replay completed under the current token; reset on
        # every acquisition so a re-acquired shard replays again. The
        # lock keeps the tick thread and an opportunistic request-path
        # acquire from running two cluster-LIST replays concurrently.
        self.replayed_token = -1
        self.replay_lock = threading.Lock()
        self.handoffs = 0
        self.takeover_reaps = 0
        self.fence_rejections = 0
        # gangs this shard placed on a neighbor's nodes (vtscale spill)
        self.spills = 0


class ShardedScheduler:
    """N shards, one process, active-active with the process's peers.

    Exposes the same ``filter``/``bind``/``preempt`` entry points as the
    single predicates so routes.py serves it unchanged; each call routes
    to the owning shard and is served only while this process holds that
    shard's lease fresh (and has finished the takeover replay).
    """

    def __init__(self, client: KubeClient, plan: ShardPlan, holder: str,
                 lease_ttl_s: float = lease_mod.DEFAULT_LEASE_TTL_S,
                 lease_namespace: str = lease_mod.DEFAULT_LEASE_NAMESPACE,
                 use_snapshot: bool = False,
                 filter_kwargs: dict | None = None,
                 preempt_kwargs: dict | None = None,
                 policy_factory=None, snapshot_factory=None,
                 bind_locker=None,
                 scale_pipeline: bool = False,
                 pipeline_kwargs: dict | None = None,
                 plan_spec: str = "", plan_epoch: int = 0,
                 monotonic=time.monotonic, wall=time.time):
        self.client = client
        self.plan = plan
        self.holder = holder
        self.lease_ttl_s = lease_ttl_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # everything a unit is built from is kept on self, because
        # vtscale plan adoption rebuilds the whole unit list at a new
        # epoch (no process restart)
        self._lease_namespace = lease_namespace
        self._use_snapshot = use_snapshot
        self._make_policy = policy_factory or (lambda: None)
        self._filter_kwargs = dict(filter_kwargs or {})
        # preempt_kwargs rides exactly like filter_kwargs so the
        # vtexplain victim-order hint reaches every shard's predicate
        self._preempt_kwargs = dict(preempt_kwargs or {})
        self._snapshot_factory = snapshot_factory
        self._bind_locker = bind_locker
        self._monotonic = monotonic
        self._wall = wall
        # vtscale (ScalePipeline gate, resolved by the caller): wave-
        # batched bind commits, the published shard plan with its epoch,
        # cross-shard gang spill. All defaults = byte-identical vtha.
        self.scale_pipeline = bool(scale_pipeline)
        self._pipeline_kwargs = dict(pipeline_kwargs or {})
        self.plan_spec = plan_spec
        self.plan_epoch = int(plan_epoch)
        self._started = False
        self._snapshot_poll_s = 1.0
        self.units: list[ShardUnit] = self._build_units(plan,
                                                        self.plan_epoch)
        # takeover replay pages through the cluster pod list; keep its
        # own retry budget (it runs on the tick thread, not a request)
        self._replay_policy = self._make_policy() or RetryPolicy(
            max_attempts=3, base_delay_s=0.05, deadline_s=5.0)

    def _build_units(self, plan: ShardPlan,
                     epoch: int) -> list[ShardUnit]:
        # One ShardUnit per shard of the given plan, fence-stamping the
        # given epoch. Selectors close over the plan ARGUMENT (never
        # self.plan) so units built for a new epoch cannot read the old
        # partition mid-swap.
        units: list[ShardUnit] = []
        for spec in plan.shards:
            lease = ShardLease(self.client, spec.name, self.holder,
                               ttl_s=self.lease_ttl_s,
                               namespace=self._lease_namespace,
                               policy=self._make_policy(),
                               monotonic=self._monotonic,
                               wall=self._wall)
            # the plan epoch folds into every fence this lease stamps
            # (epoch 0 emits no suffix — byte-identical pre-plan wire)
            lease.epoch = epoch
            selector = self._shard_selector(plan, spec)
            snapshot = None
            if self._use_snapshot:
                node_selector = (
                    lambda node, s=spec, p=plan: s.owns_labels(
                        (node.get("metadata") or {}).get("labels") or {},
                        p.named_pools))
                if self._snapshot_factory is not None:
                    # test hook: the chaos harness injects snapshots with
                    # forgiving breakers / fast policies
                    snapshot = self._snapshot_factory(node_selector)
                else:
                    snapshot = ClusterSnapshot(self.client,
                                               node_selector=node_selector)
            filter_pred = FilterPredicate(
                self.client, snapshot=snapshot, fence=lease,
                shard_selector=selector,
                policy=self._make_policy(), **self._filter_kwargs)
            # bind_locker is shared across shards on purpose: the
            # SerialBindNode gate promises GLOBAL bind ordering in this
            # process, and shard boundaries must not weaken it
            bind_pred = BindPredicate(self.client,
                                      locker=self._bind_locker,
                                      fence=lease,
                                      policy=self._make_policy())
            preempt_pred = PreemptPredicate(self.client,
                                            snapshot=snapshot,
                                            **self._preempt_kwargs)
            pipeline = None
            if self.scale_pipeline:
                pipeline = BindCommitPipeline(bind_pred,
                                              **self._pipeline_kwargs)
            units.append(ShardUnit(spec, lease, snapshot,
                                   filter_pred, bind_pred,
                                   preempt_pred, pipeline=pipeline))
        return units

    def _shard_selector(self, plan: ShardPlan, spec: ShardSpec):
        return lambda labels: spec.owns_labels(labels, plan.named_pools)

    # -- lifecycle ----------------------------------------------------------

    def start(self, tick_s: float | None = None,
              snapshot_poll_s: float = 1.0) -> None:
        """Production entry: seed + background-watch every shard snapshot
        (hot standby keeps them warm even for shards we don't lead) and
        run the lease tick on a daemon thread (default cadence ttl/3)."""
        self._started = True
        self._snapshot_poll_s = snapshot_poll_s
        for unit in self.units:
            if unit.snapshot is not None:
                unit.snapshot.start_background(poll_s=snapshot_poll_s)
        interval = tick_s if tick_s is not None else self.lease_ttl_s / 3.0

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.tick()
                except Exception:
                    log.exception("vtha lease tick failed")

        self.tick()      # first acquisition attempt before serving
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtha-lease-tick")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        for unit in self.units:
            if unit.snapshot is not None:
                unit.snapshot.stop_background()
            if unit.pipeline is not None:
                unit.pipeline.shutdown()
            if unit.lease.held:
                unit.lease.release()

    # -- leadership maintenance ---------------------------------------------

    def tick(self) -> None:
        """One maintenance pass over every shard: renew what we hold,
        try to acquire what is free/expired, replay after acquisition.
        Deterministic and thread-free by itself — the chaos harness
        drives it directly."""
        self._check_plan()
        for unit in self.units:
            self._maintain(unit)

    # -- dynamic shard plans (vtscale) --------------------------------------

    def _check_plan(self) -> None:
        """Adopt a newer published shard plan, rolling. Old-epoch units
        are torn down AFTER the new ones are routable; their in-flight
        binds die safely at the commit fence — building new ShardLease
        objects for the same shard names takes the same-holder/new-
        incarnation acquisition path, which CAS-bumps the token, so an
        old unit's confirm() 409s exactly like a fenced-off ex-leader.
        Commitments stamped with the old epoch are reaped by takeover
        replay (below) and by the reschedule controller's intent reaper
        — no replica restart, no dropped or doubled placement."""
        if not self.scale_pipeline:
            return
        state = plan_mod.read_plan(self.client,
                                   namespace=self._lease_namespace)
        if state is None or state.epoch <= self.plan_epoch:
            return
        if state.spec == self.plan_spec:
            # same partition republished at a higher epoch: advance the
            # fence stamps in place, keep the units
            self.plan_epoch = state.epoch
            for unit in self.units:
                unit.lease.epoch = state.epoch
            return
        self._adopt_plan(state)

    def _adopt_plan(self, state) -> None:
        try:
            new_plan = ShardPlan.parse(state.spec)
        except ValueError as e:
            log.error("vtscale: published plan epoch %d unparseable "
                      "(%s); staying on epoch %d", state.epoch, e,
                      self.plan_epoch)
            return
        log.warning("vtscale: adopting shard plan epoch %d (spec %r, "
                    "was epoch %d)", state.epoch, state.spec,
                    self.plan_epoch)
        old_units = self.units
        new_units = self._build_units(new_plan, state.epoch)
        # swap order matters: the new routing must be in place before
        # the old units lose their snapshots, so a request arriving
        # mid-adoption sees a complete plan (worst case it bounces off
        # a not-yet-acquired lease and retries onto the leader)
        self.plan = new_plan
        self.plan_spec = state.spec
        self.plan_epoch = state.epoch
        self.units = new_units
        if self._started:
            for unit in new_units:
                if unit.snapshot is not None:
                    unit.snapshot.start_background(
                        poll_s=self._snapshot_poll_s)
        for unit in new_units:
            self._maintain(unit)
        for unit in old_units:
            if unit.snapshot is not None:
                unit.snapshot.stop_background()
            if unit.pipeline is not None:
                unit.pipeline.shutdown()
        # old leases are NOT released: shard names shared with the new
        # plan were already taken over by the token bump above, and
        # names the new plan dropped just expire by TTL — releasing
        # here would race the new incarnation's record

    def _maintain(self, unit: ShardUnit) -> None:
        lease = unit.lease
        if lease.held:
            try:
                lease.renew()
            except LeaseLostError:
                unit.replayed_token = -1
            except KubeError as e:
                # transient: keep leadership, held_fresh decays it if
                # renewals keep failing
                log.warning("shard %s: renew failed transiently: %s",
                            unit.spec.name, e)
        elif self._try_acquire(unit):
            unit.handoffs += 1
            self._replay_takeover(unit)
        if lease.held and unit.replayed_token != lease.token:
            # acquisition succeeded earlier but the replay didn't (crash
            # or API failure mid-replay): retry until the shard may serve
            self._replay_takeover(unit)

    @staticmethod
    def _try_acquire(unit: ShardUnit) -> bool:
        """try_acquire with transient failures absorbed — an acquisition
        attempt that could not reach the apiserver is a standby staying
        standby, not an error to surface."""
        try:
            return unit.lease.try_acquire()
        except KubeError as e:
            log.warning("shard %s: acquire attempt failed transiently: "
                        "%s", unit.spec.name, e)
            return False

    def _replay_takeover(self, unit: ShardUnit) -> None:
        """Replay the bind-intent trail before the shard accepts work:
        any commitment stamped with an older fencing token for this
        shard belonged to a dead (or fenced-off) leader. Unbound ones
        are cleared so the pods re-enter scheduling; bound ones are the
        reschedule controller's call (eviction respects PDBs). Never
        touches real-allocated pods — that would leak devices."""
        if not unit.replay_lock.acquire(blocking=False):
            return      # a concurrent replay is already running
        try:
            self._replay_locked(unit)
        finally:
            # released on CrashFailpoint too — a crashed replay must not
            # wedge the rebuilt process's next attempt
            unit.replay_lock.release()

    def _replay_locked(self, unit: ShardUnit) -> None:
        try:
            failpoints.fire("shard.handoff", shard=unit.spec.name)
            pods = self._replay_policy.run(self.client.list_pods,
                                           op="shard.replay_list")
        except KubeError as e:
            log.warning("shard %s: takeover replay list failed (%s); "
                        "shard stays draining until the next tick",
                        unit.spec.name, e)
            return
        my_token = unit.lease.token
        for pod in pods:
            meta = pod.get("metadata") or {}
            anns = meta.get("annotations") or {}
            fence = lease_mod.parse_fence_epoch(
                anns.get(consts.shard_fence_annotation()))
            if fence is None or fence[0] != unit.spec.name:
                continue
            # vtscale: a commitment stamped under an older plan epoch
            # belonged to a superseded partition — fence-reject it like
            # a stale leader's even when its token reads current. (Old-
            # epoch stamps naming shards the new plan dropped entirely
            # are the reschedule controller's intent reaper's job.)
            stale_epoch = 0 < fence[2] < self.plan_epoch
            if fence[1] >= my_token and not stale_epoch:
                continue
            if anns.get(consts.real_allocated_annotation()):
                continue
            if (pod.get("spec") or {}).get("nodeName"):
                continue
            if not anns.get(consts.predicate_node_annotation()):
                continue
            ns = meta.get("namespace", "default")
            name = meta.get("name", "")
            log.warning("shard %s: reaping stale commitment of %s/%s "
                        "(token %d < %d)", unit.spec.name, ns, name,
                        fence[1], my_token)
            try:
                self._replay_policy.run(
                    lambda ns=ns, name=name:
                        self.client.patch_pod_annotations(
                            ns, name, recovery.commitment_clear_patch()),
                    op="shard.replay_clear")
            except KubeError as e:
                log.warning("shard %s: stale-commitment clear failed for "
                            "%s/%s (%s); retrying next tick",
                            unit.spec.name, ns, name, e)
                return
            unit.takeover_reaps += 1
        unit.replayed_token = my_token
        log.info("shard %s: takeover replay complete (token=%d, "
                 "reaped=%d)", unit.spec.name, my_token,
                 unit.takeover_reaps)

    # -- routing ------------------------------------------------------------

    def unit_for_pod(self, pod: dict) -> ShardUnit:
        """Owning unit. A pod already committed by an HA scheduler
        carries the fence stamp — routing honors it so the bind/retry of
        a committed pod lands on the shard that committed it even if the
        hash would say otherwise (e.g. plan edges during rollouts)."""
        fence = lease_mod.parse_fence(
            ((pod.get("metadata") or {}).get("annotations") or {}).get(
                consts.shard_fence_annotation()))
        if fence is not None:
            for unit in self.units:
                if unit.spec.name == fence[0]:
                    return unit
        return self.units[self.plan.home_shard(pod).index]

    def holds_fresh(self, shard_name: str) -> bool:
        for unit in self.units:
            if unit.spec.name == shard_name:
                return unit.lease.held_fresh()
        return False

    def _serving(self, unit: ShardUnit) -> str | None:
        """None when this process may serve the shard, else the routing
        error (observed holder included for operator grep-ability)."""
        lease = unit.lease
        if not lease.held_fresh():
            # opportunistic acquire: a request may arrive before the
            # first tick (or right after a peer died) — one cheap CAS
            # attempt instead of an error the extender must retry
            if self._try_acquire(unit):
                unit.handoffs += 1
                self._replay_takeover(unit)
        if not lease.held_fresh():
            observed = lease.observed
            holder = observed.holder if observed is not None else "?"
            return (f"shard {unit.spec.name} not led by this scheduler "
                    f"(holder={holder}); retry lands on the leader")
        if unit.replayed_token != lease.token:
            return (f"shard {unit.spec.name} draining: takeover replay "
                    "pending")
        return None

    # -- predicate facade (what routes.py calls) ----------------------------

    def filter(self, args: dict) -> FilterResult:
        pod = args.get("Pod") or args.get("pod") or {}
        unit = self.unit_for_pod(pod)
        why = self._serving(unit)
        if why is not None:
            unit.fence_rejections += 1
            # vtexplain: a pod bouncing off a non-led shard must
            # diagnose as ShardNotLed, not as silence (no-op when the
            # DecisionExplain gate is off)
            explain.routing_rejection(pod, unit.spec.name, why)
            return FilterResult(error=why)
        result = unit.filter_pred.filter(args)
        if self.scale_pipeline and result.error:
            spilled = self._spill_filter(args, pod, unit)
            if spilled is not None:
                return spilled
        return result

    def _spill_filter(self, args: dict, pod: dict,
                      owner: ShardUnit) -> FilterResult | None:
        """vtscale cross-shard gang spill: a gang member its home shard
        cannot place may land on a neighbor shard's nodes — chosen by
        the O(1) capacity digest, committed under the OWNER shard's
        lease + fence (fence_override), so ownership, bind routing and
        takeover replay all still follow the stamp. The digest only
        nominates; the neighbor's filter pass re-validates real
        capacity against its snapshot. None = no spill (caller returns
        the owner's verdict unchanged)."""
        gang, _ = resolve_gang_name(pod)
        if not gang:
            return None
        candidates = []
        for unit in self.units:
            if unit is owner or unit.snapshot is None:
                continue
            nodes, key_sum = unit.snapshot.capacity_digest()
            if nodes:
                # rank_key is the filter's free-capacity scalar; its
                # shard-wide sum orders neighbors by headroom
                candidates.append((key_sum, unit.spec.index, unit))
        candidates.sort(reverse=True)
        for _key_sum, _idx, unit in candidates[:2]:
            try:
                result = unit.filter_pred.filter(
                    args, fence_override=owner.lease)
            except LeaseLostError:
                # the owner's lease died between the serving check and
                # the spill commit — the pod re-enters scheduling
                return None
            if not result.error:
                owner.spills += 1
                log.info("vtscale: gang %s spilled from shard %s to "
                         "shard %s nodes", gang, owner.spec.name,
                         unit.spec.name)
                return result
        return None

    def _unit_for_node(self, node_name: str) -> ShardUnit | None:
        """Owning unit by bind-target node. The filter only places a pod
        onto its owning shard's nodes, so the node's pool names the
        shard — and the node appears in exactly that shard's scoped
        snapshot, making this a local lookup (no apiserver round-trip on
        the bind cycle). None when snapshots are off or the watch has
        not caught the node yet."""
        if not node_name:
            return None
        for unit in self.units:
            if unit.snapshot is not None \
                    and unit.snapshot.entry(node_name) is not None:
                return unit
        return None

    def bind(self, args: dict) -> BindResult:
        ns = args.get("PodNamespace") or args.get("podNamespace") \
            or "default"
        name = args.get("PodName") or args.get("podName") or ""
        node = args.get("Node") or args.get("node") or ""
        unit = self._unit_for_node(node)
        pod = None
        if unit is None:
            # TTL mode / watch lag: route by the pod's fence stamp (one
            # GET; BindPredicate re-fetches inside its serial section for
            # freshness — that second read is the authoritative one)
            try:
                pod = self.client.get_pod(ns, name)
            except KubeError as e:
                return BindResult(
                    error=f"pod fetch failed routing bind: {e}")
            unit = self.unit_for_pod(pod)
        why = self._serving(unit)
        if why is not None and self.scale_pipeline:
            # a spilled gang member binds onto a NEIGHBOR shard's node:
            # the node lookup names the neighbor, but the commitment's
            # fence stamp names the owner — re-route by the stamp before
            # rejecting (the owner's lease covers the spilled bind)
            if pod is None:
                try:
                    pod = self.client.get_pod(ns, name)
                except KubeError:
                    pod = None
            if pod is not None:
                owner = self.unit_for_pod(pod)
                if owner is not unit:
                    unit = owner
                    why = self._serving(unit)
        if why is not None:
            unit.fence_rejections += 1
            return BindResult(error=why)
        if unit.pipeline is not None:
            # vtscale: wave-batched commit — per-pod serial sections and
            # verdicts preserved, one lease confirm per wave
            return unit.pipeline.bind(args)
        return unit.bind_pred.bind(args)

    def preempt(self, args: dict) -> PreemptResult:
        pod = args.get("Pod") or args.get("pod") or {}
        unit = self.unit_for_pod(pod)
        why = self._serving(unit)
        if why is not None:
            unit.fence_rejections += 1
            return PreemptResult(error=why)
        return unit.preempt_pred.preempt(args)

    # -- observability ------------------------------------------------------

    def render_ha_metrics(self) -> str:
        """Prometheus block appended to /metrics by routes.py."""
        lines = ["# TYPE vtpu_ha_shard_leader gauge"]
        for unit in self.units:
            lines.append(f'vtpu_ha_shard_leader{{shard="{unit.spec.name}"'
                         f'}} {1 if unit.lease.held_fresh() else 0}')
        lines.append("# TYPE vtpu_ha_lease_token gauge")
        for unit in self.units:
            lines.append(f'vtpu_ha_lease_token{{shard="{unit.spec.name}"'
                         f'}} {unit.lease.token}')
        for metric, attr in (
                ("vtpu_ha_handoffs_total", "handoffs"),
                ("vtpu_ha_takeover_reaps_total", "takeover_reaps"),
                ("vtpu_ha_fence_rejections_total", "fence_rejections")):
            lines.append(f"# TYPE {metric} counter")
            for unit in self.units:
                lines.append(f'{metric}{{shard="{unit.spec.name}"}} '
                             f'{getattr(unit, attr)}')
        lines.append("# TYPE vtpu_ha_lease_conflicts_total counter")
        for unit in self.units:
            lines.append(f'vtpu_ha_lease_conflicts_total{{shard='
                         f'"{unit.spec.name}"}} {unit.lease.conflicts}')
        if any(u.snapshot is not None for u in self.units):
            lines.append(
                "# TYPE vtpu_ha_shard_snapshot_staleness_seconds gauge")
            for unit in self.units:
                if unit.snapshot is not None:
                    lines.append(
                        f'vtpu_ha_shard_snapshot_staleness_seconds'
                        f'{{shard="{unit.spec.name}"}} '
                        f"{unit.snapshot.staleness_s():.6f}")
        if self.scale_pipeline:
            lines.append("# TYPE vtpu_scale_plan_epoch gauge")
            lines.append(f"vtpu_scale_plan_epoch {self.plan_epoch}")
            lines.append("# TYPE vtpu_scale_spills_total counter")
            for unit in self.units:
                lines.append(f'vtpu_scale_spills_total{{shard='
                             f'"{unit.spec.name}"}} {unit.spills}')
            pipe_block = render_pipeline_metrics(
                [u.pipeline for u in self.units
                 if u.pipeline is not None])
            if pipe_block:
                lines.append(pipe_block)
        return "\n".join(lines)
