"""Filter predicate: the extender's core scheduling pass.

Reference: pkg/scheduler/filter/filter_predicate.go:158-268 (entry),
:312-415 (nodeFilter), :541-866 (deviceFilter). Flow per Filter call:

1. Parse the pod once into an AllocationRequest.
2. Pods with no vtpu request pass every node untouched.
3. nodeFilter: drop nodes without a device registry / failing label gates.
4. deviceFilter: build NodeInfo for each candidate in parallel from node +
   resident-pod annotations, pre-gate total capacity, allocate on each
   surviving node, score, pick the best, and write the pre-allocated +
   predicate annotations to the pod via the API server. Only the chosen
   node is returned (the reference also commits to one node at filter time).

State crosses process boundaries via annotations only. Two defenses against
double-booking (reference: SerialFilterNode gate + local informer mutation,
filter_predicate.go:853-857):
- filter passes are serialized by default (`serialize=True`; the perf
  harness may disable it to measure raw throughput);
- committed allocations enter an in-process assumed cache that is folded
  into NodeInfo until the API server's pod list reflects the annotation,
  bridging list lag even across serialized calls.

Two data paths feed the pass (SchedulerSnapshot gate):
- gate OFF (default): TTL-cached cluster-wide LISTs — every refresh
  re-decodes node registries and resident claims, O(nodes + pods) JSON;
- gate ON: the watch-driven incremental snapshot (snapshot.py) — decoded
  registries, counted-claims aggregates and free totals are maintained
  O(changed) per event, and a pass over an unchanged cluster decodes
  zero JSON (the reference's informer architecture).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from vtpu_manager import explain, trace
from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.device.allocator.allocator import (AllocationFailure,
                                                     allocate)
from vtpu_manager.device.allocator.priority import (ScoredNode, node_score,
                                                    order_nodes)
from vtpu_manager.device.allocator.request import (AllocationRequest,
                                                   RequestError,
                                                   build_allocation_request)
from vtpu_manager.device import types as dt
from vtpu_manager.clustercache import advertise as cc_advertise
from vtpu_manager.compilecache import antistorm
from vtpu_manager.device.claims import PodDeviceClaims
from vtpu_manager.device.types import NodeInfo
from vtpu_manager.fragmentation import score as frag_score
from vtpu_manager.health import codec as health_codec
from vtpu_manager.overcommit import ratio as oc_mod
from vtpu_manager.resilience import failpoints
from vtpu_manager.resilience.policy import RetryPolicy
from vtpu_manager.scheduler import gang, reason as R
from vtpu_manager.scheduler import snapshot as snap_mod
from vtpu_manager.scheduler.lease import LeaseLostError
from vtpu_manager.telemetry import pressure as tel_pressure
from vtpu_manager.topology import linkload as tl_mod
from vtpu_manager.topology.links import worst_link_load
from vtpu_manager.util import consts
from vtpu_manager.utilization import headroom as util_headroom

log = logging.getLogger(__name__)

# Nodes must carry this label to be considered when the selector is enabled
# (reference nodeFilter label gate, filter_predicate.go:312-415).
NODE_ENABLE_LABEL = "vtpu-manager-enable"

ASSUME_TTL_S = 60.0


@dataclass
class FilterResult:
    """Mirror of the extender API's ExtenderFilterResult."""

    node_names: list[str] = field(default_factory=list)
    failed_nodes: dict[str, str] = field(default_factory=dict)
    error: str = ""

    def to_wire(self) -> dict:
        out: dict = {"NodeNames": self.node_names,
                     "FailedNodes": self.failed_nodes}
        if self.error:
            out["Error"] = self.error
        return out


@dataclass
class _Assumed:
    node: str
    claims: PodDeviceClaims   # phase-peak effective set (what capacity
    ts: float                 # accounting must charge), not per-container
                              # — ts is time.monotonic(): TTL expiry must
                              # not move under an NTP step (predicate_time
                              # stays wall-clock; it crosses processes)


class FilterPredicate:
    def __init__(self, client: KubeClient, serialize: bool = True,
                 require_node_label: bool = False,
                 candidate_limit: int = 64,
                 pods_ttl_s: float = 0.0,
                 nodes_ttl_s: float = 0.0,
                 snapshot: "snap_mod.ClusterSnapshot | None" = None,
                 policy: RetryPolicy | None = None,
                 fence=None, shard_selector=None,
                 anti_storm: bool = False,
                 utilization_hint: bool = False,
                 quota_market: bool = False,
                 hbm_overcommit: bool = False,
                 cluster_cache: bool = False,
                 ici_link_aware: bool = False,
                 health_plane: bool = False,
                 frag_observatory: bool = False):
        self.client = client
        self.serialize = serialize
        # vtfrag (FragObservatory gate; default off = byte-identical
        # scrapes and ZERO extra work in BOTH data paths): OBSERVE-ONLY
        # — at the top of the shared _allocate_node body (before the
        # overcommit virtual-registry scaling, on the health-masked
        # view both paths hand in) the candidate's fragmentation rollup
        # (score.node_frag: per-gang-class disjoint placeable boxes via
        # the REAL select_submesh with the pass's dead-link set, scalar
        # 1 - largest/free score) is computed and stashed per node for
        # the /metrics frag block. Never touches the score, the
        # capacity gates, or the result: placement is byte-identical
        # with the gate on or off, and a torn rollup costs the evidence
        # for that candidate, never the placement (the _observe
        # discipline). Because the tap runs in the SHARED body on
        # caller-handed state, TTL and snapshot report identical values
        # on identical state — the parity test_frag asserts. Rides
        # filter_kwargs so vtha shards inherit it.
        self.frag_observatory = frag_observatory
        # node -> NodeFrag from the last pass that visited it (plain
        # dict assignment — GIL-atomic, same discipline as the
        # headroom-observed counter); the scheduler /metrics frag block
        # renders it gate-on, stays {} forever gate-off
        self.frag_last: dict = {}
        # vtheal (HealthPlane gate; default off = byte-identical
        # placement in BOTH data paths): the node-published chip-health
        # annotation (health/codec.py; suspect chips schedule normally,
        # degraded/failed chips cordon) becomes a HARD admission gate —
        # capacity-shaped, not score-shaped: the fast capacity pre-gate
        # and the allocator both run against a masked registry view
        # (codec.masked_registry flips cordoned chips unhealthy), so
        # every existing capacity rule excludes them with zero new
        # per-chip logic, and probe-confirmed dead ICI links are a hard
        # submesh exclusion (select_submesh dead_links). Audited as
        # UnhealthyChip / DegradedLink in failed_nodes + vtexplain.
        # Staleness re-judged at every visit: a dead health publisher
        # UN-cordons (the legacy registry healthy flip is the
        # non-decaying backstop). TTL path raw-rides the annotation per
        # visited candidate, snapshot path decodes at event-apply
        # (NodeEntry.chiphealth). Rides filter_kwargs so vtha shards
        # inherit it.
        self.health_plane = health_plane
        # vtici (ICILinkAware gate; default off = byte-identical
        # placement in BOTH data paths): score gang/ICI candidates by
        # worst-link contention with co-resident tenants — the node's
        # published link-load rollup (topology/linkload.py codec; TTL
        # path decodes per visited candidate, snapshot path at
        # event-apply on NodeEntry.linkload) feeds (1) the submesh
        # search's link dimension (box choice INSIDE a node avoids
        # contended rings) and (2) a soft link_term penalty in the
        # shared _allocate_node body (node choice ACROSS the cluster
        # repels hot fabrics — reorders fits, never vetoes one).
        # Staleness re-judged at use time (load_map), every
        # per-candidate link score rides the vtexplain breakdown, and
        # the term rides filter_kwargs so vtha shards inherit it.
        self.ici_link_aware = ici_link_aware
        # vtcs (ClusterCompileCache gate; default off = byte-identical
        # placement in BOTH data paths): a fingerprint-carrying pod
        # gets a soft warm_term bonus on nodes whose warm-keys
        # advertisement names its program — the artifact is already
        # there, so landing there starts at warm-node speed without a
        # fetch. Soft like pressure/storm (reorders fits, never vetoes
        # one), staleness re-judged at score time (a dead advertiser's
        # phantom warmth decays), decoded per-candidate on the TTL
        # path and at event-apply on the snapshot path (NodeEntry.
        # warm), and the term rides the vtexplain candidate record so
        # spread-vs-warm is auditable. Rides filter_kwargs so vtha
        # shards inherit it.
        self.cluster_cache = cluster_cache
        # vtovc (HBMOvercommit gate; default off = byte-identical
        # placement in BOTH data paths): admit the memory axis against
        # VIRTUAL capacity — physical × the node's published per-class
        # ratio (overcommit/ratio.py codec; no/stale signal = 1.0 = the
        # physical gate) — and subtract a spill-rate penalty so nodes
        # actively servicing host-tier spills repel new pods before
        # they thrash harder. Decoded per-candidate on the TTL path, at
        # event-apply on the snapshot path (NodeEntry.overcommit); the
        # virtual/physical split and the spill term ride the vtexplain
        # candidate record. Rides filter_kwargs so vtha shards inherit.
        self.hbm_overcommit = hbm_overcommit
        # vtqm (QuotaMarket gate; default off = byte-identical scores):
        # the reclaimable-headroom input both paths have decoded
        # observe-only since PR 8 becomes a REAL score term — but only
        # for latency-critical pods (the borrower class), and only
        # while the signal is fresh (headroom_score_term re-judges
        # staleness at use time, so a dead publisher degrades to the
        # exact pre-market placement). Rides filter_kwargs so vtha
        # shards inherit it like the pressure/storm terms.
        self.quota_market = quota_market
        # vtuse (UtilizationLedger gate; default off = zero extra work):
        # OBSERVE-ONLY this PR — after a pass commits, the chosen node's
        # reclaimable-headroom annotation is decoded and the score input
        # it WOULD contribute is logged in the pod's trace span and
        # counted on /metrics, so the elastic-quota PR can flip the real
        # score term on against recorded evidence. Never touches the
        # score: placement is byte-identical with the gate on or off.
        # Rides filter_kwargs in the binary, so vtha shards inherit it.
        self.utilization_hint = utilization_hint
        self.headroom_observed = 0
        # vtcc (CompileCache gate; default off = byte-identical scores):
        # spread simultaneously-starting replicas of one program
        # fingerprint as a SOFT preference so one node warms the shared
        # compile cache while the wave lands elsewhere. Rides
        # filter_kwargs in the binary, so vtha shards inherit it the
        # same way they inherit the pressure penalty.
        self.anti_storm = anti_storm
        # node -> [(pod_uid, fingerprint, commit_wall_ts)] for THIS
        # process's own commits: a same-pass gang burst must spread
        # before any watch event or pod-list refresh surfaces the
        # annotations. Entries retire the moment their pod becomes
        # visible in the resident set (the _assumed pattern — keeping
        # both would double-count the penalty) and expire by wall clock
        # as the backstop. Guarded by _assumed_lock.
        self._recent_fp: dict[str, list[tuple[str, str, float]]] = {}
        # vtha (both default None = pre-HA behavior, byte-identical):
        # `fence` is the shard's ShardLease — commits stamp its fencing
        # token in the SAME patch as the pre-allocation, and a locally
        # expired lease fails the pass instead of committing unstamped.
        # `shard_selector(labels) -> bool` gates candidates to this
        # shard's node pools on the TTL path (the snapshot path is
        # already shard-scoped at the watch).
        self.fence = fence
        self.shard_selector = shard_selector
        self._serial_lock = threading.Lock()
        self.require_node_label = require_node_label
        # commit-patch retry: the pass already paid its full allocation
        # cost when the commit patch runs, so absorbing a transient
        # 429/5xx is far cheaper than failing the pod back through the
        # scheduling queue. Tight budget — the pass holds the serial
        # section (other pods queue behind it).
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            base_delay_s=0.05,
                                            deadline_s=5.0)
        # SchedulerSnapshot gate: when a ClusterSnapshot is provided every
        # cluster read (candidates, residents, gang siblings) comes from
        # its watch-maintained state and the TTL caches below sit idle;
        # when None the TTL path runs exactly as before (the fallback).
        self._snapshot = snapshot
        # full allocation runs only on the top-K capacity-ranked nodes;
        # pure-Python work gains nothing from thread pools (GIL), and
        # allocating on every node of a 1000+-node cluster per pod is the
        # dominant filter cost
        self.candidate_limit = candidate_limit
        self._assumed: dict[str, _Assumed] = {}   # pod uid -> commit
        self._assumed_lock = threading.Lock()
        # Pod-snapshot TTL: the reference reads residents from an informer
        # cache; our analogue amortizes the cluster-wide pod LIST across
        # filter calls (a per-call LIST is O(pods) against the apiserver —
        # quadratic over a sustained admission wave). Freshness for OUR
        # own placements comes from the assumed cache, which overlays the
        # snapshot until commits become visible; 0 disables (every call
        # lists fresh — the right default for tests and tiny clusters).
        self.pods_ttl_s = pods_ttl_s
        self._pods_cache: tuple[list[dict], dict[str, list[dict]]] | None \
            = None
        self._pods_cache_ts = 0.0
        # gang resolution needs the FULL pod list (pending siblings count);
        # cached separately with the same TTL so a gang burst does not
        # re-list the 100k-scale cluster per member
        self._all_pods_cache: list[dict] | None = None
        self._all_pods_cache_ts = 0.0
        self._pods_cache_lock = threading.Lock()
        # single-flight state for _ttl_cached: which cache keys have a
        # fetch in progress, plus a condition (same underlying lock) that
        # fetchers signal so cache-empty waiters wake up
        self._pods_cache_cond = threading.Condition(self._pods_cache_lock)
        self._fetch_in_flight: set[str] = set()
        # Node-snapshot TTL, same informer-fidelity rationale as the pod
        # snapshot: the list_nodes() fallback (kube-scheduler usually
        # ships nodes IN the ExtenderArgs, but nodeCacheCapable=false
        # setups and direct callers hit it) must not LIST a 5000-node
        # cluster per pod. Node registries change on device
        # (re-)registration, minutes-scale; capacity freshness comes from
        # the pod snapshot + assumed overlay, not the node objects.
        self.nodes_ttl_s = nodes_ttl_s
        self._nodes_cache: list[dict] | None = None
        self._nodes_cache_ts = 0.0

    @staticmethod
    def _partition_by_node(pods: list[dict]) -> dict[str, list[dict]]:
        by_node: dict[str, list[dict]] = {}
        for p in pods:
            node_name = (p.get("spec") or {}).get("nodeName")
            if node_name:
                by_node.setdefault(node_name, []).append(p)
        return by_node

    # Server-side index: only pods bound to a node can hold counted claims,
    # so capacity accounting lists with this selector and a 100k-pending
    # admission wave never taxes the snapshot rebuild (the p99 of a
    # sustained run otherwise grows O(total pods) — r2 verdict).
    _SCHEDULED_SELECTOR = "spec.nodeName!="

    def _ttl_cached(self, ttl_s: float, cache_attr: str, ts_attr: str,
                    fetch):
        """ONE home for the snapshot-TTL idiom (scheduled pods, full pod
        list, nodes). time.monotonic() throughout — the idiom existed as
        three hand-rolled copies until the third (nodes) drifted to
        time.time() and an NTP step could pin a stale snapshot.

        Single-flight: the fetch runs outside the lock (it is a
        cluster-wide LIST), so without coordination N callers arriving on
        an expired cache issue N concurrent LISTs — a thundering herd
        against the apiserver exactly when the scheduler is busiest. The
        first expired caller fetches; the rest reuse the stale value (the
        assumed cache covers our own placements) or, when the cache is
        empty/invalidated, wait on the condition for the fetcher."""
        if ttl_s <= 0:
            return fetch()
        with self._pods_cache_cond:
            while True:
                now = time.monotonic()
                cached = getattr(self, cache_attr)
                if cached is not None and \
                        now - getattr(self, ts_attr) < ttl_s:
                    return cached
                if cache_attr not in self._fetch_in_flight:
                    self._fetch_in_flight.add(cache_attr)
                    break
                if cached is not None:
                    return cached          # stale beats a stampede
                self._pods_cache_cond.wait()
        try:
            value = fetch()
        except BaseException:
            with self._pods_cache_cond:
                self._fetch_in_flight.discard(cache_attr)
                self._pods_cache_cond.notify_all()
            raise
        with self._pods_cache_cond:
            setattr(self, cache_attr, value)
            setattr(self, ts_attr, time.monotonic())
            self._fetch_in_flight.discard(cache_attr)
            self._pods_cache_cond.notify_all()
        return value

    def _list_pods(self) -> tuple[list[dict], dict[str, list[dict]]]:
        """(scheduled pods, same partitioned by nodeName). The partition is
        built once per snapshot, not per filter call — at 100k pods the
        per-call walk would dominate every admission. Pending pods are
        excluded by selector; anything unbound that matters to capacity is
        in the assumed cache, and gang resolution does its own full list
        (siblings are committed before they carry a nodeName)."""

        def fetch():
            pods = self.client.list_pods(
                field_selector=self._SCHEDULED_SELECTOR)
            return pods, self._partition_by_node(pods)

        return self._ttl_cached(self.pods_ttl_s, "_pods_cache",
                                "_pods_cache_ts", fetch)

    def _list_all_pods(self) -> list[dict]:
        """Full cluster pod list (gang paths only), TTL-cached like the
        scheduled snapshot and invalidated on every commit the same way."""
        return self._ttl_cached(self.pods_ttl_s, "_all_pods_cache",
                                "_all_pods_cache_ts",
                                self.client.list_pods)

    # -- assumed-allocation cache -------------------------------------------

    def _assume(self, pod_uid: str, node: str,
                claims: PodDeviceClaims) -> None:
        with self._assumed_lock:
            self._assumed[pod_uid] = _Assumed(node, claims,
                                              time.monotonic())
        # A commit also patched pod ANNOTATIONS (pre-allocation, gang
        # origin) that the assumed cache does not carry — drop the pod
        # snapshot so the next pass (e.g. the next member of a gang
        # burst) sees them. Refresh cost scales with placement rate, not
        # filter rate; sustained rejection waves keep the cache.
        with self._pods_cache_lock:
            self._pods_cache = None
            self._all_pods_cache = None

    def _assumed_by_node(self) -> dict[str, list]:
        """One snapshot of live assumed commits per filter PASS,
        partitioned by node — the per-candidate-node walk of the whole
        assumed dict was ~15% of a 5000-node pass. Expired entries (pod
        deleted before ever appearing) are dropped here; entries whose
        pod became visible in the pod list are dropped by the caller via
        _drop_assumed, where the per-node resident set exists."""
        now = time.monotonic()
        out: dict[str, list] = {}
        with self._assumed_lock:
            for uid in list(self._assumed):
                entry = self._assumed[uid]
                if now - entry.ts > ASSUME_TTL_S:
                    del self._assumed[uid]
                else:
                    out.setdefault(entry.node, []).append((uid, entry))
        return out

    def _drop_assumed(self, uids) -> None:
        with self._assumed_lock:
            for uid in uids:
                self._assumed.pop(uid, None)

    # -- stage 1: node-level gates (cheap, no pod listing) ------------------

    def _node_gate(self, node: dict, req: AllocationRequest) -> str | None:
        meta = node.get("metadata") or {}
        labels = meta.get("labels") or {}
        if self.shard_selector is not None \
                and not self.shard_selector(labels):
            return R.NODE_OUTSIDE_SHARD
        if self.require_node_label:
            if labels.get(NODE_ENABLE_LABEL) != "true":
                return R.NODE_LABEL_MISMATCH
        anns = meta.get("annotations") or {}
        if not anns.get(consts.node_device_register_annotation()):
            return R.NODE_NO_DEVICES
        return None

    def _entry_gate(self, entry) -> str | None:
        """Snapshot analogue of _node_gate over a precomputed NodeEntry
        (registry decoded at watch-apply time, labels cached)."""
        if self.shard_selector is not None \
                and not self.shard_selector(entry.labels):
            return R.NODE_OUTSIDE_SHARD
        if self.require_node_label and \
                entry.labels.get(NODE_ENABLE_LABEL) != "true":
            return R.NODE_LABEL_MISMATCH
        if entry.registry is None:
            return R.NODE_NO_DEVICES
        return None

    # -- entry --------------------------------------------------------------

    def filter(self, args: dict, fence_override=None) -> FilterResult:
        # fence_override (vtscale cross-shard gang spill): the OWNER
        # shard's lease, when this predicate runs a spill pass on behalf
        # of a neighboring shard — the commitment must carry the owning
        # leader's fence, not this shard's (ScalePipeline gate; None =
        # byte-identical)
        pod = args.get("Pod") or args.get("pod") or {}
        fence = fence_override if fence_override is not None else self.fence
        if self._snapshot is not None:
            return self._filter_snapshot(args, pod, fence)
        nodes = self._candidate_nodes(args)
        try:
            req = build_allocation_request(pod)
        except RequestError as e:
            return FilterResult(error=f"invalid vtpu request: {e}")
        if req.is_empty():
            return FilterResult(node_names=[
                (n.get("metadata") or {}).get("name", "") for n in nodes])

        # the span opens BEFORE the serial section so serialization wait
        # (queueing behind other pods' passes) lands in the filter span —
        # under an admission wave that wait IS the pod's filter latency
        ctx = trace.context_for_pod(pod)
        with trace.span(ctx, "scheduler.filter", nodes=len(nodes)):
            if self.serialize:
                # Serializing the WHOLE pass including its API I/O is this
                # lock's purpose (reference SerialFilterNode): two
                # concurrent filters may not interleave list/allocate/
                # patch, or they double-book devices. Nothing else ever
                # takes _serial_lock, so nothing can deadlock on it.
                with self._serial_lock:
                    # vtlint: disable=lock-discipline — see above
                    return self._filter_locked(pod, req, nodes,
                                               fence=fence)
            return self._filter_locked(pod, req, nodes, fence=fence)

    def _filter_snapshot(self, args: dict, pod: dict,
                         fence=None) -> FilterResult:
        """SchedulerSnapshot entry: same pass, fed from the watch-driven
        snapshot instead of TTL LISTs. The snapshot pump is its own trace
        stage so apply-lag is attributable per pod."""
        snap = self._snapshot
        ctx = trace.context_for_pod(pod)
        pump_stats: dict = {}
        with trace.span(ctx, "scheduler.snapshot", pump=pump_stats):
            applied, relisted = snap.ensure_fresh()
            pump_stats.update(applied=applied, relisted=relisted,
                              staleness_s=round(snap.staleness_s(), 6),
                              generation=snap.generation)
        names = self._candidate_names(args)
        try:
            req = build_allocation_request(pod)
        except RequestError as e:
            return FilterResult(error=f"invalid vtpu request: {e}")
        if req.is_empty():
            # non-vtpu pods pass every requested node untouched — the
            # requested NAMES, not the snapshot's view of them (a node
            # the watch has not caught up with is none of our business)
            return FilterResult(node_names=(
                names if names is not None
                else list(snap.entries().keys())))
        missing: list[str] = []
        candidates = None
        if names is not None:
            entries = snap.entries()
            candidates = []
            for name in names:
                entry = entries.get(name)
                if entry is not None:
                    candidates.append(entry)
                else:
                    # the scheduler's informer can be fresher than our
                    # watch (apply-lag); surface the gap instead of
                    # silently shrinking the candidate set
                    missing.append(name)
        n_nodes = (len(candidates) if candidates is not None
                   else len(snap.entries()))
        with trace.span(ctx, "scheduler.filter", nodes=n_nodes):
            if self.serialize:
                # same whole-pass serial section as the TTL path: see
                # the rationale on filter()'s serialize branch
                with self._serial_lock:
                    # vtlint: disable=lock-discipline — see above
                    result = self._filter_locked(pod, req, candidates,
                                                 snap=snap, fence=fence)
            else:
                result = self._filter_locked(pod, req, candidates,
                                             snap=snap, fence=fence)
        for name in missing:
            result.failed_nodes.setdefault(
                name, "node not yet in scheduler snapshot")
        return result

    @staticmethod
    def _candidate_names(args: dict) -> list[str] | None:
        """Requested candidate node names from ExtenderArgs. Both wire
        shapes reduce to names on the snapshot path: with
        nodeCacheCapable=true the names ARE the payload (no more
        one-GET-per-name), and full NodeList payloads are treated as a
        name filter over the snapshot. None = no restriction — the pass
        walks the snapshot's capacity rank directly, without
        materializing an O(nodes) list."""
        node_list = args.get("Nodes") or args.get("nodes")
        if node_list:
            items = node_list.get("Items") or node_list.get("items")
            if items:
                return [(n.get("metadata") or {}).get("name", "")
                        for n in items]
        raw = args.get("NodeNames") or args.get("nodenames")
        if raw is not None:
            return list(raw)
        return None

    def _candidate_nodes(self, args: dict) -> list[dict]:
        # ExtenderArgs with nodeCacheCapable=false carries the full NodeList
        # (k8s JSON: {"nodes":{"items":[...]}}); with nodeCacheCapable=true
        # only node names. Accept both Go-field and JSON-tag casing.
        node_list = args.get("Nodes") or args.get("nodes")
        if node_list:
            items = node_list.get("Items") or node_list.get("items")
            if items:
                return items
        names = args.get("NodeNames") or args.get("nodenames")
        listing = self._ttl_cached(self.nodes_ttl_s, "_nodes_cache",
                                   "_nodes_cache_ts",
                                   self.client.list_nodes)
        if names is None:
            return listing
        # nodeCacheCapable=true sends names only; resolving them with one
        # get_node per name was O(N) API round-trips per pass — serve
        # them from the (TTL-cached) listing instead. A name the cached
        # listing lacks may be a node newer than the cache (the
        # scheduler's informer is independent and can be fresher), so
        # only those few fall back to a fresh GET; a real 404 skips the
        # name, same as the per-name path did.
        by_name = {(n.get("metadata") or {}).get("name", ""): n
                   for n in listing}
        out = []
        for name in names:
            node = by_name.get(name)
            if node is None:
                try:
                    node = self.client.get_node(name)
                except KubeError as e:
                    if e.status != 404:
                        # only "node really gone" may silently shrink the
                        # candidate set; a throttle/outage must be visible
                        log.warning("node %s fetch failed during filter "
                                    "(%s); skipping it this pass", name, e)
                    continue
            out.append(node)
        return out

    def _filter_locked(self, pod: dict, req: AllocationRequest,
                       nodes: list, snap=None, fence=None) -> FilterResult:
        """One pass. ``nodes`` carries node dicts on the TTL path and
        snapshot NodeEntry objects when ``snap`` is set; both converge on
        the same ranked tuples, so ordering/allocation/commit below are
        one code path and cannot drift between the modes."""
        now = time.time()
        ctx = trace.context_for_pod(pod)
        result = FilterResult()
        reasons = R.FailureReasons()
        # vtexplain (DecisionExplain gate; None when off = one is-None
        # check, pass byte-identical): the per-pass audit record. Every
        # touch point below guards on the builder, and record() is ring-
        # only (zero I/O) — the decision hot path never pays disk.
        explain_b = explain.pass_builder(
            pod, "snapshot" if snap is not None else "ttl",
            fence=fence)
        if explain_b is not None:
            explain_b.set_request(req)

        if snap is not None and nodes is None:
            # unrestricted snapshot pass: no O(nodes) candidate list —
            # the rank walk gates each visited entry lazily
            candidates = None
        else:
            candidates = []
            for node in nodes:
                if snap is not None:
                    name, why = node.name, self._entry_gate(node)
                else:
                    name = (node.get("metadata") or {}).get("name", "")
                    why = self._node_gate(node, req)
                if why is None:
                    candidates.append(node)
                else:
                    result.failed_nodes[name] = why
                    reasons.add(why, name)
                    if explain_b is not None:
                        explain_b.reject(name, why)

        # One cluster-wide scheduled-pod list per pass (TTL-cached, see
        # _list_pods), partitioned by nodeName — not one API call per
        # candidate node. The snapshot path keeps residents per entry.
        by_node = {} if snap is not None else self._list_pods()[1]

        prefer_origin = None
        gang_domains: set[str] = set()
        gang_siblings: list[dict] = []
        if req.gang_name:
            # Siblings resolved ONCE per pass (not per candidate node —
            # the cluster pod list is the 100k-scale structure here),
            # excluding this pod itself and members that no longer count;
            # every gang signal below (origin, domains, anchors) derives
            # from this one list so a dead member cannot bias any of them.
            # Needs the FULL list: burst siblings are committed (and carry
            # the gang/predicate annotations) before they have a nodeName.
            # Traced as its own child stage: gang resolution is the one
            # filter step whose cost scales with the CLUSTER pod list
            # (snapshot mode: with the gang index, only with the GANG), so
            # a slow placement must be attributable to it specifically.
            with trace.span(ctx, "scheduler.gang", gang=req.gang_name):
                pod_meta = pod.get("metadata") or {}
                gang_ns = pod_meta.get("namespace", "default")
                if snap is not None:
                    gang_siblings = gang.live_siblings_indexed(
                        snap.gang_members(gang_ns, req.gang_name),
                        pod_meta.get("uid", ""))
                else:
                    gang_siblings = gang.live_siblings(
                        req.gang_name, pod_meta.get("uid", ""),
                        self._list_all_pods(), namespace=gang_ns)
                prefer_origin = gang.resolve_gang_origin(
                    req.gang_name, gang_siblings, namespace=gang_ns)
                # L2 cross-node affinity: domains the gang already
                # occupies. Domain lookup is bounded to the nodes this
                # call can see; a sibling on a node outside the candidate
                # list contributes no signal (bias degrades to none,
                # never to a wrong bias).
                domain_by_node = {}
                if snap is not None:
                    pool = (candidates if candidates is not None
                            else snap.entries().values())
                    for entry in pool:
                        if entry.registry is not None \
                                and entry.registry.mesh_domain:
                            domain_by_node[entry.name] = \
                                entry.registry.mesh_domain
                else:
                    for node in nodes:
                        meta = node.get("metadata") or {}
                        reg = dt.decode_registry(
                            (meta.get("annotations") or {}).get(
                                consts.node_device_register_annotation()))
                        if reg is not None and reg.mesh_domain:
                            domain_by_node[meta.get("name", "")] = \
                                reg.mesh_domain
                gang_domains = gang.sibling_domains(gang_siblings,
                                                    domain_by_node)

        assumed_by_node = self._assumed_by_node()
        spread = req.node_policy == consts.NODE_POLICY_SPREAD
        # The program fingerprint keys TWO soft terms, each behind its
        # own gate (both off => "" => zero extra work, scores
        # byte-identical): vtcc anti-storm repels the next replica from
        # nodes that just took one (spread the cold wave), vtcs
        # warm-preference attracts replicas to nodes ALREADY advertising
        # the compiled artifact. The uid keeps a re-filtered committed
        # pod from repelling itself through the unbound-commitment scan.
        fp = (antistorm.pod_fingerprint(pod)
              if (self.anti_storm or self.cluster_cache) else "")
        pod_fp = fp if self.anti_storm else ""       # storm signal key
        warm_fp = fp if self.cluster_cache else ""   # vtcs warm key
        pod_uid = (pod.get("metadata") or {}).get("uid", "")
        # vtqm: the headroom term scores only latency-critical pods
        # (one webhook-normalized annotation read per pass; gate off or
        # other classes => False => zero extra work, scores identical)
        hr_term = False
        if self.quota_market:
            from vtpu_manager.quota import workload_class_of
            hr_term = (workload_class_of(pod)
                       == consts.WORKLOAD_CLASS_LATENCY_CRITICAL)
        # vtovc: the pod's class selects which published ratio admits it
        # (one annotation read per pass; gate off => "" is never used
        # because no overcommit object is ever decoded)
        oc_class = ""
        if self.hbm_overcommit:
            from vtpu_manager.quota import workload_class_of
            oc_class = workload_class_of(pod)
        if snap is not None:
            # walk the snapshot's incrementally maintained capacity rank
            # — no per-pass O(nodes) ranking, no decode
            scored = self._snapshot_scored(
                snap, req, candidates, assumed_by_node, spread,
                gang_domains, gang_siblings, prefer_origin, result,
                reasons, now, pod_fp=pod_fp, pod_uid=pod_uid,
                explain_b=explain_b, hr_term=hr_term, oc_class=oc_class,
                warm_fp=warm_fp)
        else:
            scored = self._ttl_scored(
                req, candidates, by_node, assumed_by_node, spread,
                gang_domains, gang_siblings, prefer_origin, result,
                reasons, now, pod_fp=pod_fp, pod_uid=pod_uid,
                explain_b=explain_b, hr_term=hr_term, oc_class=oc_class,
                warm_fp=warm_fp)

        if not scored:
            result.error = reasons.summary() or "no schedulable vtpu node"
            if explain_b is not None:
                explain_b.error(result.error)
                explain.submit(explain_b)
            self._emit_rejection_event(pod, result.error)
            return result

        ordered = order_nodes(scored)
        best = ordered[0]
        try:
            self._commit(pod, req, best, fence)
        except LeaseLostError as e:
            # vtha: the shard lease expired (or was taken over) between
            # pass start and commit — the pass must fail WITHOUT writing
            # a commitment another leader could race
            result.node_names = []
            result.error = f"shard lease lost before commit: {e}"
            if explain_b is not None:
                explain_b.error(result.error, code=R.POD_LEASE_LOST)
                explain.submit(explain_b)
            return result
        result.node_names = [best.name]
        if explain_b is not None:
            explain_b.chosen(best.name,
                             best.score - ordered[1].score
                             if len(ordered) > 1 else None)
            explain.submit(explain_b)
        if self.utilization_hint:
            self._observe_headroom(pod, best.name,
                                   candidates if snap is None else None,
                                   snap)
        return result

    def _observe_headroom(self, pod: dict, node_name: str,
                          candidates: list | None, snap) -> None:
        """vtuse observe-only tap: record the reclaimable-headroom
        signal the chosen node carried at placement time — the evidence
        stream ("would this score term have changed anything?") the
        quota-market PR validates against before flipping it on. A
        failure here can cost the EVIDENCE, never the placement (the
        pass already committed)."""
        try:
            hr = None
            if snap is not None:
                entry = snap.entry(node_name)
                hr = entry.headroom if entry is not None else None
            else:
                for node in candidates or []:
                    meta = node.get("metadata") or {}
                    if meta.get("name") == node_name:
                        hr = util_headroom.parse_headroom(
                            (meta.get("annotations") or {}).get(
                                consts.
                                node_reclaimable_headroom_annotation()))
                        break
            score_input = util_headroom.headroom_score_input(hr)
            if hr is not None:
                self.headroom_observed += 1
            trace.event(
                trace.context_for_pod(pod), "scheduler.headroom",
                node=node_name, signal=hr is not None,
                score_input=round(score_input, 2),
                reclaim_core_pct=round(hr.total_reclaim_core_pct(), 2)
                if hr else 0.0)
        except Exception:  # noqa: BLE001 — observability must never
            # fail a committed pass
            log.debug("headroom observe failed", exc_info=True)

    def _ttl_scored(self, req: AllocationRequest, candidates: list[dict],
                    by_node: dict, assumed_by_node: dict, spread: bool,
                    gang_domains: set, gang_siblings: list,
                    prefer_origin, result: FilterResult, reasons,
                    now: float, pod_fp: str = "", pod_uid: str = "",
                    explain_b=None, hr_term: bool = False,
                    oc_class: str = "",
                    warm_fp: str = "") -> list[ScoredNode]:
        """TTL-path ranking: gate + rank every surviving node on fast
        free totals (memoized registry totals minus claim sums — no
        DeviceUsage materialized), then build the full usage view lazily,
        only for nodes the allocator actually visits."""
        ranked = []
        reg_ann = consts.node_device_register_annotation()
        hr_ann = consts.node_reclaimable_headroom_annotation()
        oc_ann = consts.node_overcommit_annotation()
        warm_ann = consts.node_cache_keys_annotation()
        ll_ann = consts.node_ici_link_load_annotation()
        hp_ann = consts.node_chip_health_annotation()
        now_visible: set[str] = set()
        req_number, req_cores, req_memory = (
            req.total_number(), req.total_cores(), req.total_memory())
        # anti-storm signal sources, collected only for fingerprinted
        # pods: resident pods' stamped annotations (one dict-get per
        # resident, alongside the claims walk this loop already does),
        # this process's own recent commits, AND committed-but-unbound
        # pods from the cluster list — the wave an independent scheduler
        # just placed, invisible to the nodeName-keyed resident scan
        # (the snapshot path reads the same signal from its index)
        fp_overlay = self._recent_fp_overlay(now) if pod_fp else {}
        unbound_fp = (self._unbound_committed_fp(now, exclude_uid=pod_uid)
                      if pod_fp else {})
        for node in candidates:
            meta = node.get("metadata") or {}
            name = meta.get("name", "")
            registry = dt.decode_registry(
                (meta.get("annotations") or {}).get(reg_ann))
            if registry is None:
                result.failed_nodes[name] = R.NODE_NO_DEVICES
                reasons.add(R.NODE_NO_DEVICES, name)
                if explain_b is not None:
                    explain_b.reject(name, R.NODE_NO_DEVICES)
                continue
            resident = by_node.get(name, [])
            counted = dt.counted_claims(resident, now=now)
            assumed = assumed_by_node.get(name, [])
            if assumed:
                # an assumed commit whose pod reached the pod list is
                # double-counted if kept; visible ones retire now
                visible = {(p.get("metadata") or {}).get("uid", "")
                           for p in resident}
                retired = [u for u, _ in assumed if u in visible]
                if retired:
                    now_visible.update(retired)
                    assumed = [(u, e) for u, e in assumed
                               if u not in visible]
            # vtheal: the cordon is a masked registry view — every
            # capacity rule below (totals, mem_bonus, the allocator's
            # per-device gate) runs against it, so degraded/failed
            # chips are excluded exactly like exhausted capacity.
            # Raw-ride discipline like headroom/warm (one dict-get +
            # cheap parse per candidate under the gate; off = None =
            # byte-identical), staleness re-judged by cordon_mask.
            h_health = (health_codec.parse_chip_health(
                (meta.get("annotations") or {}).get(hp_ann), now=now)
                if self.health_plane else None)
            h_mask = health_codec.cordon_mask(h_health, now=now)
            h_dead = health_codec.dead_links(h_health, now=now)
            gate_reg = health_codec.masked_registry(registry, h_mask)
            claim_sets = ([c for _, c in counted]
                          + [e.claims for _, e in assumed])
            free_number, free_cores, free_memory = dt.fast_free_totals(
                gate_reg, claim_sets)
            # vtovc: the memory axis may admit against VIRTUAL capacity
            # — physical free plus (ratio-1)×healthy HBM, a safe
            # overestimate the allocator below re-validates against the
            # exactly-scaled per-chip registry. Decoded per candidate
            # (the ISSUE'd TTL-path discipline, same cost class as the
            # pressure parse); gate off = no parse, bonus 0.
            overcommit = None
            oc_ratio = 1.0
            if self.hbm_overcommit:
                overcommit = oc_mod.parse_overcommit(
                    (meta.get("annotations") or {}).get(oc_ann), now=now)
                oc_ratio = oc_mod.ratio_for_class(overcommit, oc_class,
                                                  now=now)
            mem_bonus = (int((oc_ratio - 1.0)
                             * gate_reg.healthy_totals()[2])
                         if oc_ratio > 1.0 else 0)
            if (free_number < req_number or free_cores < req_cores
                    or free_memory + mem_bonus < req_memory):
                why = R.NODE_INSUFFICIENT_CAPACITY
                if h_mask and self._fits_unmasked(
                        registry, claim_sets, oc_ratio,
                        req_number, req_cores, req_memory):
                    # the cordon — not real exhaustion — shaped this
                    # verdict: audit it as the health gate it is
                    why = R.UNHEALTHY_CHIP
                result.failed_nodes[name] = why
                reasons.add(why, name)
                if explain_b is not None:
                    explain_b.reject(name, why)
                continue
            pressure = tel_pressure.parse_pressure(
                (meta.get("annotations") or {}).get(
                    consts.node_pressure_annotation()))
            storm = (self._storm_for_node(
                name, fp_overlay,
                {(p.get("metadata") or {}).get("uid", "")
                 for p in resident}
                if (fp_overlay.get(name) or unbound_fp.get(name))
                else (),
                antistorm.recent_from_pods(resident, now),
                unbound=unbound_fp.get(name, ()))
                if pod_fp else ())
            # headroom rides RAW here (one dict-get) and decodes only
            # for nodes the allocation loop actually visits — parsing
            # per ranked node would decode ~cluster-size annotations per
            # pass to record at most candidate_limit of them. Fetched
            # for the audit record AND for the vtqm score term
            # (latency-critical pods under QuotaMarket); every other
            # pass carries None.
            hr_raw = ((meta.get("annotations") or {}).get(hr_ann)
                      if explain_b is not None or hr_term else None)
            # vtcs: same raw-ride discipline as headroom — one dict-get
            # per ranked node, parsed only for nodes the allocation
            # loop actually visits (and only for fingerprinted pods
            # under the gate; every other pass carries None)
            warm_raw = ((meta.get("annotations") or {}).get(warm_ann)
                        if warm_fp else None)
            # vtici: same raw-ride discipline — the link-load rollup
            # decodes only for nodes the allocation loop visits (gate
            # off = no dict-get, no parse, byte-identical scores)
            ll_raw = ((meta.get("annotations") or {}).get(ll_ann)
                      if self.ici_link_aware else None)
            # gate_reg rides in the registry slot: the gang-domain sort
            # reads only mesh_domain (mask-invariant) and the allocator
            # must see the SAME cordoned view the gate admitted against
            ranked.append((free_cores + (free_memory >> 24) + free_number,
                           name, gate_reg, counted, assumed, pressure,
                           storm, hr_raw, overcommit, oc_ratio,
                           warm_raw, ll_raw, h_mask, h_dead))
        if now_visible:
            self._drop_assumed(now_visible)
        # binpack wants the least-free node first, spread the most-free.
        # Gang-domain nodes walk FIRST regardless: the +100 scoring bonus
        # is useless if candidate_limit truncation never visits them (a
        # sibling's partially-used slice sorts last under spread on a big
        # cluster — exactly the node that must be scored).
        ranked.sort(key=lambda t: (t[0], t[1]), reverse=spread)
        if gang_domains:
            ranked.sort(key=lambda t: t[2].mesh_domain not in gang_domains)

        # Full allocation on the top-K ranked nodes; if NONE of them fit
        # (the capacity rank is blind to topology/uuid constraints), keep
        # walking the remainder until one succeeds — truncation must trade
        # only placement optimality, never schedulability.
        scored: list[ScoredNode] = []
        for rank, (_, name, registry, counted, assumed, pressure,
                   storm, hr_raw, overcommit, oc_ratio, warm_raw,
                   ll_raw, h_mask, h_dead) in enumerate(ranked):
            if rank >= self.candidate_limit and scored:
                break
            self._allocate_node(name, registry, counted, assumed, req,
                                prefer_origin, gang_siblings,
                                gang_domains, scored, result, reasons,
                                pressure=pressure, storm_fp=pod_fp,
                                storm_recent=storm,
                                headroom=util_headroom.parse_headroom(
                                    hr_raw) if hr_raw else None,
                                explain_b=explain_b, hr_term=hr_term,
                                overcommit=overcommit,
                                oc_ratio=oc_ratio, warm_fp=warm_fp,
                                warm=cc_advertise.parse_warm_keys(
                                    warm_raw) if warm_raw else None,
                                linkload=tl_mod.parse_link_load(
                                    ll_raw) if ll_raw else None,
                                health_mask=h_mask, health_dead=h_dead)
        return scored

    @staticmethod
    def _fits_unmasked(registry, claim_sets: list, oc_ratio: float,
                       req_number: int, req_cores: int,
                       req_memory: int) -> bool:
        """Whether the UNMASKED registry would have admitted the pod —
        the cordon-attribution probe behind the UnhealthyChip reason
        (runs only for nodes that both carry a cordon mask AND failed
        the masked gate, so the steady state never pays it)."""
        free = dt.fast_free_totals(registry, claim_sets)
        bonus = (int((oc_ratio - 1.0) * registry.healthy_totals()[2])
                 if oc_ratio > 1.0 else 0)
        return (free[0] >= req_number and free[1] >= req_cores
                and free[2] + bonus >= req_memory)

    def _snapshot_scored(self, snap, req: AllocationRequest,
                         candidates: list, assumed_by_node: dict,
                         spread: bool, gang_domains: set,
                         gang_siblings: list, prefer_origin,
                         result: FilterResult, reasons,
                         now: float, pod_fp: str = "", pod_uid: str = "",
                         explain_b=None, hr_term: bool = False,
                         oc_class: str = "",
                         warm_fp: str = "") -> list[ScoredNode]:
        """Snapshot-path candidate walk. The capacity rank is maintained
        by the snapshot O(log n) per event, so the pass walks its head in
        policy order (ascending for binpack, descending for spread) and
        stops at candidate_limit successful-capacity visits — the same
        truncation contract as the TTL sort, without ranking 5000 nodes
        per pod. Every visited node is re-validated on exact totals
        (conditional expiries and the assumed overlay folded in), so a
        stale rank key can cost a visit, never an overcommit. Nodes the
        walk never reaches don't get failed_nodes entries (the TTL path
        reports every node); a no-fit pass still walks everything.
        ``candidates`` None means unrestricted: entries resolve straight
        off the snapshot and the node gate runs per visit."""
        req_number, req_cores, req_memory = (
            req.total_number(), req.total_cores(), req.total_memory())
        if candidates is None:
            cand_get = snap.entries().get
        else:
            cand_get = {e.name: e for e in candidates}.get
        # retire assumed commits whose pods reached the snapshot; keep
        # the leftovers as the per-node overlay for the walk (O(assumed),
        # not O(candidates))
        assumed_left: dict[str, list] = {}
        now_visible: set[str] = set()
        for name, assumed in assumed_by_node.items():
            entry = cand_get(name)
            if entry is not None:
                retired = [u for u, _ in assumed if u in entry.resident]
                if retired:
                    now_visible.update(retired)
                    assumed = [(u, e) for u, e in assumed
                               if u not in entry.resident]
            if assumed:
                assumed_left[name] = assumed
        if now_visible:
            self._drop_assumed(now_visible)

        scored: list[ScoredNode] = []
        visited = 0
        lazy_gate = candidates is None
        fp_overlay = self._recent_fp_overlay(now) if pod_fp else {}
        # vtcs: one O(1) reverse lookup per pass on the snapshot's
        # copy-on-write fp→nodes index — only indexed nodes carry their
        # parsed advertisement into scoring (warm_term still re-judges
        # staleness per use; the index and NodeEntry.warm are updated
        # by the same event apply, so membership is never narrower
        # than the entry's own fps)
        warm_set = frozenset(snap.warm_nodes(warm_fp)) if warm_fp \
            else frozenset()

        def visit(entry) -> None:
            nonlocal visited
            name = entry.name
            if lazy_gate:
                why = self._entry_gate(entry)
                if why is not None:
                    result.failed_nodes[name] = why
                    reasons.add(why, name)
                    if explain_b is not None:
                        explain_b.reject(name, why)
                    return
            if entry.conditional and any(now > c[2]
                                         for c in entry.conditional):
                # grace expiries have no watch event; prune lazily so
                # the steady state returns to the precomputed triple
                snap.prune_expired(name, now)
                entry = snap.entry(name) or entry
            assumed = assumed_left.get(name, [])
            # vtheal: cordoned chips gate exactly like the TTL path —
            # the parsed rollup was cached at event-apply
            # (NodeEntry.chiphealth), staleness re-judged per visit by
            # cordon_mask, and a non-empty mask forces the exact-totals
            # recompute against the masked view (cordoned nodes are the
            # rare case; the steady state keeps the precomputed triple)
            h_health = entry.chiphealth if self.health_plane else None
            h_mask = health_codec.cordon_mask(h_health, now=now)
            h_dead = health_codec.dead_links(h_health, now=now)
            gate_reg = entry.registry
            if h_mask:
                gate_reg = health_codec.masked_registry(entry.registry,
                                                        h_mask)
                free = dt.fast_free_totals(
                    gate_reg,
                    [c for _, c in snap_mod.entry_counted(entry, now)]
                    + [e.claims for _, e in assumed])
            elif entry.conditional or assumed:
                free = snap_mod.entry_free_totals(
                    entry, [e.claims for _, e in assumed], now)
            else:
                free = entry.base_free
            # vtovc: virtual memory admission — the ratio was decoded
            # at event-apply time (NodeEntry.overcommit); class lookup
            # + staleness re-judgement happen per visit, so a dead
            # publisher decays to the physical gate without any event
            overcommit = entry.overcommit if self.hbm_overcommit \
                else None
            oc_ratio = (oc_mod.ratio_for_class(overcommit, oc_class,
                                               now=now)
                        if overcommit is not None else 1.0)
            mem_bonus = (int((oc_ratio - 1.0)
                             * gate_reg.healthy_totals()[2])
                         if oc_ratio > 1.0 else 0)
            if (free[0] < req_number or free[1] < req_cores
                    or free[2] + mem_bonus < req_memory):
                why = R.NODE_INSUFFICIENT_CAPACITY
                if h_mask and self._fits_unmasked(
                        entry.registry,
                        [c for _, c in snap_mod.entry_counted(entry,
                                                              now)]
                        + [e.claims for _, e in assumed],
                        oc_ratio, req_number, req_cores, req_memory):
                    why = R.UNHEALTHY_CHIP
                result.failed_nodes[name] = why
                reasons.add(why, name)
                if explain_b is not None:
                    explain_b.reject(name, why)
                return
            visited += 1
            storm = (self._storm_for_node(
                name, fp_overlay, entry.resident, entry.fp_recent,
                unbound=tuple(e for e in snap.unbound_fp(name)
                              if e[0] != pod_uid))
                     if pod_fp else ())
            self._allocate_node(name, gate_reg,
                                snap_mod.entry_counted(entry, now),
                                assumed, req, prefer_origin,
                                gang_siblings, gang_domains, scored,
                                result, reasons,
                                pressure=entry.pressure, storm_fp=pod_fp,
                                storm_recent=storm,
                                headroom=entry.headroom
                                if explain_b is not None or hr_term
                                else None,
                                explain_b=explain_b, hr_term=hr_term,
                                overcommit=overcommit,
                                oc_ratio=oc_ratio, warm_fp=warm_fp,
                                warm=entry.warm if name in warm_set
                                else None,
                                linkload=entry.linkload
                                if self.ici_link_aware else None,
                                health_mask=h_mask, health_dead=h_dead)

        # gang-domain candidates walk first regardless of global rank
        # (same bump the TTL sort applies): the +100 scoring bonus is
        # useless if truncation never visits them
        gang_names: set[str] = set()
        if gang_domains:
            pool = (candidates if candidates is not None
                    else snap.entries().values())
            bumped = [e for e in pool
                      if e.registry is not None
                      and e.registry.mesh_domain in gang_domains]
            bumped.sort(key=lambda e: (e.rank_key, e.name),
                        reverse=spread)
            gang_names = {e.name for e in bumped}
            for entry in bumped:
                if visited >= self.candidate_limit and scored:
                    break
                visit(entry)
        # lazy rank walk (vtscale): a head-limited pass visits
        # candidate_limit items without materializing the 50k-node rank
        for _key, name in snap.rank_walk(reverse=spread):
            if visited >= self.candidate_limit and scored:
                break
            if name in gang_names:
                continue
            entry = cand_get(name)
            if entry is None:
                continue
            visit(entry)
        return scored

    def _allocate_node(self, name: str, registry, counted: list,
                       assumed: list, req: AllocationRequest,
                       prefer_origin, gang_siblings: list,
                       gang_domains: set, scored: list,
                       result: FilterResult, reasons,
                       pressure=None, storm_fp: str = "",
                       storm_recent=(), headroom=None,
                       explain_b=None, hr_term: bool = False,
                       overcommit=None, oc_ratio: float = 1.0,
                       warm_fp: str = "", warm=None,
                       linkload=None,
                       health_mask: frozenset = frozenset(),
                       health_dead: frozenset = frozenset()) -> None:
        """Full allocation + scoring for one capacity-gated node — the
        one body both data paths share, so placement semantics cannot
        drift between them (and so the vtexplain breakdown is assembled
        HERE, where the actual score arithmetic runs: the record carries
        the exact values applied, not a re-derivation that could
        diverge)."""
        # vtfrag observe-only tap: BEFORE the overcommit scaling below,
        # on the exact (health-masked) registry + claim state the pass
        # places against — the one point both data paths fund with
        # identical inputs, so TTL and snapshot report the same rollup
        # on the same state. A torn rollup may cost the evidence for
        # this candidate, never the placement (the _observe discipline).
        if self.frag_observatory:
            try:
                self.frag_last[name] = frag_score.node_frag(
                    registry,
                    [c for _uid, c in counted]
                    + [e.claims for _uid, e in assumed],
                    dead_links=health_dead)
            except Exception:  # noqa: BLE001 — observe-only: the frag
                # signal is advisory and must never fail a pass
                log.warning("frag rollup failed for %s", name,
                            exc_info=True)
        # vtovc: admission runs against the VIRTUAL registry — every
        # healthy chip's HBM scaled by the pod-class ratio (memoized
        # copy; ratio 1.0 returns the physical registry object itself,
        # so the gate-off pass is byte-identical). The allocator's
        # per-chip placement therefore respects the virtual per-chip
        # caps exactly, not just a node-total approximation.
        if oc_ratio > 1.0:
            registry = oc_mod.virtual_registry(registry, oc_ratio)
        # the gate already decoded/filtered everything this needs —
        # build the usage view from its outputs, never recompute
        info = NodeInfo.from_registry(name, registry, counted)
        for uid, entry in assumed:
            info.assume_pod(uid, entry.claims)
        # same-node siblings anchor the submesh search so a gang
        # sharing a node tiles contiguously on the mesh (cross-pod
        # ICI adjacency — the L0 NVLink-component analogue); burst
        # siblings are attributed via the predicate-node annotation
        # because they are committed before they carry a nodeName
        anchor = gang.sibling_anchor_cells(
            name, gang_siblings, registry) if gang_siblings else None
        # vtici: the load map decodes the cached rollup ONCE per
        # candidate, re-judging staleness at use time (a dead
        # publisher's last contention claim decays to None = the
        # byte-identical pre-vtici search + score)
        link_load = tl_mod.load_map(linkload) \
            if linkload is not None else None
        try:
            alloc_result = allocate(info, req,
                                    prefer_origin=prefer_origin,
                                    anchor_cells=anchor,
                                    link_load=link_load,
                                    dead_links=health_dead or None)
        except AllocationFailure as f:
            if health_mask and f.reasons.counts.get(R.UNHEALTHY):
                # vtheal: the registry handed in was the masked cordon
                # view, so the allocator's generic Unhealthy rejections
                # on this node include cordoned chips — surface the
                # health-plane cause alongside (the doctor keys off it)
                f.reasons.add(R.UNHEALTHY_CHIP, name)
            why = f.reasons.summary() or "allocation failed"
            result.failed_nodes[name] = why
            # ONE derivation (explain.reason_code) feeds both the event
            # aggregation and the audit record — they cannot disagree
            code = explain.reason_code(why)
            reasons.add(code, name)
            if explain_b is not None:
                explain_b.reject(name, code, detail=why)
            return
        base = node_score(alloc_result, req)
        score = base
        # vttel soft hint: tenants on this node are stalling in the
        # throttle — prefer an equal node whose tenants aren't. A
        # PENALTY only: pressure can reorder fits, never veto one (a
        # pressured node with the only free chips still schedules).
        pressure_pen = tel_pressure.pressure_penalty(pressure)
        score -= pressure_pen
        # vtcc anti-storm: same soft-only contract as pressure —
        # recently-placed same-fingerprint pods repel the next replica
        # so compile storms spread, but a storm-heavy node with the
        # only free chips still schedules (runs after the capacity
        # gate; subtracts, never vetoes)
        storm_pen = 0.0
        if storm_fp:
            storm_pen = antistorm.storm_penalty(storm_fp, storm_recent)
            score -= storm_pen
        # vtovc thrash backoff: a node actively servicing host-tier
        # spills repels new pods — soft like pressure/storm (reorders
        # fits, never vetoes one; a thrashing node with the only free
        # chips still schedules), staleness re-judged at use time so a
        # dead publisher's last panic decays to no penalty.
        spill_pen = 0.0
        if overcommit is not None:
            spill_pen = oc_mod.spill_penalty(overcommit)
            score -= spill_pen
        gang_bonus = 0.0
        if gang_domains and registry.mesh_domain in gang_domains:
            # keeping the gang on one multi-host slice outweighs any
            # per-node topology/packing difference: a member placed
            # off-slice pays DCN for every gang collective
            gang_bonus = 100.0
            score += gang_bonus
        warm_bonus = 0.0
        if warm_fp:
            # vtcs warm-preference: this node advertises the pod's
            # program — landing here starts at warm-node speed with no
            # fetch at all. Soft like pressure/storm (reorders fits,
            # never vetoes one), staleness re-judged inside warm_term
            # so a dead advertiser's claim decays to 0.0 (the
            # byte-identical pre-vtcs score).
            warm_bonus = cc_advertise.warm_term(warm, warm_fp)
            score += warm_bonus
        link_pen = 0.0
        if link_load is not None:
            # vtici: worst-link contention of the chips just chosen —
            # the cross-node leg of the link dimension (the submesh
            # search already avoided hot rings INSIDE the node; this
            # penalty repels the whole selection from nodes whose
            # fabric is busy). Computed from the final effective claim
            # set so every topology kind (rect/greedy/host/any) pays
            # the same honest metric. Soft like pressure/storm:
            # reorders fits, never vetoes one.
            chips = registry.chip_by_uuid()
            cells = {chips[c.uuid].coords
                     for c in alloc_result.effective.all_claims()
                     if c.uuid in chips}
            link_pen = tl_mod.link_term(
                worst_link_load(cells, link_load, registry.mesh))
            score -= link_pen
        headroom_term = 0.0
        mix_term = 0.0
        if hr_term:
            # vtqm (QuotaMarket gate + latency-critical pod): prefer
            # nodes with fresh lendable headroom — the market can
            # absorb this pod's bursts there. Soft like pressure/storm
            # (reorders fits, never vetoes one), and a stale or
            # no-confidence signal contributes exactly 0.0, i.e. the
            # byte-identical pre-market score.
            headroom_term = util_headroom.headroom_score_term(headroom)
            score += headroom_term
            # class-mix-aware packing (ROADMAP quota item (a), the PR
            # 11 observe-only decode made real): a borrower-class pod
            # prefers nodes whose resident mix contains throughput
            # LENDERS — the market only pays off with counterparties.
            # Same staleness rule as the headroom term (the mix rides
            # the same annotation): stale/absent mix = 0.0 = the
            # byte-identical pre-mix score.
            mix_term = util_headroom.class_mix_term(headroom)
            score += mix_term
        if explain_b is not None:
            # the audit record gets the exact terms just applied, plus
            # the raw headroom input — total == base - pressure - storm
            # - spill - link_term + gang_bonus + headroom_term +
            # mix_term + warm_term holds by construction (headroom_term
            # /mix_term are 0.0 unless the QuotaMarket gate scored
            # them, spill 0.0 unless HBMOvercommit did, warm_term 0.0
            # unless ClusterCompileCache did, link_term 0.0 unless
            # ICILinkAware did) and is asserted end-to-end by
            # test_explain/test_quota/test_overcommit/
            # test_clustercache/test_ici; virt_ratio records the
            # virtual/physical admission split
            explain_b.candidate(
                name, base=base, pressure=pressure_pen, storm=storm_pen,
                gang_bonus=gang_bonus,
                headroom_input=util_headroom.headroom_score_input(
                    headroom),
                topology=alloc_result.topology_kind, total=score,
                headroom_term=headroom_term, spill=spill_pen,
                virt_ratio=oc_ratio, warm_term=warm_bonus,
                link_term=link_pen, mix_term=mix_term)
        scored.append(ScoredNode(name, score, alloc_result))

    # -- commit: annotation patch is the only cross-process channel ---------

    def _commit(self, pod: dict, req: AllocationRequest,
                best: ScoredNode, fence=None) -> None:
        meta = pod.get("metadata") or {}
        anns = {
            consts.pre_allocated_annotation(): best.result.claims.encode(),
            consts.predicate_node_annotation(): best.name,
            consts.predicate_time_annotation(): str(time.time()),
        }
        if fence is not None:
            # the fencing token rides the SAME patch as the commitment:
            # every pre-allocation names the leader incarnation that made
            # it (on a spill pass, the OWNER shard's leader — not the
            # shard whose nodes are being committed), and a locally
            # expired lease raises before any write
            anns.update(fence.fence_annotations())
        if req.gang_name:
            origin = gang.chosen_origin(best.result.node_info,
                                        best.result.claims)
            if origin is not None:
                anns[gang.gang_origin_annotation()] = \
                    gang.encode_origin(origin)
        self.policy.run(
            lambda: self.client.patch_pod_annotations(
                meta.get("namespace", "default"), meta.get("name", ""),
                anns),
            op="filter.commit")
        # crash window: the commitment is on the apiserver but not yet in
        # the assumed cache — exactly the state a scheduler crash here
        # leaves, reconciled by stuck-grace + the bind-intent reaper
        failpoints.fire("scheduler.filter_commit",
                        pod_uid=meta.get("uid", ""), node=best.name)
        self._assume(meta.get("uid", ""), best.name, best.result.effective)
        if self.anti_storm:
            fp = antistorm.pod_fingerprint(pod)
            if fp:
                self._record_recent_fp(best.name, meta.get("uid", ""),
                                       fp, time.time())

    # -- vtcc anti-storm: in-process recent-placement overlay ---------------

    def _record_recent_fp(self, node: str, uid: str, fp: str,
                          now: float) -> None:
        with self._assumed_lock:
            entries = [e for e in self._recent_fp.get(node, [])
                       if now - e[2] <= antistorm.STORM_WINDOW_S]
            entries.append((uid, fp, now))
            self._recent_fp[node] = entries

    def _recent_fp_overlay(self, now: float) -> dict[str, list]:
        """One snapshot of live in-process fingerprint commits per pass,
        pruned by window — same pattern as _assumed_by_node."""
        out: dict[str, list] = {}
        with self._assumed_lock:
            for node in list(self._recent_fp):
                live = [e for e in self._recent_fp[node]
                        if now - e[2] <= antistorm.STORM_WINDOW_S]
                if live:
                    self._recent_fp[node] = live
                    out[node] = live
                else:
                    del self._recent_fp[node]
        return out

    def _storm_for_node(self, name: str, fp_overlay: dict,
                        resident_uids, annotation_recent,
                        unbound=()) -> list:
        """Per-node (fingerprint, ts) storm signal: resident pods'
        stamped annotations, committed-but-unbound pods from the
        cluster view (``unbound``: (uid, fp, ts) triples — another
        scheduler's in-flight placements), plus the in-process overlay
        MINUS overlay entries whose pod is now visible among residents
        OR the unbound set — a visible pod contributes through its
        annotation, and keeping its overlay twin would double the
        penalty (same retirement rule as the assumed cache)."""
        overlay = fp_overlay.get(name, [])
        if overlay:
            visible = set(resident_uids)
            visible.update(u for u, _f, _t in unbound)
            retired = [e[0] for e in overlay if e[0] in visible]
            if retired:
                overlay = [e for e in overlay
                           if e[0] not in visible]
                self._drop_recent_fp(name, retired)
        return (list(annotation_recent)
                + [(f, t) for _u, f, t in unbound]
                + [(f, t) for _u, f, t in overlay])

    def _unbound_committed_fp(self, now: float,
                              exclude_uid: str = "") -> dict:
        """vtcc TTL-path follow-up: committed-but-unbound fingerprints
        from the full pod list (TTL-cached like the gang path — one
        cluster LIST per snapshot window, not per candidate), so
        independent non-HA scheduler processes repel each other's
        in-flight placements. Snapshot mode reads the same signal from
        the ClusterSnapshot's incrementally maintained index instead."""
        try:
            pods = self._list_all_pods()
        except KubeError as e:
            # soft signal: a throttled LIST degrades to no storm data
            # for this pass, never to a failed pass
            log.warning("unbound-commitment scan failed (%s); anti-storm "
                        "runs on resident signals only this pass", e)
            return {}
        out = antistorm.unbound_recent_from_pods(pods, now)
        if exclude_uid:
            # a re-filtered committed pod must not repel itself
            for node in list(out):
                kept = [e for e in out[node] if e[0] != exclude_uid]
                if kept:
                    out[node] = kept
                else:
                    del out[node]
        return out

    def _drop_recent_fp(self, node: str, uids) -> None:
        with self._assumed_lock:
            entries = self._recent_fp.get(node)
            if not entries:
                return
            live = [e for e in entries if e[0] not in uids]
            if live:
                self._recent_fp[node] = live
            else:
                self._recent_fp.pop(node, None)

    def _emit_rejection_event(self, pod: dict, message: str) -> None:
        """One aggregated event per rejected pod (reference: reason.go)."""
        meta = pod.get("metadata") or {}
        ns = meta.get("namespace", "default")
        try:
            self.client.create_event(ns, {
                "metadata": {"generateName": "vtpu-filter-"},
                "involvedObject": {"kind": "Pod", "namespace": ns,
                                   "name": meta.get("name", ""),
                                   "uid": meta.get("uid", "")},
                "reason": "FilterFailed",
                "message": message[:1024],
                "type": "Warning",
            })
        except KubeError:
            log.warning("failed to emit rejection event for %s",
                        meta.get("name"))
