"""Bind predicate: verify the pre-allocation and create the Binding.

Reference: pkg/scheduler/bind/bind_predicate.go:54-142 — the extender owns
bind: it re-checks that the node kube-scheduler settled on matches the node
the filter pre-allocated, that the pre-allocation is still fresh, patches the
"allocating" status, then creates the Binding object itself.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

from vtpu_manager import explain, trace
from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.resilience import failpoints, recovery
from vtpu_manager.resilience.policy import RetryPolicy
from vtpu_manager.scheduler.lease import LeaseLostError
from vtpu_manager.scheduler.serial import SerialLocker
from vtpu_manager.util import consts
from vtpu_manager.util import stalecodec

log = logging.getLogger(__name__)


@dataclass
class BindResult:
    error: str = ""

    def to_wire(self) -> dict:
        return {"Error": self.error} if self.error else {}


class BindPredicate:
    def __init__(self, client: KubeClient, locker: SerialLocker | None = None,
                 freshness_s: float = consts.DEFAULT_STUCK_GRACE_S,
                 policy: RetryPolicy | None = None,
                 fence=None):
        self.client = client
        self.locker = locker or SerialLocker(serialize_all=False)
        self.freshness_s = freshness_s
        # vtha (None = pre-HA behavior, byte-identical): the shard's
        # ShardLease. The fencing token rides the allocating/intent
        # patch, and confirm() — a CAS lease renew through the apiserver
        # — runs between that patch and the Binding POST, so a
        # paused-then-resumed ex-leader's stale bind is rejected at
        # commit time (its CAS 409s against the new leader's write).
        self.fence = fence
        # Bind sits on kube-scheduler's binding cycle: keep the retry
        # budget tight (the scheduler re-dispatches a failed bind anyway)
        # but absorb one throttle/transient blip instead of bouncing the
        # pod back through the whole scheduling queue.
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            base_delay_s=0.05,
                                            deadline_s=5.0)

    def bind(self, args: dict) -> BindResult:
        ns = args.get("PodNamespace") or args.get("podNamespace") or "default"
        name = args.get("PodName") or args.get("podName") or ""
        node = args.get("Node") or args.get("node") or ""
        # The serial section exists to order bind's get/patch/bind API
        # sequence against concurrent binds of the same pod (reference
        # SerialBindNode) — holding it across the I/O is the feature.
        with self.locker.section(f"{ns}/{name}"):
            # vtlint: disable=lock-discipline — see above
            result, pod = self._bind_locked(ns, name, node)
        if explain.is_enabled():
            # the bind verdict closes the pod's decision trail (ring
            # append only — the serial section is already released)
            meta = (pod or {}).get("metadata") or {}
            anns = meta.get("annotations") or {}
            explain.bind_outcome(
                ns, name, node, pod_uid=meta.get("uid", ""),
                trace_id=anns.get(consts.trace_id_annotation(), ""),
                error=result.error,
                shard=getattr(self.fence, "shard", "")
                if self.fence is not None else "",
                plan_epoch=getattr(self.fence, "epoch", 0)
                if self.fence is not None else 0)
        return result

    def validate_commitment(self, pod: dict, node: str) -> str:
        """The pre-Binding checks, shared with the vtscale commit
        pipeline (scheduler/bindpipe.py): returns an error string, or ""
        when the pod's pre-allocation matches ``node`` and is fresh."""
        anns = (pod.get("metadata") or {}).get("annotations") or {}
        predicate_node = anns.get(consts.predicate_node_annotation())
        if not predicate_node:
            return "pod has no vtpu pre-allocation"
        if predicate_node != node:
            # kube-scheduler picked a different node than the filter
            # committed to; binding there would detach the claim from its
            # devices (reference :54-142 fails the bind the same way).
            return (f"predicate node {predicate_node!r} != bind "
                    f"target {node!r}")
        ts = consts.parse_predicate_time(anns)
        # is_fresh also rejects a far-future stamp (skewed filter clock):
        # trusting it would honor the commitment forever, and re-filtering
        # is the safe direction
        if ts and not stalecodec.is_fresh(ts, max_age_s=self.freshness_s):
            return "pre-allocation expired; re-filter needed"
        return ""

    def commit_patch(self, pod: dict, node: str) -> dict | None:
        """The allocating+intent+fence patch for this pod, or None when
        the plugin already fulfilled the commitment (never downgrade a
        completed allocation's status back to "allocating"). Shared with
        the pipeline so batched waves patch the exact serial bytes.
        Raises LeaseLostError via fence_annotations when leadership
        cannot be locally proven."""
        anns = (pod.get("metadata") or {}).get("annotations") or {}
        if anns.get(consts.real_allocated_annotation()):
            return None
        patch = {
            consts.allocation_status_annotation():
                consts.ALLOC_STATUS_ALLOCATING,
            consts.bind_intent_annotation():
                recovery.encode_bind_intent(node)}
        if self.fence is not None:
            # the fencing token rides the same patch: the intent trail
            # names the leader incarnation (and, under a shard plan, the
            # plan epoch), so a takeover replay reaps by token, not
            # guesswork
            patch.update(self.fence.fence_annotations())
        return patch

    def _bind_locked(self, ns: str, name: str,
                     node: str) -> tuple[BindResult, dict | None]:
        """(result, fetched pod) — the pod rides back so the caller can
        stamp the explain bind record without a second GET."""
        try:
            pod = self.policy.run(lambda: self.client.get_pod(ns, name),
                                  op="bind.get_pod")
        except KubeError as e:
            return BindResult(error=f"pod fetch failed: {e}"), None
        anns = (pod.get("metadata") or {}).get("annotations") or {}

        invalid = self.validate_commitment(pod, node)
        if invalid:
            return BindResult(error=invalid), pod
        ts = consts.parse_predicate_time(anns)

        # the bind span carries the filter's commit wall time, so the
        # assembled timeline shows filter-commit -> bind queueing (the
        # kube-scheduler round trip) without a span of its own
        ctx = trace.context_for_pod(pod)
        uid = (pod.get("metadata") or {}).get("uid", "")
        with trace.span(ctx, "scheduler.bind", node=node,
                        predicate_time=ts or 0.0):
            try:
                # the plugin may have fulfilled the commitment BEFORE the
                # Binding lands (its pending scan accepts predicate-node
                # pods to bridge watch lag); commit_patch returns None
                # then — just bind. Otherwise the bind-intent rides the
                # SAME patch as the allocating status: it is on the
                # apiserver before the Binding POST, so a crash in the
                # window below leaves a reapable trail
                # (resilience/recovery.py) instead of a wedged pod.
                patch = self.commit_patch(pod, node)
                if patch is not None:
                    self.policy.run(
                        lambda: self.client.patch_pod_annotations(
                            ns, name, patch),
                        op="bind.patch")
                failpoints.fire("scheduler.bind_patch", pod_uid=uid,
                                node=node)
                if self.fence is not None:
                    # commit-time fence: CAS-confirm the lease between
                    # the intent patch and the Binding POST. A paused or
                    # fenced-off ex-leader fails HERE — the Binding never
                    # lands, and the intent just written is exactly the
                    # trail the new leader's takeover replay reaps.
                    self.fence.confirm()
                self.policy.run(
                    lambda: self.client.bind_pod(ns, name, node),
                    op="bind.binding")
            except LeaseLostError as e:
                return BindResult(
                    error=f"bind rejected at commit (lease fence): {e}"), pod
            except KubeError as e:
                return BindResult(error=f"bind failed: {e}"), pod
            return BindResult(), pod
