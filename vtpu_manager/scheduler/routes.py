"""HTTP routing for the scheduler extender.

Reference: pkg/route/routes.go:19-232 — POST /scheduler/filter, /bind,
/preempt (kube-scheduler extender webhooks), plus healthz/readyz/version and
Prometheus metrics. TLS optional. Request/response bodies are the upstream
scheduler-extender JSON types, passed as dicts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

from aiohttp import web

from vtpu_manager.scheduler.bind import BindPredicate
from vtpu_manager.scheduler.filter import FilterPredicate
from vtpu_manager.scheduler.preempt import PreemptPredicate

log = logging.getLogger(__name__)

VERSION = "0.1.0"


class SchedulerAPI:
    def __init__(self, filter_pred: FilterPredicate, bind_pred: BindPredicate,
                 preempt_pred: PreemptPredicate,
                 debug_endpoints: bool = False,
                 snapshot=None, ha=None, pipeline=None,
                 explain_dir: str | None = None,
                 explain_token_file: str | None = None):
        self.filter_pred = filter_pred
        self.bind_pred = bind_pred
        self.preempt_pred = preempt_pred
        self.debug_endpoints = debug_endpoints
        # vtexplain (DecisionExplain gate): when set, GET /explain serves
        # the per-pod decision audit (latest breakdown + the pending-pod
        # doctor verdict, ?shard= cut under vtha). Gate off = no route
        # at all (404), matching the zero-new-surfaces contract.
        # Decisions name pods/namespaces, so the route is bearer-auth
        # gated when a token file is configured (the monitor's /metrics
        # convention — mounted secret, re-read per request).
        self.explain_dir = explain_dir
        self.explain_token_file = explain_token_file
        # SchedulerSnapshot gate: exported on /metrics when present
        self.snapshot = snapshot
        # SchedulerHA gate: the ShardedScheduler (the three predicates
        # above are then its routing facade); /metrics grows the
        # per-shard leader/token/handoff block and each shard's snapshot
        self.ha = ha
        # ScalePipeline gate, non-HA branch: the BindCommitPipeline
        # fronting bind_pred (bind_pred IS the pipeline then); /metrics
        # grows its wave counters. Under vtha the pipelines are
        # per-shard and render through render_ha_metrics instead.
        self.pipeline = pipeline
        self.stats = {"filter": 0, "bind": 0, "preempt": 0, "errors": 0}
        self._started = time.time()

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 2**20)
        app.router.add_post("/scheduler/filter", self.handle_filter)
        app.router.add_post("/scheduler/bind", self.handle_bind)
        app.router.add_post("/scheduler/preempt", self.handle_preempt)
        app.router.add_get("/healthz", self.handle_healthz)
        app.router.add_get("/readyz", self.handle_healthz)
        app.router.add_get("/version", self.handle_version)
        app.router.add_get("/metrics", self.handle_metrics)
        if self.explain_dir:
            app.router.add_get("/explain", self.handle_explain)
        if self.debug_endpoints:
            # stack traces disclose internals; opt-in only
            from vtpu_manager.util.debug import aiohttp_stacks_handler
            app.router.add_get("/debug/stacks", aiohttp_stacks_handler)
        return app

    async def _body(self, request: web.Request) -> dict:
        raw = await request.read()
        if log.isEnabledFor(logging.DEBUG):
            log.debug("%s body: %s", request.path, raw[:4096])
        return json.loads(raw)

    async def handle_filter(self, request: web.Request) -> web.Response:
        self.stats["filter"] += 1
        try:
            args = await self._body(request)
            result = await asyncio.get_running_loop().run_in_executor(
                None, self.filter_pred.filter, args)
            return web.json_response(result.to_wire())
        except Exception as e:   # extender contract: report via Error field
            self.stats["errors"] += 1
            log.exception("filter failed")
            return web.json_response({"Error": str(e)})

    async def handle_bind(self, request: web.Request) -> web.Response:
        self.stats["bind"] += 1
        try:
            args = await self._body(request)
            result = await asyncio.get_running_loop().run_in_executor(
                None, self.bind_pred.bind, args)
            return web.json_response(result.to_wire())
        except Exception as e:
            self.stats["errors"] += 1
            log.exception("bind failed")
            return web.json_response({"Error": str(e)})

    async def handle_preempt(self, request: web.Request) -> web.Response:
        self.stats["preempt"] += 1
        try:
            args = await self._body(request)
            result = await asyncio.get_running_loop().run_in_executor(
                None, self.preempt_pred.preempt, args)
            return web.json_response(result.to_wire())
        except Exception as e:
            self.stats["errors"] += 1
            log.exception("preempt failed")
            return web.json_response({"Error": str(e)})

    async def handle_healthz(self, request: web.Request) -> web.Response:
        return web.Response(text="ok")

    def _explain_authorized(self, request: web.Request) -> bool:
        if not self.explain_token_file:
            return True
        import hmac
        try:
            # re-read per request: kubernetes rotates mounted secrets in
            # place (the monitor's /metrics auth convention)
            with open(self.explain_token_file) as f:
                token = f.read().strip()
        except OSError:
            return False
        if not token:
            return False
        return hmac.compare_digest(
            request.headers.get("Authorization", ""), f"Bearer {token}")

    async def handle_explain(self, request: web.Request) -> web.Response:
        """Per-pod decision audit: the latest breakdown + the doctor
        verdict (?pod= by uid / trace id / name; ?shard= cuts the trail
        to one vtha shard; no ?pod= lists audited pods). The spool read
        runs in an executor thread — a slow disk (or an injected
        explain.rollup fault) stalls only this route, never the event
        loop serving filter/bind/preempt."""
        if not self._explain_authorized(request):
            return web.json_response({"error": "unauthorized"}, status=401)
        from vtpu_manager import explain as explain_mod
        from vtpu_manager.explain import doctor
        pod = request.query.get("pod", "")
        shard = request.query.get("shard", "")

        def collect():
            # flush the in-process ring first so the route serves the
            # pass that JUST committed, not the one before the flusher's
            # last tick (this is the recorder's own process)
            explain_mod.flush()
            return doctor.explain_document(self.explain_dir,
                                           pod_key=pod, shard=shard)
        try:
            status, doc = await asyncio.get_running_loop() \
                .run_in_executor(None, collect)
        except Exception as e:  # noqa: BLE001 — a wedged audit plane
            # serves an explicit error, never a hang or a half-truth
            log.warning("explain rollup failed: %s", e)
            return web.json_response(
                {"error": f"explain rollup failed: {e}"}, status=503)
        return web.json_response(doc, status=status)

    async def handle_version(self, request: web.Request) -> web.Response:
        return web.json_response({"version": VERSION,
                                  "uptime_s": time.time() - self._started})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        lines = ["# TYPE vtpu_scheduler_requests_total counter"]
        for k, v in self.stats.items():
            lines.append(
                f'vtpu_scheduler_requests_total{{endpoint="{k}"}} {v}')
        breakers = []
        if self.pipeline is not None:
            from vtpu_manager.scheduler.bindpipe import \
                render_pipeline_metrics
            block = render_pipeline_metrics([self.pipeline])
            if block:
                lines.append(block.rstrip("\n"))
        if self.ha is not None:
            # vtha: per-shard leadership, fencing tokens, handoffs, reaps
            lines.append(self.ha.render_ha_metrics())
            for unit in self.ha.units:
                if unit.snapshot is not None:
                    breakers.extend(unit.snapshot.breakers())
        if self.snapshot is not None:
            # watch-driven snapshot health: how much change is flowing,
            # how often the watch window was lost (relists), how much
            # decode the O(changed) contract actually paid, and how stale
            # the state a filter pass reads can be
            lines.append(
                "# TYPE vtpu_scheduler_snapshot_events_total counter")
            for name, value in self.snapshot.stats.as_dict().items():
                lines.append(
                    f'vtpu_scheduler_snapshot_events_total'
                    f'{{kind="{name}"}} {value}')
            lines.append(
                "# TYPE vtpu_scheduler_snapshot_staleness_seconds gauge")
            lines.append(f"vtpu_scheduler_snapshot_staleness_seconds "
                         f"{self.snapshot.staleness_s():.6f}")
            lines.append("# TYPE vtpu_scheduler_snapshot_generation gauge")
            lines.append(f"vtpu_scheduler_snapshot_generation "
                         f"{self.snapshot.generation}")
            # LIST/watch verb-family breakers (vtfault follow-up):
            # vtpu_circuit_state tells an operator the snapshot stopped
            # even TRYING to reach the apiserver, breaker_open in the
            # events block counts the rejected pumps
            breakers.extend(self.snapshot.breakers())
        # vtuse observe-only tap (UtilizationLedger gate; the block is
        # emitted only when some filter path is armed, so the gate-off
        # scrape stays byte-identical): how many committed passes saw a
        # live reclaimable-headroom signal on the chosen node — the
        # coverage denominator for the quota-market PR's evidence
        preds = [self.filter_pred]
        if self.ha is not None:
            preds = [u.filter_pred for u in self.ha.units]
        armed = [p for p in preds
                 if getattr(p, "utilization_hint", False)]
        if armed:
            lines.append(
                "# TYPE vtpu_scheduler_headroom_observed_total counter")
            lines.append(
                f"vtpu_scheduler_headroom_observed_total "
                f"{sum(p.headroom_observed for p in armed)}")
        # vtfrag per-candidate rollups (FragObservatory gate; "" when
        # off — the stash is never populated — so the gate-off scrape
        # stays byte-identical): the shared _allocate_node tap's last
        # NodeFrag per visited node, stale entries dropped at render
        frag_by_node: dict = {}
        for p in preds:
            frag_by_node.update(getattr(p, "frag_last", None) or {})
        if frag_by_node:
            from vtpu_manager.fragmentation import metrics as frag_metrics
            frag_block = frag_metrics.render_sched_frag(frag_by_node)
            if frag_block:
                lines.append(frag_block.rstrip("\n"))
        # vtexplain counters (DecisionExplain gate; "" when off so the
        # gate-off scrape stays byte-identical): audited passes,
        # per-reason rejection tallies, and ring drops — the drop
        # counter is the "records lost, not silent" contract
        from vtpu_manager import explain as explain_mod
        explain_block = explain_mod.render_metrics()
        if explain_block:
            lines.append(explain_block.rstrip("\n"))
        # retry/breaker counters + failpoint fires (vtfault): how often
        # this process leaned on the resilience layer, and what the
        # FaultInjection gate injected (zero in production)
        from vtpu_manager.resilience.policy import render_resilience_metrics
        lines.append(render_resilience_metrics(breakers or None))
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")


def run_server(api: SchedulerAPI, host: str = "0.0.0.0", port: int = 8768,
               ssl_context=None) -> None:
    web.run_app(api.build_app(), host=host, port=port,
                ssl_context=ssl_context, print=None)
