"""HTTP routing for the scheduler extender.

Reference: pkg/route/routes.go:19-232 — POST /scheduler/filter, /bind,
/preempt (kube-scheduler extender webhooks), plus healthz/readyz/version and
Prometheus metrics. TLS optional. Request/response bodies are the upstream
scheduler-extender JSON types, passed as dicts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

from aiohttp import web

from vtpu_manager.scheduler.bind import BindPredicate
from vtpu_manager.scheduler.filter import FilterPredicate
from vtpu_manager.scheduler.preempt import PreemptPredicate

log = logging.getLogger(__name__)

VERSION = "0.1.0"


class SchedulerAPI:
    def __init__(self, filter_pred: FilterPredicate, bind_pred: BindPredicate,
                 preempt_pred: PreemptPredicate,
                 debug_endpoints: bool = False,
                 snapshot=None, ha=None):
        self.filter_pred = filter_pred
        self.bind_pred = bind_pred
        self.preempt_pred = preempt_pred
        self.debug_endpoints = debug_endpoints
        # SchedulerSnapshot gate: exported on /metrics when present
        self.snapshot = snapshot
        # SchedulerHA gate: the ShardedScheduler (the three predicates
        # above are then its routing facade); /metrics grows the
        # per-shard leader/token/handoff block and each shard's snapshot
        self.ha = ha
        self.stats = {"filter": 0, "bind": 0, "preempt": 0, "errors": 0}
        self._started = time.time()

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 2**20)
        app.router.add_post("/scheduler/filter", self.handle_filter)
        app.router.add_post("/scheduler/bind", self.handle_bind)
        app.router.add_post("/scheduler/preempt", self.handle_preempt)
        app.router.add_get("/healthz", self.handle_healthz)
        app.router.add_get("/readyz", self.handle_healthz)
        app.router.add_get("/version", self.handle_version)
        app.router.add_get("/metrics", self.handle_metrics)
        if self.debug_endpoints:
            # stack traces disclose internals; opt-in only
            from vtpu_manager.util.debug import aiohttp_stacks_handler
            app.router.add_get("/debug/stacks", aiohttp_stacks_handler)
        return app

    async def _body(self, request: web.Request) -> dict:
        raw = await request.read()
        if log.isEnabledFor(logging.DEBUG):
            log.debug("%s body: %s", request.path, raw[:4096])
        return json.loads(raw)

    async def handle_filter(self, request: web.Request) -> web.Response:
        self.stats["filter"] += 1
        try:
            args = await self._body(request)
            result = await asyncio.get_running_loop().run_in_executor(
                None, self.filter_pred.filter, args)
            return web.json_response(result.to_wire())
        except Exception as e:   # extender contract: report via Error field
            self.stats["errors"] += 1
            log.exception("filter failed")
            return web.json_response({"Error": str(e)})

    async def handle_bind(self, request: web.Request) -> web.Response:
        self.stats["bind"] += 1
        try:
            args = await self._body(request)
            result = await asyncio.get_running_loop().run_in_executor(
                None, self.bind_pred.bind, args)
            return web.json_response(result.to_wire())
        except Exception as e:
            self.stats["errors"] += 1
            log.exception("bind failed")
            return web.json_response({"Error": str(e)})

    async def handle_preempt(self, request: web.Request) -> web.Response:
        self.stats["preempt"] += 1
        try:
            args = await self._body(request)
            result = await asyncio.get_running_loop().run_in_executor(
                None, self.preempt_pred.preempt, args)
            return web.json_response(result.to_wire())
        except Exception as e:
            self.stats["errors"] += 1
            log.exception("preempt failed")
            return web.json_response({"Error": str(e)})

    async def handle_healthz(self, request: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def handle_version(self, request: web.Request) -> web.Response:
        return web.json_response({"version": VERSION,
                                  "uptime_s": time.time() - self._started})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        lines = ["# TYPE vtpu_scheduler_requests_total counter"]
        for k, v in self.stats.items():
            lines.append(
                f'vtpu_scheduler_requests_total{{endpoint="{k}"}} {v}')
        breakers = []
        if self.ha is not None:
            # vtha: per-shard leadership, fencing tokens, handoffs, reaps
            lines.append(self.ha.render_ha_metrics())
            for unit in self.ha.units:
                if unit.snapshot is not None:
                    breakers.extend(unit.snapshot.breakers())
        if self.snapshot is not None:
            # watch-driven snapshot health: how much change is flowing,
            # how often the watch window was lost (relists), how much
            # decode the O(changed) contract actually paid, and how stale
            # the state a filter pass reads can be
            lines.append(
                "# TYPE vtpu_scheduler_snapshot_events_total counter")
            for name, value in self.snapshot.stats.as_dict().items():
                lines.append(
                    f'vtpu_scheduler_snapshot_events_total'
                    f'{{kind="{name}"}} {value}')
            lines.append(
                "# TYPE vtpu_scheduler_snapshot_staleness_seconds gauge")
            lines.append(f"vtpu_scheduler_snapshot_staleness_seconds "
                         f"{self.snapshot.staleness_s():.6f}")
            lines.append("# TYPE vtpu_scheduler_snapshot_generation gauge")
            lines.append(f"vtpu_scheduler_snapshot_generation "
                         f"{self.snapshot.generation}")
            # LIST/watch verb-family breakers (vtfault follow-up):
            # vtpu_circuit_state tells an operator the snapshot stopped
            # even TRYING to reach the apiserver, breaker_open in the
            # events block counts the rejected pumps
            breakers.extend(self.snapshot.breakers())
        # vtuse observe-only tap (UtilizationLedger gate; the block is
        # emitted only when some filter path is armed, so the gate-off
        # scrape stays byte-identical): how many committed passes saw a
        # live reclaimable-headroom signal on the chosen node — the
        # coverage denominator for the quota-market PR's evidence
        preds = [self.filter_pred]
        if self.ha is not None:
            preds = [u.filter_pred for u in self.ha.units]
        armed = [p for p in preds
                 if getattr(p, "utilization_hint", False)]
        if armed:
            lines.append(
                "# TYPE vtpu_scheduler_headroom_observed_total counter")
            lines.append(
                f"vtpu_scheduler_headroom_observed_total "
                f"{sum(p.headroom_observed for p in armed)}")
        # retry/breaker counters + failpoint fires (vtfault): how often
        # this process leaned on the resilience layer, and what the
        # FaultInjection gate injected (zero in production)
        from vtpu_manager.resilience.policy import render_resilience_metrics
        lines.append(render_resilience_metrics(breakers or None))
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")


def run_server(api: SchedulerAPI, host: str = "0.0.0.0", port: int = 8768,
               ssl_context=None) -> None:
    web.run_app(api.build_app(), host=host, port=port,
                ssl_context=ssl_context, print=None)
