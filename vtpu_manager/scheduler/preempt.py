"""Preempt predicate: re-validate kube-scheduler's victim sets.

Reference: pkg/scheduler/preempt/preempt_predicate.go:1-747 — the in-tree
preemption logic picks victims by pod priority without understanding vtpu
device occupancy, so the extender corrects it: victims whose eviction frees
no needed vtpu capacity are dropped, extra vtpu victims are added when the
proposed set is not enough, and nodes where no victim set makes the pod fit
are removed entirely.

PDB handling mirrors the reference two ways:
- candidates ADDED by us skip pods that match a PodDisruptionBudget with
  zero disruptions allowed (violationOfPDBs, preempt_predicate.go:595-620);
- the response's NumPDBViolations is computed EXACTLY over the final victim
  set by budget-decrementing PDB matching (same derivation as the reference,
  preempt_predicate.go:466-496: walk victims consuming each matched PDB's
  remaining disruptionsAllowed; a victim whose eviction would exceed some
  matched PDB's remaining budget is a violator). Only when a PDB lister
  fails does the node fall back to the conservative upper bound
  (min(original, kept-from-input) + added) — erring high there is right:
  under-reporting would make kube-scheduler's pickOneNodeForPreemption
  prefer our node and inflict more real disruption than it should.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

from vtpu_manager import explain, trace
from vtpu_manager.client.kube import KubeClient
from vtpu_manager.device.allocator.allocator import (AllocationFailure,
                                                     allocate)
from vtpu_manager.device.allocator.request import (RequestError,
                                                   build_allocation_request)
from vtpu_manager.device.types import NodeInfo, get_pod_device_claims
from vtpu_manager.quota import victimcost as vc_mod
from vtpu_manager.telemetry import pressure as tel_pressure
from vtpu_manager.util import consts
from vtpu_manager.utilization import headroom as hr_mod

log = logging.getLogger(__name__)

# identical gang-disruption warnings for one pending preemptor are
# suppressed within this window (scheduler retry cadence is seconds)
_GANG_WARN_WINDOW_S = 300.0


@dataclass
class NodeVictims:
    pods: list[dict] = field(default_factory=list)
    num_pdb_violations: int = 0


@dataclass
class PreemptResult:
    node_to_victims: dict[str, NodeVictims] = field(default_factory=dict)
    error: str = ""

    def to_wire(self) -> dict:
        if self.error:
            return {"Error": self.error}
        return {"NodeNameToMetaVictims": {
            node: {"Pods": [{"UID": (p.get("metadata") or {}).get("uid", "")}
                            for p in v.pods],
                   "NumPDBViolations": v.num_pdb_violations}
            for node, v in self.node_to_victims.items()}}


def _pod_priority(pod: dict) -> int:
    return (pod.get("spec") or {}).get("priority", 0)


def _pod_uid(pod: dict) -> str:
    return (pod.get("metadata") or {}).get("uid", "")


def pdb_violations_upper_bound(original: int, kept_from_input: int,
                               added: int) -> int:
    """Conservative violator count without per-victim PDB matching; always
    <= kept_from_input + added so NumPDBViolations <= len(Pods) holds."""
    return min(original, kept_from_input) + added


def _label_selector_matches(selector: dict | None, labels: dict) -> bool:
    if not selector:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key, op = expr.get("key", ""), expr.get("operator", "")
        values = expr.get("values") or []
        if op == "In" and labels.get(key) not in values:
            return False
        if op == "NotIn" and labels.get(key) in values:
            return False
        if op == "Exists" and key not in labels:
            return False
        if op == "DoesNotExist" and key in labels:
            return False
    return True


class PreemptPredicate:
    def __init__(self, client: KubeClient, snapshot=None,
                 victim_order_hint: bool = False):
        self.client = client
        # SchedulerSnapshot gate: node objects and resident pods come
        # from the watch-driven snapshot instead of per-node GET/LIST
        # round-trips (the validate loop was 2 API calls per candidate
        # node); None = legacy client path.
        self._snapshot = snapshot
        # vtexplain satellite (DecisionExplain gate; default off =
        # victim choice byte-identical to the pre-explain tree): among
        # otherwise-equal extra victims, prefer LOW-utilization /
        # HIGH-burstiness tenants — idle quota is cheap to evict, and a
        # spiky tenant's quota is exactly what the headroom accounting
        # refuses to call reclaimable, so eviction is the only way to
        # free it. Inputs come from the vtuse reclaimable-headroom
        # annotation (per-chip used/alloc, apportioned to the victim by
        # quota share) and the vttel node pressure; the ordering applied
        # and every per-victim input land in the preempt decision record
        # so the choice is auditable. Priority stays the PRIMARY key —
        # the hint only orders within a priority class, and a stale/
        # absent headroom rollup degrades to the old priority-only sort.
        self.victim_order_hint = victim_order_hint
        # (preemptor uid, individual group) -> monotonic time of last
        # warning (per-group, NOT per-victim-set: retry loops vary the
        # set per cycle — ADVICE r4)
        self._gang_warned: dict[tuple, float] = {}

    def preempt(self, args: dict) -> PreemptResult:
        pod = args.get("Pod") or args.get("pod") or {}
        if self._snapshot is not None:
            self._snapshot.ensure_fresh()
        with trace.span(trace.context_for_pod(pod), "scheduler.preempt"):
            return self._preempt(args, pod)

    def _preempt(self, args: dict, pod: dict) -> PreemptResult:
        # kube-scheduler sends NodeNameToVictims (full pods) when
        # nodeCacheCapable=false and NodeNameToMetaVictims (UIDs only) when
        # true; accept both, in Go-field or JSON-tag casing.
        victims_in = (args.get("NodeNameToVictims")
                      or args.get("nodeNameToVictims"))
        meta_only = False
        if victims_in is None:
            victims_in = (args.get("NodeNameToMetaVictims")
                          or args.get("nodeNameToMetaVictims") or {})
            meta_only = True
        try:
            req = build_allocation_request(pod)
        except RequestError as e:
            return PreemptResult(error=f"invalid vtpu request: {e}")
        if req.is_empty():
            # nothing for us to correct; pass the proposal through. Clamp
            # the carried count: unresolvable MetaVictim UIDs (victim
            # deleted in flight) shrink Pods, and NumPDBViolations must
            # never exceed it.
            out: dict[str, NodeVictims] = {}
            for node, v in victims_in.items():
                pods = self._proposal_pods(node, v, meta_only)
                out[node] = NodeVictims(
                    pods=pods,
                    num_pdb_violations=min(self._proposal_pdb_count(v),
                                           len(pods)))
            return PreemptResult(node_to_victims=out)

        result = PreemptResult()
        # one list per namespace; None = lister failed for that namespace
        pdb_cache: dict[str, list[dict] | None] = {}
        victim_pods: list[dict] = []
        # vtexplain: per-node victim reasoning collected only when the
        # gate armed the recorder (None = zero extra work)
        victim_logs: dict[str, dict] | None = \
            {} if explain.is_enabled() else None
        for node_name, proposal in victims_in.items():
            vlog: dict | None = {} if victim_logs is not None else None
            proposed = self._proposal_pods(node_name, proposal, meta_only)
            kept = self._validate_node(
                node_name, req, proposed,
                original_pdb=self._proposal_pdb_count(proposal),
                pdb_cache=pdb_cache, victim_log=vlog)
            if victim_logs is not None and vlog:
                victim_logs[node_name] = vlog
            if kept is not None:
                result.node_to_victims[node_name] = kept
                victim_pods += kept.pods
        if not result.node_to_victims:
            result.error = "no node becomes schedulable by preemption"
        else:
            self._warn_disrupted_gangs(pod, victim_pods)
        if victim_logs is not None:
            meta = pod.get("metadata") or {}
            anns = meta.get("annotations") or {}
            explain.record_raw({
                "kind": "preempt",
                "pod": meta.get("uid", ""),
                "trace": anns.get(consts.trace_id_annotation(), ""),
                "ns": meta.get("namespace", "default"),
                "name": meta.get("name", ""),
                "ts": time.time(),
                "nodes": victim_logs,
                "error": result.error,
            })
        return result

    def _warn_disrupted_gangs(self, preemptor: dict,
                              victims: list[dict]) -> None:
        """One Warning event when candidate victims belong to gangs:
        evicting a member strands its siblings' aligned placement and
        likely triggers whole-group rescheduling — operators need the
        signal (reference preempt_predicate.go EventGangDisrupted).
        Phrased as CANDIDATES: kube-scheduler picks one of the passing
        nodes afterwards, so gangs on the non-chosen nodes are never
        actually touched. Best-effort and deduped per (preemptor,
        INDIVIDUAL group) for a window (ADVICE r4: retry loops vary the
        candidate victim set per cycle, so a set-keyed dedup treated
        every distinct set as new and fired again inside the window) —
        scheduler retry loops must not flood etcd."""
        from vtpu_manager.util.gangname import resolve_gang_name
        disrupted = sorted({
            f"{(v.get('metadata') or {}).get('namespace', 'default')}"
            f"/{name}"
            for v in victims
            for name, _ in (resolve_gang_name(v),) if name})
        if not disrupted:
            return
        meta = preemptor.get("metadata") or {}
        uid = meta.get("uid", "")
        now = time.monotonic()
        fresh = [g for g in disrupted
                 if now - self._gang_warned.get(
                     (uid, g), -_GANG_WARN_WINDOW_S) >= _GANG_WARN_WINDOW_S]
        if not fresh:
            return
        # prune expired entries: the predicate lives for the scheduler
        # process lifetime and preemptor uids churn — the dedup map must
        # not grow monotonically
        self._gang_warned = {
            k: t for k, t in self._gang_warned.items()
            if now - t < _GANG_WARN_WINDOW_S}
        for group in fresh:
            self._gang_warned[(uid, group)] = now
        ns = meta.get("namespace", "default")
        try:
            self.client.create_event(ns, {
                "metadata": {"generateName": "vtpu-preempt-"},
                # uid included so the event binds to THIS pod object,
                # not a later pod reusing the name (ADVICE r4)
                "involvedObject": {"kind": "Pod", "namespace": ns,
                                   "name": meta.get("name", ""),
                                   **({"uid": uid} if uid else {})},
                "reason": "VtpuGangDisrupted",
                "message": ("preemption candidate victims include "
                            "members of pod group(s) "
                            + ", ".join(fresh)
                            + "; evicting them strands their siblings' "
                              "aligned placement")[:1024],
                "type": "Warning",
            })
        except Exception:          # noqa: BLE001 — best-effort signal:
            # a failed event POST (HTTP, socket, TLS) must never abort a
            # preemption cycle whose victim set already validated
            log.warning("gang-disruption event POST failed",
                        exc_info=True)

    @staticmethod
    def _proposal_pdb_count(proposal: dict | None) -> int:
        p = proposal or {}
        return int(p.get("NumPDBViolations")
                   or p.get("numPDBViolations") or 0)

    def _proposal_pods(self, node_name: str, proposal: dict | None,
                       meta_only: bool) -> list[dict]:
        """Resolve a victim proposal to pod dicts. MetaVictims carry only
        UIDs; resolve them against the node's resident pods."""
        pods = list((proposal or {}).get("Pods")
                    or (proposal or {}).get("pods") or [])
        if not meta_only:
            return pods
        uids = {(p.get("UID") or p.get("uid") or "") for p in pods}
        resident = self._resident_pods(node_name)
        return [p for p in resident if _pod_uid(p) in uids]

    def _resident_pods(self, node_name: str) -> list[dict]:
        if self._snapshot is not None:
            entry = self._snapshot.entry(node_name)
            return list(entry.resident.values()) if entry else []
        return self.client.list_pods(node_name=node_name)

    def _pdbs_for_ns(self, ns: str,
                     cache: dict[str, list[dict] | None]
                     ) -> list[dict] | None:
        """One PDB list per namespace per preempt() call — a per-candidate
        fetch would be N+1 API requests in the scheduling hot path. None
        (cached) on lister failure so callers can distinguish "no PDBs"
        from "unknown"."""
        if ns not in cache:
            try:
                cache[ns] = self.client.list_pdbs(namespace=ns)
            except Exception as e:
                # An RBAC gap would otherwise silently disable PDB
                # protection — say so, and let the caller pick its
                # failure posture (skip-add keeps adding; the violation
                # count falls back to the upper bound).
                log.warning("PDB list failed for namespace %s: %s", ns, e)
                cache[ns] = None
        return cache[ns]

    @staticmethod
    def _matching_pdbs(pod: dict, pdbs: list[dict]) -> list[dict]:
        """Live PDBs whose selector matches the pod and which have not
        already recorded it as disrupted."""
        meta = pod.get("metadata") or {}
        labels = meta.get("labels") or {}
        out = []
        for pdb in pdbs:
            if (pdb.get("metadata") or {}).get("deletionTimestamp"):
                continue
            status = pdb.get("status") or {}
            if meta.get("name") in (status.get("disruptedPods") or {}):
                continue   # already counted as disrupted
            spec = pdb.get("spec") or {}
            if _label_selector_matches(spec.get("selector"), labels):
                out.append(pdb)
        return out

    def _violates_pdb(self, pod: dict,
                      pdb_cache: dict[str, list[dict] | None]) -> bool:
        """True when the pod matches a live PDB in its own namespace with
        no disruptions left. Lister failure reads as no violation (the
        reference's posture for candidate adding; warned in _pdbs_for_ns)."""
        ns = (pod.get("metadata") or {}).get("namespace") or "default"
        pdbs = self._pdbs_for_ns(ns, pdb_cache)
        for pdb in self._matching_pdbs(pod, pdbs or []):
            status = pdb.get("status") or {}
            if int(status.get("disruptionsAllowed", 0)) <= 0:
                return True
        return False

    def _count_pdb_violations(self, pods: list[dict],
                              pdb_cache: dict[str, list[dict] | None]
                              ) -> int | None:
        """Exact violator count over a victim set (reference
        preempt_predicate.go:466-496): evicting the whole set consumes
        each matched PDB's disruptionsAllowed one victim at a time; a
        victim that would push some matched PDB past its remaining budget
        is a violator. None when any victim's namespace lister failed —
        the caller then uses the conservative upper bound."""
        budget: dict[tuple[str, str], int] = {}
        count = 0
        for pod in pods:
            ns = (pod.get("metadata") or {}).get("namespace") or "default"
            pdbs = self._pdbs_for_ns(ns, pdb_cache)
            if pdbs is None:
                return None
            violates = False
            matched: list[tuple[str, str]] = []
            for pdb in self._matching_pdbs(pod, pdbs):
                key = (ns, (pdb.get("metadata") or {}).get("name", ""))
                if key not in budget:
                    budget[key] = int((pdb.get("status") or {})
                                      .get("disruptionsAllowed", 0))
                if budget[key] <= 0:
                    violates = True
                matched.append(key)
            for key in matched:
                budget[key] -= 1
            if violates:
                count += 1
        return count

    def _node_signals(self, node_name: str, node: dict):
        """(NodeHeadroom | None, NodePressure | None,
        NodeVictimCosts | None) for one candidate node — snapshot
        entries carry all three pre-decoded; the TTL path parses the
        annotations of the node object it already fetched. Called only
        when the victim hint or explain recording is armed, so the
        gate-off preempt pass does zero extra work."""
        if self._snapshot is not None:
            entry = self._snapshot.entry(node_name)
            if entry is None:
                return None, None, None
            return entry.headroom, entry.pressure, entry.victim_costs
        anns = (node.get("metadata") or {}).get("annotations") or {}
        return (hr_mod.parse_headroom(
                    anns.get(consts.node_reclaimable_headroom_annotation())),
                tel_pressure.parse_pressure(
                    anns.get(consts.node_pressure_annotation())),
                vc_mod.parse_victim_costs(
                    anns.get(consts.node_victim_cost_annotation())))

    @staticmethod
    def _victim_inputs(pod: dict, headroom, victim_costs=None) -> dict:
        """The per-victim ordering inputs, recorded verbatim in the
        preempt decision record. Estimated utilization = the chip's
        measured used % apportioned to this victim by its quota share
        of the chip's allocation (the vtuse ledger's own fallback
        apportioning rule); burstiness = the chip's headroom discount
        (alloc - used - reclaimable), the part of the idle quota the
        ledger refused to call reclaimable, likewise apportioned.
        ``leased``/``spilled_frac`` come from the node's victim-cost
        rollup (quota/victimcost.py): an active borrow lease and a
        host-resident working set each make eviction cheaper, and both
        land in the record so the ordering is auditable against its
        own inputs (None = no published row for this tenant)."""
        meta = pod.get("metadata") or {}
        claims = get_pod_device_claims(pod)
        row: dict = {"uid": meta.get("uid", ""),
                     "name": meta.get("name", ""),
                     "priority": _pod_priority(pod),
                     "est_used_core_pct": None,
                     "burst_core_pct": None}
        if victim_costs is not None:
            cost = victim_costs.lookup(row["uid"])
            row["leased"] = cost[0] if cost is not None else None
            row["spilled_frac"] = round(cost[1], 3) \
                if cost is not None else None
        if claims is None:
            return row
        alloc = 0.0
        used = burst = 0.0
        matched = 0
        for claim in claims.all_claims():
            alloc += claim.cores
            ch = (headroom.chips.get(claim.host_index)
                  if headroom is not None else None)
            if ch is None or ch.alloc_core_pct <= 0:
                continue
            matched += 1
            share = claim.cores / ch.alloc_core_pct
            used += ch.used_core_pct * share
            burst += max(0.0, ch.alloc_core_pct - ch.used_core_pct
                         - ch.reclaim_core_pct) * share
        row["alloc_core_pct"] = alloc
        if matched:
            row["est_used_core_pct"] = round(used, 2)
            row["burst_core_pct"] = round(burst, 2)
        return row

    def _victim_order_key(self, pod: dict, headroom,
                          victim_costs=None) -> tuple:
        """Extra-victim ordering under the hint: priority first (the
        unchanged primary), then the victim-cost refinements —
        lease-holders before base allocations (a revocable/expiring
        quota lease is a strictly cheaper victim: its capacity was
        leaving anyway), mostly-spilled tenants before HBM-resident
        ones (their locality is already forfeit) — then measured-idle
        tenants before busy ones, spikier before smoother among
        equals, uid for determinism. Victims without a chip-level
        signal sort after measured ones in their priority class —
        "prefer low-utilization" requires evidence of low utilization.
        With no fresh victim-cost rollup the lease/spill keys are
        (1, -0.0) for every victim, i.e. the byte-identical pre-vtcs
        ordering; freshness is the CALLER's judgement (_validate_node
        passes None for a stale rollup)."""
        row = self._victim_inputs(pod, headroom, victim_costs)
        est = row["est_used_core_pct"]
        burst = row["burst_core_pct"]
        leased = row.get("leased") or False
        spilled = row.get("spilled_frac") or 0.0
        return (row["priority"],
                0 if leased else 1,
                -spilled,
                est if est is not None else float("inf"),
                -(burst if burst is not None else 0.0),
                row["uid"])

    def _validate_node(self, node_name: str, req, proposed: list[dict],
                       original_pdb: int = 0,
                       pdb_cache: dict[str, list[dict] | None] | None = None,
                       victim_log: dict | None = None
                       ) -> NodeVictims | None:
        if pdb_cache is None:
            pdb_cache = {}
        if self._snapshot is not None:
            entry = self._snapshot.entry(node_name)
            if entry is None:
                log.warning("preempt: node %s not in the cluster "
                            "snapshot, dropping it from the victim map",
                            node_name)
                return None
            node = entry.node
        else:
            try:
                node = self.client.get_node(node_name)
            except Exception as e:
                # dropping the node from the victim map is correct (it
                # cannot be validated), but a systematic lookup failure
                # (RBAC, apiserver outage) must be visible, not read as
                # "no fit"
                log.warning("preempt: node %s lookup failed, dropping it "
                            "from the victim map: %s", node_name, e)
                return None
        resident = self._resident_pods(node_name)
        # victim-ordering inputs: fetched only when the hint or the
        # audit record needs them (gate off = zero extra work), and the
        # cached headroom's freshness is re-judged at use time — a dead
        # publisher degrades the ordering to priority-only, never to an
        # ordering justified by stale utilization claims
        headroom = pressure = victim_costs = None
        if self.victim_order_hint or victim_log is not None:
            headroom, pressure, victim_costs = \
                self._node_signals(node_name, node)
        hr_fresh = hr_mod.headroom_is_fresh(headroom)
        # the victim-cost rollup (lease state + spill residency) is a
        # second, independent ordering input: stale/absent degrades it
        # to None HERE so every downstream key reads the byte-identical
        # neutral values — never an eviction justified by a dead
        # publisher's claims
        vc_fresh = vc_mod.victim_costs_fresh(victim_costs)
        if not vc_fresh:
            victim_costs = None
        ordering = ("utilization"
                    if self.victim_order_hint and (hr_fresh or vc_fresh)
                    else "priority")
        # a fresh victim-cost rollup alone may engage the utilization
        # ordering — the stale headroom object still feeds the audit
        # rows below (flagged headroom_fresh=False) but must never feed
        # the SORT keys, or a dead publisher's est-used claims decide
        # who gets evicted
        order_headroom = headroom if hr_fresh else None
        added_uids: list[str] = []
        spared: list[dict] = []

        def fits(victim_uids: set[str]) -> bool:
            info = NodeInfo.build(
                node, [p for p in resident if _pod_uid(p) not in victim_uids])
            if info is None:
                return False
            try:
                allocate(info, req)
                return True
            except AllocationFailure:
                return False

        proposed_uids = {_pod_uid(v) for v in proposed}
        victims: dict[str, dict] = {_pod_uid(p): p for p in resident
                                    if _pod_uid(p) in proposed_uids}

        if not fits(set(victims)):
            # proposed set insufficient: add vtpu-holding pods, lowest
            # priority first, until the pod fits or we run out. Pods whose
            # PDB has no disruptions left are never added by US (the
            # in-tree proposal may still contain them). Under the
            # victim-order hint (DecisionExplain gate) with a FRESH
            # headroom rollup, equal-priority extras order by measured
            # utilization instead of list order.
            pool = (p for p in resident
                    if _pod_uid(p) not in victims
                    and get_pod_device_claims(p) is not None
                    and not self._violates_pdb(p, pdb_cache))
            if ordering == "utilization":
                extras = sorted(
                    pool, key=lambda p: self._victim_order_key(
                        p, order_headroom, victim_costs))
            else:
                extras = sorted(pool, key=_pod_priority)
            ok = False
            for extra in extras:
                victims[_pod_uid(extra)] = extra
                added_uids.append(_pod_uid(extra))
                if fits(set(victims)):
                    ok = True
                    break
            if not ok:
                if victim_log is not None:
                    victim_log.update(
                        result="dropped", ordering=ordering,
                        considered=len(extras) + len(proposed))
                return None

        # minimize: a victim whose claims are not needed is spared
        # (reference "drops unneeded victims")
        for uid, victim in sorted(victims.items(),
                                  key=lambda kv: _pod_priority(kv[1]),
                                  reverse=True):
            if get_pod_device_claims(victim) is None:
                # non-vtpu victim: not ours to spare — kube-scheduler wants
                # it for other resources; keep it
                continue
            if fits(set(victims) - {uid}):
                spared.append(victim)
                del victims[uid]
        final = [victims[uid] for uid in sorted(victims)]
        exact = self._count_pdb_violations(final, pdb_cache)
        if exact is None:
            kept_from_input = sum(1 for p in final
                                  if _pod_uid(p) in proposed_uids)
            added = len(final) - kept_from_input
            exact = pdb_violations_upper_bound(
                original_pdb, kept_from_input, added)
        if victim_log is not None:
            added_set = set(added_uids)

            def row(pod: dict, role: str) -> dict:
                return dict(self._victim_inputs(pod, headroom,
                                                victim_costs),
                            role=role)

            victim_log.update(
                result="kept", ordering=ordering,
                headroom_fresh=hr_fresh,
                victim_costs_fresh=vc_fresh,
                pressure_frac=pressure.throttle_frac
                if pressure is not None else None,
                pdb_violations=exact,
                victims=[row(p, "added"
                             if _pod_uid(p) in added_set
                             and _pod_uid(p) not in proposed_uids
                             else "kept") for p in final],
                spared=[row(p, "spared") for p in spared])
        return NodeVictims(pods=final, num_pdb_violations=exact)
