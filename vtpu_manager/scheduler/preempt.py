"""Preempt predicate: re-validate kube-scheduler's victim sets.

Reference: pkg/scheduler/preempt/preempt_predicate.go:1-747 — the in-tree
preemption logic picks victims by pod priority without understanding vtpu
device occupancy, so the extender corrects it: victims whose eviction frees
no needed vtpu capacity are dropped, extra vtpu victims are added when the
proposed set is not enough, and nodes where no victim set makes the pod fit
are removed entirely. PDB-violation counts are preserved for kept victims.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from vtpu_manager.client.kube import KubeClient
from vtpu_manager.device.allocator.allocator import (AllocationFailure,
                                                     allocate)
from vtpu_manager.device.allocator.request import (RequestError,
                                                   build_allocation_request)
from vtpu_manager.device.types import NodeInfo, get_pod_device_claims

log = logging.getLogger(__name__)


@dataclass
class PreemptResult:
    node_to_victims: dict[str, list[dict]] = field(default_factory=dict)
    error: str = ""

    def to_wire(self) -> dict:
        if self.error:
            return {"Error": self.error}
        return {"NodeNameToMetaVictims": {
            node: {"Pods": [{"UID": (p.get("metadata") or {}).get("uid", "")}
                            for p in pods]}
            for node, pods in self.node_to_victims.items()}}


def _pod_priority(pod: dict) -> int:
    return (pod.get("spec") or {}).get("priority", 0)


def _pod_uid(pod: dict) -> str:
    return (pod.get("metadata") or {}).get("uid", "")


class PreemptPredicate:
    def __init__(self, client: KubeClient):
        self.client = client

    def preempt(self, args: dict) -> PreemptResult:
        pod = args.get("Pod") or args.get("pod") or {}
        # kube-scheduler sends NodeNameToVictims (full pods) when
        # nodeCacheCapable=false and NodeNameToMetaVictims (UIDs only) when
        # true; accept both, in Go-field or JSON-tag casing.
        victims_in = (args.get("NodeNameToVictims")
                      or args.get("nodeNameToVictims"))
        meta_only = False
        if victims_in is None:
            victims_in = (args.get("NodeNameToMetaVictims")
                          or args.get("nodeNameToMetaVictims") or {})
            meta_only = True
        try:
            req = build_allocation_request(pod)
        except RequestError as e:
            return PreemptResult(error=f"invalid vtpu request: {e}")
        if req.is_empty():
            # nothing for us to correct; pass the proposal through
            return PreemptResult(node_to_victims={
                node: self._proposal_pods(node, v, meta_only)
                for node, v in victims_in.items()})

        result = PreemptResult()
        for node_name, proposal in victims_in.items():
            proposed = self._proposal_pods(node_name, proposal, meta_only)
            kept = self._validate_node(node_name, req, proposed)
            if kept is not None:
                result.node_to_victims[node_name] = kept
        if not result.node_to_victims:
            result.error = "no node becomes schedulable by preemption"
        return result

    def _proposal_pods(self, node_name: str, proposal: dict | None,
                       meta_only: bool) -> list[dict]:
        """Resolve a victim proposal to pod dicts. MetaVictims carry only
        UIDs; resolve them against the node's resident pods."""
        pods = list((proposal or {}).get("Pods")
                    or (proposal or {}).get("pods") or [])
        if not meta_only:
            return pods
        uids = {(p.get("UID") or p.get("uid") or "") for p in pods}
        resident = self.client.list_pods(node_name=node_name)
        return [p for p in resident if _pod_uid(p) in uids]

    def _validate_node(self, node_name: str, req,
                       proposed: list[dict]) -> list[dict] | None:
        try:
            node = self.client.get_node(node_name)
        except Exception:
            return None
        resident = self.client.list_pods(node_name=node_name)

        def fits(victim_uids: set[str]) -> bool:
            info = NodeInfo.build(
                node, [p for p in resident if _pod_uid(p) not in victim_uids])
            if info is None:
                return False
            try:
                allocate(info, req)
                return True
            except AllocationFailure:
                return False

        proposed_uids = {_pod_uid(v) for v in proposed}
        victims: dict[str, dict] = {_pod_uid(p): p for p in resident
                                    if _pod_uid(p) in proposed_uids}

        if not fits(set(victims)):
            # proposed set insufficient: add vtpu-holding pods, lowest
            # priority first, until the pod fits or we run out
            extras = sorted(
                (p for p in resident
                 if _pod_uid(p) not in victims
                 and get_pod_device_claims(p) is not None),
                key=_pod_priority)
            ok = False
            for extra in extras:
                victims[_pod_uid(extra)] = extra
                if fits(set(victims)):
                    ok = True
                    break
            if not ok:
                return None

        # minimize: a victim whose claims are not needed is spared
        # (reference "drops unneeded victims")
        for uid, victim in sorted(victims.items(),
                                  key=lambda kv: _pod_priority(kv[1]),
                                  reverse=True):
            if get_pod_device_claims(victim) is None:
                # non-vtpu victim: not ours to spare — kube-scheduler wants
                # it for other resources; keep it
                continue
            if fits(set(victims) - {uid}):
                del victims[uid]
        return [victims[uid] for uid in sorted(victims)]


