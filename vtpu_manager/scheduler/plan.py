"""vtscale dynamic shard plans: the cluster's shard layout as a CAS'd
apiserver object instead of a per-replica flag.

Before this module, the shard layout lived only in each replica's
``--shard-pools`` argv: changing it meant restarting every scheduler
replica, and a half-rolled fleet ran two layouts at once with nothing to
arbitrate between them. The plan object fixes both:

- **One authoritative layout.** A single Lease object
  (``vtpu-scheduler-plan`` in the lease namespace) carries the
  ``--shard-pools`` spec string and a monotonically increasing **plan
  epoch** in its annotations, CAS'd through ``metadata.resourceVersion``
  exactly like the shard leader leases (scheduler/lease.py). Publishing
  the same spec twice is a no-op; publishing a different spec bumps the
  epoch by one.

- **Rolling reshard, fenced.** Every replica polls the plan on its
  maintenance tick. On an epoch bump it rebuilds its shard units to the
  new layout in place — no restart — and folds the new epoch into every
  fence stamp it writes (``<shard>:<token>+<epoch>``,
  lease.encode_fence). Commitments stamped under an older epoch are
  thereby *fence-rejected exactly like a stale leader's*: the takeover
  replay and the reschedule controller's reaper treat epoch-stale stamps
  as reapable trails, and the bind path refuses to post a Binding for
  them. The safety argument is the PR 6 fencing argument unchanged —
  the epoch is just a second monotone component in the same stamp.

The spec annotation body rides the shared ``…@ts`` staleness codec
(util/stalecodec.py) so operators can see *when* the layout last moved;
the epoch — not the stamp — is the authority (a plan never expires, it
is only superseded).

Gate story (ScalePipeline, default off): no plan object is created or
read, every fence stamp keeps the two-field form, and `--shard-pools`
behaves exactly as before.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.util import stalecodec

log = logging.getLogger(__name__)

PLAN_OBJECT_NAME = "vtpu-scheduler-plan"

# Plan annotation keys (protocol state in annotations, resourceVersion
# as the CAS handle — the ShardLease pattern)
PLAN_SPEC_ANN = "vtpu-manager.io/plan-spec"
PLAN_EPOCH_ANN = "vtpu-manager.io/plan-epoch"
PLAN_HOLDER_ANN = "vtpu-manager.io/plan-holder"

# one publish retry on CAS conflict: the loser re-reads and either
# adopts the winner's identical spec or re-CASes on top of it
_PUBLISH_ATTEMPTS = 3


@dataclass(frozen=True)
class PlanState:
    """Decoded view of the cluster shard plan, as any replica reads it."""

    epoch: int
    spec: str          # the --shard-pools grammar (shard.ShardPlan.parse)
    published_ts: float
    holder: str


def encode_plan_annotations(spec: str, epoch: int, holder: str,
                            ts: float) -> dict:
    return {
        PLAN_SPEC_ANN: stalecodec.stamp(spec, ts),
        PLAN_EPOCH_ANN: str(epoch),
        PLAN_HOLDER_ANN: holder,
    }


def decode_plan(lease: dict | None) -> PlanState | None:
    """PlanState from the plan object; None when absent or garbage.
    An undecodable plan reads as no-plan — replicas keep their argv
    layout at epoch 0 rather than guessing at a corrupt one."""
    if lease is None:
        return None
    anns = (lease.get("metadata") or {}).get("annotations") or {}
    stamped = stalecodec.split_stamp(anns.get(PLAN_SPEC_ANN))
    if stamped is None:
        return None
    spec, ts = stamped
    try:
        epoch = int(anns.get(PLAN_EPOCH_ANN, ""))
    except (TypeError, ValueError):
        return None
    if not spec or epoch < 1:
        return None
    return PlanState(epoch=epoch, spec=spec, published_ts=ts,
                     holder=anns.get(PLAN_HOLDER_ANN, ""))


def read_plan(client: KubeClient, namespace: str) -> PlanState | None:
    """One-shot plan probe. None means "no usable plan" — absent,
    undecodable, or the read failed transiently — and the caller keeps
    its current layout (argv at epoch 0, or the last adopted plan)."""
    try:
        lease = client.get_lease(namespace, PLAN_OBJECT_NAME)
    except KubeError as e:
        if e.status != 404:
            log.warning("plan read failed (%s); keeping current layout",
                        e)
        return None
    return decode_plan(lease)


def publish_plan(client: KubeClient, spec: str, holder: str,
                 namespace: str, now: float | None = None) -> PlanState:
    """Publish ``spec`` as the cluster shard plan, bumping the epoch iff
    the spec actually changed. Idempotent and CAS-safe: concurrent
    publishers of the same spec converge on one epoch; of different
    specs, on the last CAS winner. Raises KubeError when the apiserver
    stays unreachable."""
    if now is None:
        now = time.time()
    last_err: KubeError | None = None
    for _ in range(_PUBLISH_ATTEMPTS):
        try:
            lease = client.get_lease(namespace, PLAN_OBJECT_NAME)
        except KubeError as e:
            if e.status != 404:
                raise
            lease = None
        current = decode_plan(lease)
        if current is not None and current.spec == spec:
            return current
        epoch = (current.epoch if current is not None else 0) + 1
        anns = encode_plan_annotations(spec, epoch, holder, now)
        try:
            if lease is None:
                client.create_lease(namespace, PLAN_OBJECT_NAME, anns)
            else:
                version = (lease.get("metadata") or {}).get(
                    "resourceVersion", "")
                client.update_lease(namespace, PLAN_OBJECT_NAME, anns,
                                    version)
        except KubeError as e:
            if e.status == 409:
                last_err = e
                continue       # lost the race; re-read and re-judge
            raise
        log.info("shard plan published: epoch=%d spec=%r by %s",
                 epoch, spec, holder)
        return PlanState(epoch=epoch, spec=spec, published_ts=now,
                         holder=holder)
    raise last_err if last_err is not None else KubeError(
        409, "plan publish kept conflicting")
