"""Serial-section helpers for the extender endpoints.

Reference: pkg/scheduler/serial/serial.go:1-111 — optional global locking of
Filter and Bind passes (gated by SerialFilterNode / SerialBindNode) so that
concurrent extender calls do not double-book devices before annotation
patches land. Without the gate we still serialize per-pod via a keyed mutex.
"""

from __future__ import annotations

import contextlib
import threading


class SerialLocker:
    def __init__(self, serialize_all: bool):
        self._serialize_all = serialize_all
        self._global = threading.Lock()
        self._keyed: dict[str, threading.Lock] = {}
        self._keyed_guard = threading.Lock()

    @contextlib.contextmanager
    def section(self, key: str = ""):
        if self._serialize_all:
            with self._global:
                yield
            return
        with self._keyed_guard:
            lock = self._keyed.setdefault(key, threading.Lock())
        with lock:
            yield
