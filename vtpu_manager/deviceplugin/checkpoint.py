"""Kubelet checkpoint reader: pod <-> device recovery after restarts.

Reference: pkg/deviceplugin/checkpoint/checkpoint.go:11-99 reads the
kubelet's own kubelet_internal_checkpoint to recover which pods own which
device IDs (used by the recovery controller when a pod references devices
that no longer exist — controller/reschedule/recovery.go).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

KUBELET_CHECKPOINT = \
    "/var/lib/kubelet/device-plugins/kubelet_internal_checkpoint"


@dataclass(frozen=True)
class CheckpointEntry:
    pod_uid: str
    container: str
    resource: str
    device_ids: tuple[str, ...]


def read_checkpoint(path: str = KUBELET_CHECKPOINT) -> list[CheckpointEntry]:
    """Parse the kubelet device-manager checkpoint (JSON with a Data.
    PodDeviceEntries list). Malformed/absent files yield []."""
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return []
    entries = []
    for entry in ((doc.get("Data") or {}).get("PodDeviceEntries") or []):
        ids: list[str] = []
        dev_map = entry.get("DeviceIDs") or {}
        if isinstance(dev_map, dict):
            for chunk in dev_map.values():
                ids.extend(chunk or [])
        elif isinstance(dev_map, list):
            ids = dev_map
        entries.append(CheckpointEntry(
            pod_uid=entry.get("PodUID", ""),
            container=entry.get("ContainerName", ""),
            resource=entry.get("ResourceName", ""),
            device_ids=tuple(ids)))
    return entries


def devices_for_resource(resource: str,
                         path: str = KUBELET_CHECKPOINT) -> dict[str, set]:
    """pod_uid -> set of device ids held for one resource."""
    out: dict[str, set] = {}
    for entry in read_checkpoint(path):
        if entry.resource == resource:
            out.setdefault(entry.pod_uid, set()).update(entry.device_ids)
    return out
