"""Kubelet checkpoint reader: pod <-> device recovery after restarts.

Reference: pkg/deviceplugin/checkpoint/checkpoint.go:11-99 reads the
kubelet's own kubelet_internal_checkpoint to recover which pods own which
device IDs (used by the recovery controller when a pod references devices
that no longer exist — controller/reschedule/recovery.go).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

KUBELET_CHECKPOINT = \
    "/var/lib/kubelet/device-plugins/kubelet_internal_checkpoint"


@dataclass(frozen=True)
class CheckpointEntry:
    pod_uid: str
    container: str
    resource: str
    device_ids: tuple[str, ...]


def _string_ids(chunk) -> list[str]:
    """Only a list of strings contributes device ids. Anything else —
    a bare string (iterating it would yield CHARACTERS), a number, a
    nested dict — is a malformed entry and contributes nothing, because
    a garbage id here feeds the recovery controller's ghost-device
    eviction: mis-parsing must never read as "devices vanished"."""
    if not isinstance(chunk, list):
        return []
    return [d for d in chunk if isinstance(d, str)]


def read_checkpoint(path: str = KUBELET_CHECKPOINT) -> list[CheckpointEntry]:
    """Parse the kubelet device-manager checkpoint (JSON with a Data.
    PodDeviceEntries list). Malformed/absent/truncated files yield [];
    wrong-typed fields degrade per entry, never crash the reconcile."""
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return []
    if not isinstance(doc, dict):
        return []
    data = doc.get("Data")
    raw_entries = data.get("PodDeviceEntries") if isinstance(data, dict) \
        else None
    if not isinstance(raw_entries, list):
        return []
    entries = []
    for entry in raw_entries:
        if not isinstance(entry, dict):
            continue
        ids: list[str] = []
        dev_map = entry.get("DeviceIDs") or {}
        if isinstance(dev_map, dict):
            for chunk in dev_map.values():
                ids.extend(_string_ids(chunk))
        else:
            ids = _string_ids(dev_map)
        entries.append(CheckpointEntry(
            pod_uid=str(entry.get("PodUID") or ""),
            container=str(entry.get("ContainerName") or ""),
            resource=str(entry.get("ResourceName") or ""),
            device_ids=tuple(ids)))
    return entries


def devices_for_resource(resource: str,
                         path: str = KUBELET_CHECKPOINT) -> dict[str, set]:
    """pod_uid -> set of device ids held for one resource."""
    out: dict[str, set] = {}
    for entry in read_checkpoint(path):
        if entry.resource == resource:
            out.setdefault(entry.pod_uid, set()).update(entry.device_ids)
    return out
