"""Device-plugin gRPC scaffold: serving, kubelet registration, restarts.

Reference: pkg/deviceplugin/base/plugin_server.go:1-203 (serving scaffold)
and cmd/device-plugin/main.go:172-230 (kubelet-restart detection via
fsnotify on kubelet.sock + re-register loop). grpc stubs are hand-wired
(grpc codegen is unavailable in this image); the wire contract lives in
api/deviceplugin.proto.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent import futures

import grpc

from vtpu_manager.deviceplugin.api import deviceplugin_pb2 as pb
from vtpu_manager.util.grpcutil import unary as _unary

log = logging.getLogger(__name__)

KUBELET_DIR = "/var/lib/kubelet/device-plugins"
KUBELET_SOCKET = f"{KUBELET_DIR}/kubelet.sock"
API_VERSION = "v1beta1"


class DevicePluginServicer:
    """Override per plugin. Default implementations are inert."""

    resource_name = "example.com/none"
    socket_name = "vtpu-none.sock"
    pre_start_required = False
    preferred_allocation_available = False

    def list_devices(self) -> list[pb.Device]:
        return []

    def watch_devices(self):
        """Yield device lists; must yield at least once, then on changes."""
        yield self.list_devices()
        while True:
            time.sleep(5)
            yield self.list_devices()

    def get_preferred_allocation(
            self, request: pb.PreferredAllocationRequest
    ) -> pb.PreferredAllocationResponse:
        return pb.PreferredAllocationResponse()

    def allocate(self, request: pb.AllocateRequest) -> pb.AllocateResponse:
        return pb.AllocateResponse()

    def pre_start_container(
            self, request: pb.PreStartContainerRequest
    ) -> pb.PreStartContainerResponse:
        return pb.PreStartContainerResponse()


class PluginServer:
    """Serves one DevicePluginServicer on a unix socket and keeps it
    registered with the kubelet."""

    def __init__(self, servicer: DevicePluginServicer,
                 plugin_dir: str = KUBELET_DIR,
                 kubelet_socket: str | None = None):
        self.servicer = servicer
        self.plugin_dir = plugin_dir
        self.kubelet_socket = kubelet_socket or os.path.join(
            plugin_dir, "kubelet.sock")
        self.socket_path = os.path.join(plugin_dir, servicer.socket_name)
        self._server: grpc.Server | None = None
        self._stop = threading.Event()

    # -- grpc plumbing ------------------------------------------------------

    def _handlers(self) -> grpc.GenericRpcHandler:
        s = self.servicer

        def options(request, context):
            return pb.DevicePluginOptions(
                pre_start_required=s.pre_start_required,
                get_preferred_allocation_available=
                s.preferred_allocation_available)

        def list_and_watch(request, context):
            for devices in s.watch_devices():
                if self._stop.is_set():
                    return
                yield pb.ListAndWatchResponse(devices=devices)

        handlers = {
            "GetDevicePluginOptions": _unary(options, pb.Empty,
                                             pb.DevicePluginOptions),
            "ListAndWatch": grpc.unary_stream_rpc_method_handler(
                list_and_watch, request_deserializer=pb.Empty.FromString,
                response_serializer=pb.ListAndWatchResponse.SerializeToString),
            "GetPreferredAllocation": _unary(
                lambda req, ctx: s.get_preferred_allocation(req),
                pb.PreferredAllocationRequest,
                pb.PreferredAllocationResponse),
            "Allocate": _unary(lambda req, ctx: s.allocate(req),
                               pb.AllocateRequest, pb.AllocateResponse),
            "PreStartContainer": _unary(
                lambda req, ctx: s.pre_start_container(req),
                pb.PreStartContainerRequest, pb.PreStartContainerResponse),
        }
        return grpc.method_handlers_generic_handler("v1beta1.DevicePlugin",
                                                    handlers)

    def serve(self) -> None:
        os.makedirs(self.plugin_dir, exist_ok=True)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        log.info("%s serving on %s", self.servicer.resource_name,
                 self.socket_path)

    def register(self) -> None:
        """Announce to the kubelet (reference RegisterRequest flow)."""
        with grpc.insecure_channel(f"unix://{self.kubelet_socket}") as chan:
            stub = chan.unary_unary(
                "/v1beta1.Registration/Register",
                request_serializer=pb.RegisterRequest.SerializeToString,
                response_deserializer=pb.Empty.FromString)
            stub(pb.RegisterRequest(
                version=API_VERSION,
                endpoint=self.servicer.socket_name,
                resource_name=self.servicer.resource_name,
                options=pb.DevicePluginOptions(
                    pre_start_required=self.servicer.pre_start_required,
                    get_preferred_allocation_available=
                    self.servicer.preferred_allocation_available)),
                timeout=10)
        log.info("registered %s with kubelet",
                 self.servicer.resource_name)

    def watch_kubelet_restarts(self, poll_s: float = 2.0) -> None:
        """Re-register when kubelet.sock is recreated (reference: fsnotify
        + SIGHUP restart loop, main.go:172-230; polling works without
        inotify deps)."""

        # latch the current socket identity synchronously: a restart in
        # the window before the thread's first poll must not pass unseen
        try:
            st = os.stat(self.kubelet_socket)
            initial_id = (st.st_ino, st.st_ctime_ns)
        except OSError:
            initial_id = None

        def loop():
            last_id = initial_id
            while not self._stop.wait(poll_s):
                try:
                    st = os.stat(self.kubelet_socket)
                except OSError:
                    continue
                # inode alone is not enough: a recreated socket can reuse
                # the freed inode number (observed on tmpfs); the creation
                # time disambiguates
                sock_id = (st.st_ino, st.st_ctime_ns)
                if last_id is None:
                    last_id = sock_id
                    continue
                if sock_id != last_id:
                    log.warning("kubelet restarted; re-registering")
                    try:
                        self.register()
                        # only remember the new socket once registration
                        # succeeded — a kubelet whose Registration service
                        # is not up yet must be retried on the next poll,
                        # or the plugin silently vanishes from allocatable
                        last_id = sock_id
                    except grpc.RpcError:
                        log.error("re-registration failed; will retry")

        threading.Thread(target=loop, daemon=True,
                         name="vtpu-kubelet-watch").start()

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop(grace=1)
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
