"""vtpu-cores / vtpu-memory reporter plugins.

Reference: vcore_plugin.go:1-111 / vmem_plugin.go:1-113 behind the
CorePlugin/MemoryPlugin feature gates — they only *advertise* capacity so
requests/limits arithmetic works cluster-wide; allocation is carried
entirely by the vtpu-number plugin.
"""

from __future__ import annotations

from vtpu_manager.deviceplugin.api import deviceplugin_pb2 as pb
from vtpu_manager.deviceplugin.base import DevicePluginServicer
from vtpu_manager.manager.device_manager import DeviceManager
from vtpu_manager.util import consts

MIB = 2**20


class VcorePlugin(DevicePluginServicer):
    """Advertises 100 core-percent units per chip."""

    def __init__(self, manager: DeviceManager):
        self.manager = manager
        self.resource_name = consts.vtpu_cores_resource()
        self.socket_name = "vtpu-cores.sock"

    def list_devices(self) -> list[pb.Device]:
        out = []
        for chip in self.manager.chips:
            health = "Healthy" if chip.healthy else "Unhealthy"
            for pct in range(100):
                out.append(pb.Device(ID=f"{chip.uuid}::core-{pct}",
                                     health=health))
        return out


class VmemPlugin(DevicePluginServicer):
    """Advertises HBM capacity in MiB units (capped to bound the device
    list the kubelet must track: 1 unit = mem_unit MiB)."""

    def __init__(self, manager: DeviceManager, mem_unit_mib: int = 256):
        self.manager = manager
        self.mem_unit_mib = mem_unit_mib
        self.resource_name = consts.vtpu_memory_resource()
        self.socket_name = "vtpu-memory.sock"

    def list_devices(self) -> list[pb.Device]:
        out = []
        for chip in self.manager.chips:
            health = "Healthy" if chip.healthy else "Unhealthy"
            units = chip.memory // (self.mem_unit_mib * MIB)
            for unit in range(units):
                out.append(pb.Device(ID=f"{chip.uuid}::mem-{unit}",
                                     health=health))
        return out
