"""vtpu-number plugin: the real allocation path.

Reference: pkg/deviceplugin/vgpu/vnum_plugin.go:61-1150 —
- ListAndWatch advertises split_count slots per chip (re-announced on
  health flips);
- GetPreferredAllocation honors the scheduler's pre-allocated annotation
  (:321-502);
- Allocate (:663-916) finds the pod the scheduler committed, builds env +
  mounts + device nodes, writes the binary vtpu.config, patches the
  real-allocated annotation ("succeed"), or patches "failed" for the
  reschedule controller;
- PreStartContainer (:1042-1121) verifies recorded devices and rewrites a
  missing config under the gate, cleaning stale per-container state.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import Counter

from vtpu_manager import trace
from vtpu_manager.client.kube import KubeClient, KubeError
from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.config.node_config import NodeConfig
from vtpu_manager.deviceplugin.api import deviceplugin_pb2 as pb
from vtpu_manager.deviceplugin.base import DevicePluginServicer
from vtpu_manager.device.claims import DeviceClaim, PodDeviceClaims
from vtpu_manager.device.types import ChipSpec
from vtpu_manager.manager.device_manager import DeviceManager
from vtpu_manager.resilience import failpoints
from vtpu_manager.resilience.policy import RetryPolicy
from vtpu_manager.util import consts

log = logging.getLogger(__name__)

_COMPAT_BITS = {"host": consts.COMPAT_HOST, "cgroup": consts.COMPAT_CGROUP,
                "client": consts.COMPAT_CLIENT,
                "open-kernel": consts.COMPAT_OPEN_KERNEL}


def device_id(uuid: str, slot: int) -> str:
    return f"{uuid}::{slot}"


def device_uuid(dev_id: str) -> str:
    return dev_id.split("::", 1)[0]


class VnumPlugin(DevicePluginServicer):
    pre_start_required = True
    preferred_allocation_available = False   # gated: HonorPreAllocatedDeviceIDs
    step_telemetry_enabled = False           # gated: StepTelemetry (vttel)
    comm_telemetry_enabled = False           # gated: CommTelemetry (vtcomm;
                                             # rides the step ring — armed
                                             # only alongside StepTelemetry)
    compile_cache_enabled = False            # gated: CompileCache (vtcc)
    cluster_cache_enabled = False            # gated: ClusterCompileCache
                                             # (vtcs; requires vtcc — the
                                             # node store is the landing
                                             # surface either way)
    quota_market_enabled = False             # gated: QuotaMarket (vtqm)
    hbm_overcommit_enabled = False           # gated: HBMOvercommit (vtovc)
    ici_link_aware_enabled = False           # gated: ICILinkAware (vtici)
    # vtovc: the node's live policy engine (OvercommitPolicy | None) —
    # Allocate stamps each chip's virtual capacity from the CURRENT
    # per-class ratio, and the node host-RAM spill budget rides every
    # device entry (0 = gate off, the v3 zeros)
    overcommit_policy = None
    spill_budget_bytes = 0

    def __init__(self, manager: DeviceManager, client: KubeClient,
                 node_name: str, node_config: NodeConfig | None = None,
                 base_dir: str = consts.MANAGER_BASE_DIR,
                 shim_host_dir: str = consts.DRIVER_DIR,
                 libtpu_path: str = "/lib/libtpu.so",
                 disable_control: bool = False,
                 policy: RetryPolicy | None = None):
        self.manager = manager
        self.client = client
        self.node_name = node_name
        # Allocate runs on kubelet's clock (10s-scale gRPC deadline),
        # and one Allocate can cross this policy up to THREE times
        # (pending-scan list, succeed patch, failed patch) on
        # independent clocks — the per-call deadline must be small
        # enough that the worst-case SUM still fits inside kubelet's,
        # or a slow-failing patch could outlive the RPC and land a
        # "succeed" status on an Allocate kubelet already abandoned
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            base_delay_s=0.05,
                                            deadline_s=2.0)
        self.node_config = node_config or NodeConfig()
        self.base_dir = base_dir
        self.shim_host_dir = shim_host_dir
        self.libtpu_path = libtpu_path
        self.disable_control = disable_control
        self.resource_name = consts.vtpu_number_resource()
        self.socket_name = "vtpu-number.sock"
        self._update = threading.Event()
        manager.on_unhealthy(lambda chip: self._update.set())
        self._served_lock = threading.Lock()
        self._served: set[tuple[str, str]] = set()   # (pod_uid, container)

    # -- advertisement ------------------------------------------------------

    def list_devices(self) -> list[pb.Device]:
        out = []
        for chip in self.manager.chips:
            health = "Healthy" if chip.healthy else "Unhealthy"
            topo = pb.TopologyInfo(nodes=[pb.NUMANode(ID=chip.numa)])
            for slot in range(chip.split_count):
                out.append(pb.Device(ID=device_id(chip.uuid, slot),
                                     health=health, topology=topo))
        return out

    def watch_devices(self):
        yield self.list_devices()
        while True:
            self._update.wait(timeout=10)
            self._update.clear()
            yield self.list_devices()

    # -- scheduler-committed pod lookup -------------------------------------

    def _pending_allocations(self) -> list[tuple[dict, str,
                                                 list[DeviceClaim]]]:
        """(pod, container_name, claims) for containers the scheduler
        committed on this node but the plugin has not served yet.

        One cluster pod list, filtered locally: bound pods carry nodeName,
        while freshly-bound ones may only carry the predicate-node
        annotation (watch lag); dedup by uid. Per-container pending: a pod
        stays pending for container B after container A's Allocate patched
        the real-allocated annotation (which then covers only A).
        """
        out = []
        try:
            all_pods = self.policy.run(self.client.list_pods,
                                       op="plugin.list_pods")
        except KubeError:
            # retries exhausted / terminal: an empty pending set fails
            # this Allocate visibly (no matching pre-allocation) rather
            # than mis-serving — log so the cause is attributable
            log.warning("pod list failed during pending-allocation scan; "
                        "treating as no pending pods", exc_info=True)
            return out
        seen_uids: set[str] = set()
        pods = []
        for pod in all_pods:
            meta = pod.get("metadata") or {}
            uid = meta.get("uid", "")
            if uid in seen_uids:
                continue
            anns = meta.get("annotations") or {}
            on_node = ((pod.get("spec") or {}).get("nodeName") ==
                       self.node_name or
                       anns.get(consts.predicate_node_annotation()) ==
                       self.node_name)
            if on_node:
                seen_uids.add(uid)
                pods.append(pod)
        with self._served_lock:
            served = set(self._served)
        from vtpu_manager.device.claims import try_decode
        for pod in pods:
            meta = pod.get("metadata") or {}
            anns = meta.get("annotations") or {}
            pre = try_decode(anns.get(consts.pre_allocated_annotation()))
            if pre is None:
                continue
            real = try_decode(anns.get(consts.real_allocated_annotation()))
            done_containers = set(real.containers) if real else set()
            uid = meta.get("uid", "")
            for cont, cont_claims in pre.containers.items():
                if (cont_claims and cont not in done_containers
                        and (uid, cont) not in served):
                    out.append((pod, cont, cont_claims))
        return out

    # -- GetPreferredAllocation --------------------------------------------

    def get_preferred_allocation(self, request):
        resp = pb.PreferredAllocationResponse()
        pending = self._pending_allocations()
        for creq in request.container_requests:
            available = list(creq.available_deviceIDs)
            preferred: list[str] = []
            for _, _, claims in pending:
                if len(claims) != creq.allocation_size:
                    continue
                picks = self._pick_ids_for_claims(claims, available)
                if picks is not None:
                    preferred = picks
                    break
            if not preferred:
                preferred = list(creq.must_include_deviceIDs)
                for dev in available:
                    if len(preferred) >= creq.allocation_size:
                        break
                    if dev not in preferred:
                        preferred.append(dev)
            resp.container_responses.append(
                pb.ContainerPreferredAllocationResponse(
                    deviceIDs=preferred[: creq.allocation_size]))
        return resp

    @staticmethod
    def _pick_ids_for_claims(claims: list[DeviceClaim],
                             available: list[str]) -> list[str] | None:
        by_uuid: dict[str, list[str]] = {}
        for dev in available:
            by_uuid.setdefault(device_uuid(dev), []).append(dev)
        picks = []
        for claim in claims:
            pool = by_uuid.get(claim.uuid)
            if not pool:
                return None
            picks.append(pool.pop(0))
        return picks

    # -- Allocate -----------------------------------------------------------

    def allocate(self, request):
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            resp.container_responses.append(
                self._allocate_container(list(creq.devicesIDs)))
        return resp

    def _match_container(self, dev_ids: list[str]
                         ) -> tuple[dict, str, list[DeviceClaim]] | None:
        want = Counter(device_uuid(d) for d in dev_ids)
        for pod, cont, claims in self._pending_allocations():
            if Counter(c.uuid for c in claims) == want:
                return (pod, cont, claims)
        return None

    def _allocate_container(self, dev_ids: list[str]
                            ) -> pb.ContainerAllocateResponse:
        match = self._match_container(dev_ids)
        if match is None:
            # kubelet allocated devices we cannot tie to a scheduler
            # commitment (e.g. bypassed scheduler): serve permissively with
            # no enforcement config, mirroring the reference's fallback.
            log.warning("allocate without matching pre-allocation: %s",
                        dev_ids)
            return self._response_for(None, "", [
                DeviceClaim(device_uuid(d), self._host_index(device_uuid(d)),
                            0, 0) for d in dev_ids])
        pod, cont, claims = match
        meta = pod.get("metadata") or {}
        uid = meta.get("uid", "")
        ctx = trace.context_for_pod(pod)
        try:
            with trace.span(ctx, "plugin.allocate", container=cont,
                            devices=len(dev_ids)):
                failpoints.fire("plugin.allocate", pod_uid=uid,
                                container=cont)
                response = self._response_for(pod, cont, claims)
                self._record_devices(uid, cont, dev_ids, claims)
                self.policy.run(
                    lambda: self.client.patch_pod_annotations(
                        meta.get("namespace", "default"),
                        meta.get("name", ""), {
                            consts.real_allocated_annotation():
                                self._claims_annotation(pod, cont, claims),
                            consts.allocation_status_annotation():
                                consts.ALLOC_STATUS_SUCCEED,
                        }),
                    op="plugin.allocate_patch")
            with self._served_lock:
                self._served.add((uid, cont))
            return response
        except Exception:
            log.exception("allocate failed for %s/%s", uid, cont)
            try:
                self.policy.run(
                    lambda: self.client.patch_pod_annotations(
                        meta.get("namespace", "default"),
                        meta.get("name", ""),
                        {consts.allocation_status_annotation():
                             consts.ALLOC_STATUS_FAILED}),
                    op="plugin.failed_patch")
            except KubeError:
                # the reschedule controller's allocating-stuck reaper is
                # the backstop when even the failed patch cannot land
                log.warning("failed-status patch did not land for %s/%s; "
                            "relying on the allocating-stuck reaper",
                            uid, cont, exc_info=True)
            raise

    def _claims_annotation(self, pod: dict, cont: str,
                           claims: list[DeviceClaim]) -> str:
        """Merge this container into the REAL allocation annotation only —
        seeding from the pre-allocation would promote other containers'
        uncommitted claims to 'real'."""
        from vtpu_manager.device.claims import try_decode
        anns = (pod.get("metadata") or {}).get("annotations") or {}
        decoded = try_decode(anns.get(consts.real_allocated_annotation()))
        # decoded objects are cached and shared — copy before mutating
        existing = decoded.copy() if decoded else PodDeviceClaims()
        existing.containers[cont] = claims
        return existing.encode()

    def _host_index(self, uuid: str) -> int:
        for chip in self.manager.chips:
            if chip.uuid == uuid:
                return chip.index
        return 0

    def _chip(self, uuid: str) -> ChipSpec | None:
        for chip in self.manager.chips:
            if chip.uuid == uuid:
                return chip
        return None

    def _container_dir(self, pod_uid: str, cont: str) -> str:
        return os.path.join(self.base_dir, f"{pod_uid}_{cont}")

    def _response_for(self, pod: dict | None, cont: str,
                      claims: list[DeviceClaim]
                      ) -> pb.ContainerAllocateResponse:
        resp = pb.ContainerAllocateResponse()
        meta = (pod or {}).get("metadata") or {}
        anns = meta.get("annotations") or {}
        uid = meta.get("uid", "")
        compute_policy = anns.get(consts.compute_policy_annotation(),
                                  consts.COMPUTE_POLICY_FIXED)
        oversold = anns.get(consts.memory_oversold_annotation(), "") == "true"

        host_indices = [c.host_index for c in claims]
        resp.envs[consts.ENV_VISIBLE_DEVICES] = ",".join(
            str(i) for i in host_indices)
        resp.envs[consts.ENV_TPU_VISIBLE_DEVICES] = ",".join(
            str(i) for i in host_indices)
        # vtovc: the chip's virtual capacity is stamped from the
        # CURRENT per-class ratio (the same policy engine the node
        # annotation publishes, so the shim and the scheduler agree on
        # the admitted split); gate off = ratio 1.0 and zeros below
        # vtici: the webhook-normalized ICI link share rides into every
        # device entry of the v5 config so the shim's ICI token bucket
        # shapes this tenant's multi-chip dispatch; gate off or
        # absent/garbage annotation = 0 = unshaped (the v4 wire bytes).
        # The webhook validated 1..100 at admission; an un-admitted
        # value that skipped normalization is re-validated, not trusted.
        ici_pct = 0
        if self.ici_link_aware_enabled and pod is not None:
            raw = anns.get(consts.ici_link_pct_annotation(), "")
            try:
                pct = int(str(raw).strip()) if raw else 0
            except (TypeError, ValueError):
                pct = 0
            if 1 <= pct <= 100:
                ici_pct = pct
        oc_ratio = 1.0
        if self.hbm_overcommit_enabled and pod is not None:
            from vtpu_manager import quota
            from vtpu_manager.overcommit import ratio as oc_mod
            oc = None
            if self.overcommit_policy is not None:
                try:
                    oc = self.overcommit_policy.compute()
                except Exception:  # noqa: BLE001 — a torn policy fold
                    # degrades THIS allocation to physical admission
                    # (ratio 1.0, the safe direction), never fails it
                    log.warning("overcommit policy compute failed; "
                                "allocating at physical capacity",
                                exc_info=True)
            oc_ratio = oc_mod.ratio_for_class(
                oc, quota.workload_class_of(pod))
        devices = []
        for i, claim in enumerate(claims):
            if claim.memory:
                resp.envs[f"{consts.ENV_MEM_LIMIT}_{i}"] = str(claim.memory)
            if claim.cores:
                resp.envs[f"{consts.ENV_CORE_LIMIT}_{i}"] = str(claim.cores)
                soft = claim.cores
                core_limit = vc.CORE_LIMIT_HARD
                if compute_policy == consts.COMPUTE_POLICY_BALANCE:
                    soft = 100
                    core_limit = vc.CORE_LIMIT_SOFT
                    resp.envs[f"{consts.ENV_CORE_SOFT_LIMIT}_{i}"] = \
                        str(soft)
                elif compute_policy == consts.COMPUTE_POLICY_NONE:
                    core_limit = vc.CORE_LIMIT_NONE
            else:
                soft, core_limit = 0, vc.CORE_LIMIT_NONE
            chip = self._chip(claim.uuid)
            real_mem = chip.memory if chip else claim.memory
            mesh = chip.coords if chip else (0, 0, 0)
            devices.append(vc.DeviceConfig(
                uuid=claim.uuid, total_memory=claim.memory,
                real_memory=real_mem, hard_core=claim.cores,
                soft_core=soft, core_limit=core_limit,
                memory_limit=claim.memory > 0, memory_oversold=oversold,
                host_index=claim.host_index, mesh=mesh,
                # vtovc: virtual chip capacity + node spill budget
                # (zeros when the gate is off — the v3 wire bytes)
                virtual_hbm_bytes=(int(real_mem * oc_ratio)
                                   if self.hbm_overcommit_enabled
                                   else 0),
                spill_budget_bytes=(self.spill_budget_bytes
                                    if self.hbm_overcommit_enabled
                                    else 0),
                # vtici: the tenant's ICI link share (0 when the gate
                # is off — the v4 wire bytes)
                ici_link_pct=ici_pct))
            resp.devices.append(pb.DeviceSpec(
                container_path=f"/dev/accel{claim.host_index}",
                host_path=f"/dev/accel{claim.host_index}",
                permissions="rw"))

        compat = _COMPAT_BITS.get(self.node_config.compat_mode,
                                  consts.COMPAT_HOST)
        resp.envs[consts.ENV_COMPAT_MODE] = str(compat)
        resp.envs[consts.ENV_POD_NAME] = meta.get("name", "")
        resp.envs[consts.ENV_POD_NAMESPACE] = meta.get("namespace", "")
        resp.envs[consts.ENV_POD_UID] = uid
        resp.envs[consts.ENV_CONTAINER_NAME] = cont

        # vtrace: hand the admission-minted context into the container
        # (env is the only channel that reaches the shim/runtime client),
        # carrying the sampling decision so tenants skip coherently
        ctx = trace.context_for_pod(pod) if pod is not None else None
        if ctx is not None and ctx.trace_id:
            resp.envs[consts.ENV_TRACE_ID] = ctx.trace_id
            resp.envs[consts.ENV_TRACE_SAMPLED] = \
                "true" if ctx.sampled else "false"

        if pod is not None and not self.disable_control:
            cont_dir = self._container_dir(uid, cont)
            config_host = os.path.join(cont_dir, "config")
            # vtcc: the cache dir must EXIST before anything arms on it
            # — the config field below and the mount/env both key off
            # this one verdict, so a failed makedirs can never leave
            # the C++ shim armed on a path that was never mounted
            cc_host = os.path.join(self.base_dir,
                                   consts.COMPILE_CACHE_SUBDIR)
            cc_ok = False
            if self.compile_cache_enabled:
                try:
                    os.makedirs(cc_host, exist_ok=True)
                    cc_ok = True
                except OSError as e:
                    log.warning("compile cache dir %s unavailable (%s); "
                                "tenant %s/%s compiles uncached",
                                cc_host, e, uid, cont)
            # vtqm: the webhook-normalized workload class rides into the
            # config ABI so the shim and the node's market manager agree
            # on which side of the market this tenant sits; vtovc reads
            # the same field for its per-class ratio samples. Both
            # gates off = WORKLOAD_CLASS_NONE = the zero bytes v2
            # carried.
            wl_class = vc.WORKLOAD_CLASS_NONE
            if self.quota_market_enabled or self.hbm_overcommit_enabled:
                from vtpu_manager import quota
                wl_class = quota.workload_class_abi(
                    quota.workload_class_of(pod))
            with trace.span(ctx, "plugin.config", container=cont,
                            devices=len(devices)):
                os.makedirs(config_host, exist_ok=True)
                cfg = vc.VtpuConfig(pod_uid=uid,
                                    pod_name=meta.get("name", ""),
                                    pod_namespace=meta.get("namespace", ""),
                                    container_name=cont, compat_mode=compat,
                                    # vtcc: non-empty only when the gate
                                    # is on AND the dir exists — the C++
                                    # shim's arm switch, mirroring the
                                    # env the runtime client reads
                                    compile_cache_dir=(
                                        consts.COMPILE_CACHE_DIR
                                        if cc_ok else ""),
                                    workload_class=wl_class,
                                    devices=devices)
                cfg_path = os.path.join(config_host, "vtpu.config")
                vc.write_config(cfg_path, cfg)
                # partial-write action tears the file just written (the
                # mid-write-crash state PreStartContainer must rewrite)
                failpoints.fire("plugin.config_write", pod_uid=uid,
                                path=cfg_path)
            # mounts: per-container config, the shim, locks, vmem, watcher
            # (reference vnum_plugin.go:799-879); the PJRT substitution envs
            # play the role of ld.so.preload (:872-879)
            resp.mounts.append(pb.Mount(
                container_path=f"{consts.MANAGER_BASE_DIR}/config",
                host_path=config_host, read_only=True))
            resp.mounts.append(pb.Mount(
                container_path=consts.DRIVER_DIR,
                host_path=self.shim_host_dir, read_only=True))
            for path in (consts.LOCK_DIR, consts.VMEM_DIR):
                resp.mounts.append(pb.Mount(container_path=path,
                                            host_path=path, read_only=False))
            if self.hbm_overcommit_enabled:
                # vtovc: the host-RAM spill pool lives under VMEM_DIR
                # (already mounted read-write above), so arming is one
                # mkdir + the env the shim's spill tier keys on — and,
                # like the compile-cache pair, the env only appears when
                # the directory actually exists
                try:
                    os.makedirs(consts.SPILL_DIR, exist_ok=True)
                    resp.envs[consts.ENV_SPILL_POOL_DIR] = \
                        consts.SPILL_DIR
                except OSError as e:
                    log.warning("spill pool dir %s unavailable (%s); "
                                "tenant %s/%s runs without the host "
                                "spill tier", consts.SPILL_DIR, e, uid,
                                cont)
            if ctx is not None and ctx.sampled:
                # tenant-side spans (shim register / first-execute) spool
                # into the node trace dir — mounted read-write like the
                # lock/vmem dirs so runtime/client's recorder reaches it
                try:
                    os.makedirs(consts.TRACE_DIR, exist_ok=True)
                    resp.mounts.append(pb.Mount(
                        container_path=consts.TRACE_DIR,
                        host_path=consts.TRACE_DIR, read_only=False))
                except OSError as e:
                    log.warning("trace dir %s unavailable (%s); tenant "
                                "spans for %s/%s will not spool",
                                consts.TRACE_DIR, e, uid, cont)
            if cc_ok:
                # vtcc: ONE node-shared executable cache (unlike the
                # per-container telemetry subdir — cross-tenant sharing
                # is the point), mounted read-write at the canonical
                # container path. The env arms the runtime client;
                # cfg.compile_cache_dir above is the same switch for
                # the C++ shim.
                resp.mounts.append(pb.Mount(
                    container_path=consts.COMPILE_CACHE_DIR,
                    host_path=cc_host, read_only=False))
                resp.envs[consts.ENV_COMPILE_CACHE] = "true"
                resp.envs[consts.ENV_COMPILE_CACHE_DIR] = \
                    consts.COMPILE_CACHE_DIR
                if self.cluster_cache_enabled:
                    # vtcs: the cluster tier arms only on top of a
                    # mounted node cache (cc_ok) — the runtime client
                    # then constructs a ClusterCompileCache whose miss
                    # path peer-fetches via the peers.json the
                    # advertiser maintains under the same mount
                    resp.envs[consts.ENV_CLUSTER_CACHE] = "true"
            if self.step_telemetry_enabled:
                # vttel: the per-container telemetry subdir (next to the
                # read-only config) is the ONE writable surface the
                # tenant gets under its own config dir — the step ring
                # lives there, the monitor tails it by host path
                tel_host = os.path.join(cont_dir, consts.TELEMETRY_SUBDIR)
                tel_cont = os.path.join(consts.MANAGER_BASE_DIR,
                                        consts.TELEMETRY_SUBDIR)
                try:
                    os.makedirs(tel_host, exist_ok=True)
                    resp.mounts.append(pb.Mount(
                        container_path=tel_cont, host_path=tel_host,
                        read_only=False))
                    resp.envs[consts.ENV_STEP_TELEMETRY] = "true"
                    resp.envs[consts.ENV_STEP_RING_PATH] = os.path.join(
                        tel_cont, consts.STEP_RING_NAME)
                    if self.comm_telemetry_enabled:
                        # vtcomm: arm the shim's measured-communication
                        # accumulators (collective/transfer spans +
                        # bytes into the ring's v3 comm block, honest
                        # ICI currency). Injected only alongside the
                        # ring env — the ring is the wire; gate off
                        # leaves the comm block zeroed pad.
                        resp.envs[consts.ENV_COMM_TELEMETRY] = "true"
                except OSError as e:
                    log.warning("telemetry dir %s unavailable (%s); "
                                "tenant %s/%s runs untelemetered",
                                tel_host, e, uid, cont)
            resp.mounts.append(pb.Mount(
                container_path=consts.WATCHER_DIR,
                host_path=consts.WATCHER_DIR, read_only=True))
            if compat & consts.COMPAT_CLIENT:
                resp.mounts.append(pb.Mount(
                    container_path=consts.REGISTRY_DIR,
                    host_path=consts.REGISTRY_DIR, read_only=False))
            shim = os.path.join(consts.DRIVER_DIR,
                                consts.CONTROL_LIBRARY_NAME)
            resp.envs[consts.ENV_TPU_LIBRARY_PATH] = shim
            resp.envs[consts.ENV_PJRT_PLUGIN_LIBRARY_PATH] = shim
            resp.envs[consts.ENV_VTPU_REAL_PLUGIN_PATH] = self.libtpu_path
            resp.envs["VTPU_CONFIG_PATH"] = \
                f"{consts.MANAGER_BASE_DIR}/config/vtpu.config"
            if self.manager.obs_excess_table is not None:
                # daemon-calibrated span-inflation table: the shim
                # discounts isolated spans by the interpolated excess
                # instead of its own transfer-leg probe (obs_calibrate.py)
                resp.envs[consts.ENV_OBS_EXCESS_TABLE] = \
                    self.manager.obs_excess_table
        return resp

    # -- records + PreStartContainer ---------------------------------------

    def _records_path(self) -> str:
        return os.path.join(self.base_dir, consts.DEVICES_JSON_NAME)

    def _record_devices(self, pod_uid: str, cont: str, dev_ids: list[str],
                        claims: list[DeviceClaim]) -> None:
        path = self._records_path()
        records = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    records = json.load(f)
            except (OSError, json.JSONDecodeError):
                records = {}
        # prune records no allocation can still reference (a week covers
        # any kubelet checkpoint lifetime; stale entries must not shadow a
        # new tenant's record in PreStartContainer)
        cutoff = time.time() - 7 * 24 * 3600
        # not a cross-node staleness signal: these records are written and
        # read by this node's own plugin, so there is no publisher clock to
        # skew against, and a future-stamped record (local clock step) must
        # SURVIVE the GC — is_fresh's skew bound would prune a live
        # allocation's record.
        records = {k: v for k, v in records.items()
                   if v.get("ts", 0) >= cutoff}  # vtlint: disable=stalecodec
        records[f"{pod_uid}/{cont}"] = {
            "devices": dev_ids,
            "claims": [c.to_wire() for c in claims],
            "ts": time.time(),
        }
        os.makedirs(self.base_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(records, f)
        os.replace(tmp, path)
        failpoints.fire("plugin.record_devices", pod_uid=pod_uid, path=path)

    def pre_start_container(self, request):
        """Verify the requested devices belong to a recorded allocation and
        the config file exists (rewriting it if the Allocate-phase write
        was lost — reference vnum_plugin.go:1042-1121)."""
        dev_ids = list(request.devicesIDs)
        want = Counter(device_uuid(d) for d in dev_ids)
        path = self._records_path()
        records = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    records = json.load(f)
            except (OSError, json.JSONDecodeError):
                records = {}
        # Only exact device-id matches (slots included), newest first.  A
        # uuid-multiset fallback would let a stale record from a previous
        # tenant of the same chip be selected, rewriting vtpu.config from
        # the wrong claims and deleting the wrong pids.config (ADVICE r1).
        ordered = sorted(records.items(),
                         key=lambda kv: kv[1].get("ts", 0), reverse=True)
        exact = [kv for kv in ordered
                 if sorted(kv[1].get("devices", [])) == sorted(dev_ids)]
        if not exact and ordered:
            log.error(
                "prestart: devices %s match no record exactly; %d records "
                "exist (same-uuid fallback refused — stale-tenant hazard)",
                dev_ids, len(ordered))
        for key, rec in exact:
            claims = [DeviceClaim.from_wire(c) for c in rec.get("claims", [])]
            if Counter(c.uuid for c in claims) != want:
                continue
            pod_uid, _, cont = key.partition("/")
            cfg_path = os.path.join(self._container_dir(pod_uid, cont),
                                    "config", "vtpu.config")
            if not os.path.exists(cfg_path):
                log.warning("config missing at prestart; rewriting %s",
                            cfg_path)
                # minimal rewrite from the recorded claims
                os.makedirs(os.path.dirname(cfg_path), exist_ok=True)
                devices = []
                for claim in claims:
                    chip = self._chip(claim.uuid)
                    devices.append(vc.DeviceConfig(
                        uuid=claim.uuid, total_memory=claim.memory,
                        real_memory=chip.memory if chip else claim.memory,
                        hard_core=claim.cores, soft_core=claim.cores,
                        core_limit=vc.CORE_LIMIT_HARD if claim.cores
                        else vc.CORE_LIMIT_NONE,
                        memory_limit=claim.memory > 0,
                        host_index=claim.host_index))
                vc.write_config(cfg_path, vc.VtpuConfig(
                    pod_uid=pod_uid, container_name=cont, devices=devices))
            # stale per-container state from a previous tenant
            pids_cfg = os.path.join(self._container_dir(pod_uid, cont),
                                    "config", consts.PIDS_CONFIG_NAME)
            if os.path.exists(pids_cfg):
                try:
                    os.unlink(pids_cfg)
                except OSError:
                    pass
            return pb.PreStartContainerResponse()
        raise RuntimeError(
            f"prestart devices {dev_ids} match no recorded allocation")
