"""Preemption victim-cost rollup: which residents are CHEAP to evict.

Closes the two carried victim-ordering gaps (ROADMAP quota item (c) +
vtovc item (c)): priority stays the primary preemption key, but among
equal-priority candidates two node-local facts make a victim strictly
cheaper than its measured utilization alone suggests —

- **lease state** (vtqm): a tenant holding an active quota *borrow*
  lease is running on capacity that is revocable/expiring by contract;
  evicting it destroys less durable entitlement than evicting a
  base-allocation tenant of the same priority.
- **spill residency** (vtovc): a tenant whose working set is mostly
  host-resident (vmem ``spilled`` / (resident + spilled)) has already
  lost its HBM locality — eviction forfeits little the spill tier
  hasn't forfeited, and frees the HBM pressure that drove the spilling.

Both facts live in node-local files (the quota lease ledger, the vmem
ledger) the scheduler can't read, so the device-plugin publishes a
compact per-tenant rollup over the registry channel::

    "<uid12>:<lease_flag>:<spill_frac>;...@<wall_ts>"

``uid12`` is the pod-uid prefix (the victim join key), ``lease_flag``
``l``/``-`` (active borrow lease or not), ``spill_frac`` a 0..1
decimal. Staleness-by-timestamp family like pressure/headroom: the
preempt path re-judges freshness at use time, and a stale or absent
rollup degrades the victim sort to the byte-identical priority-only
(or utilization-only) order — an eviction justified by a dead
publisher's claims would be a real pod killed over a ghost signal.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass

from vtpu_manager.util import consts, stalecodec

log = logging.getLogger(__name__)

MAX_VICTIM_COST_AGE_S = 120.0
FUTURE_SKEW_TOLERANCE_S = stalecodec.FUTURE_SKEW_TOLERANCE_S

UID_PREFIX_LEN = 12

# bound the annotation: a node hosts tens of tenants, not thousands —
# and the parse is per preempt candidate, so it must stay cheap
MAX_TENANTS = 64
MAX_VC_LEN = 4096


@dataclass(frozen=True)
class NodeVictimCosts:
    """Decoded rollup: uid-prefix -> (holds_lease, spill_frac)."""

    tenants: dict
    ts: float

    def encode(self) -> str:
        body = ";".join(
            f"{uid}:{'l' if leased else '-'}:{frac:.3f}"
            for uid, (leased, frac) in sorted(self.tenants.items()))
        return stalecodec.stamp(body, self.ts)

    def lookup(self, pod_uid: str) -> tuple[bool, float] | None:
        """(holds_lease, spill_frac) for a victim, joined by uid
        prefix; None = this tenant has no published row (no signal,
        which must read as 'not cheaper', never as 'cheapest')."""
        return self.tenants.get((pod_uid or "")[:UID_PREFIX_LEN])


def parse_victim_costs(raw: str | None, now: float | None = None,
                       max_age_s: float = MAX_VICTIM_COST_AGE_S
                       ) -> NodeVictimCosts | None:
    """Decode the annotation; None when absent, malformed, or stale —
    the codec-family contract: garbage degrades to no-signal, and
    no-signal degrades the ordering to the priority-only sort."""
    split = stalecodec.split_stamp(raw, max_len=MAX_VC_LEN)
    if split is None:
        return None
    body, ts = split
    if not stalecodec.is_fresh(ts, now, max_age_s):
        return None
    tenants: dict = {}
    for seg in body.split(";"):
        if not seg:
            continue
        parts = seg.split(":")
        if len(parts) != 3:
            continue        # one malformed row never blinds the rest
        uid, flag, frac_raw = parts
        if not uid or flag not in ("l", "-"):
            continue
        try:
            frac = float(frac_raw)
        except (TypeError, ValueError):
            continue
        if not math.isfinite(frac):
            continue
        tenants[uid[:UID_PREFIX_LEN]] = (flag == "l",
                                         min(max(frac, 0.0), 1.0))
        if len(tenants) >= MAX_TENANTS:
            break
    return NodeVictimCosts(tenants=tenants, ts=ts)


def victim_costs_fresh(vc: "NodeVictimCosts | None",
                       now: float | None = None) -> bool:
    """Use-time freshness re-judgement (the pressure-penalty rule: the
    snapshot caches the parsed object and a dead publisher emits no
    further node events)."""
    if vc is None:
        return False
    return stalecodec.is_fresh(vc.ts, now, MAX_VICTIM_COST_AGE_S)


# ---------------------------------------------------------------------------
# collection (device-plugin side: where the ledgers live)
# ---------------------------------------------------------------------------

def collect_victim_costs(base_dir: str, vmem_path: str | None = None,
                         include_leases: bool = True,
                         include_spill: bool = True,
                         now: float | None = None) -> NodeVictimCosts:
    """Fold the node's quota lease ledger and vmem ledger into one
    rollup. Either source may be disabled (its gate off) or broken —
    a tenant simply gets no row / a partial row, and absent rows read
    as 'not cheaper' on the preempt side."""
    now = time.time() if now is None else now
    tenants: dict = {}

    def row(uid: str) -> list:
        key = uid[:UID_PREFIX_LEN]
        got = tenants.get(key)
        if got is None:
            got = [False, 0.0]
            tenants[key] = got
        return got

    if include_leases:
        try:
            from vtpu_manager.quota.ledger import QuotaLeaseLedger
            ledger = QuotaLeaseLedger(base_dir)
            if ledger.exists():
                for lease in ledger.snapshot(now=now).active:
                    borrower = lease.get("borrower", "")
                    uid = borrower.partition("/")[0]
                    if uid:
                        row(uid)[0] = True
        except Exception:  # noqa: BLE001 — a torn ledger costs the
            # lease column only; the codec's absent-row semantics carry
            log.warning("victim-cost lease fold failed", exc_info=True)

    if include_spill:
        try:
            from vtpu_manager.config.tenantdirs import \
                iter_container_config_paths
            from vtpu_manager.config.vmem import VmemLedger, fnv64
            # resident/spilled bytes per owner token, then joined back
            # to pod uids through the one shared tenant-dir walk (the
            # vtuse join rule — one labeling, or joins desynchronize)
            by_token: dict[int, list] = {}
            ledger = VmemLedger(vmem_path or consts.VMEM_NODE_CONFIG)
            try:
                for entry in ledger.entries():
                    tot = by_token.setdefault(entry.owner_token,
                                              [0, 0])
                    tot[0] += entry.bytes
                    tot[1] += entry.spilled
            finally:
                ledger.close()
            for pod_uid, label, _path, _dra in \
                    iter_container_config_paths(base_dir):
                tot = by_token.get(fnv64(f"{pod_uid}/{label}"))
                if tot is None:
                    continue
                resident, spilled = tot
                if resident + spilled <= 0:
                    continue
                frac = spilled / (resident + spilled)
                got = row(pod_uid)
                got[1] = max(got[1], frac)
        except Exception:  # noqa: BLE001 — same posture: the spill
            # column degrades to 0.0, never to a wrong eviction
            log.warning("victim-cost spill fold failed", exc_info=True)

    return NodeVictimCosts(
        tenants={k: (v[0], v[1]) for k, v in tenants.items()}, ts=now)


class VictimCostPublisher:
    """Daemon loop: collect the rollup, patch the node annotation —
    the pressure-publisher discipline (per-tick failure tolerance, the
    timestamp ages a silent death out to no-signal)."""

    def __init__(self, client, node_name: str, base_dir: str,
                 vmem_path: str | None = None,
                 include_leases: bool = True,
                 include_spill: bool = True,
                 policy=None, interval_s: float = 15.0):
        from vtpu_manager.resilience.policy import RetryPolicy
        self.client = client
        self.node_name = node_name
        self.base_dir = base_dir
        self.vmem_path = vmem_path
        self.include_leases = include_leases
        self.include_spill = include_spill
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            deadline_s=10.0)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def publish_once(self) -> NodeVictimCosts:
        vc = collect_victim_costs(
            self.base_dir, vmem_path=self.vmem_path,
            include_leases=self.include_leases,
            include_spill=self.include_spill)
        self.policy.run(
            lambda: self.client.patch_node_annotations(
                self.node_name,
                {consts.node_victim_cost_annotation(): vc.encode()}),
            op="victimcost.patch")
        return vc

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.publish_once()
                except Exception:  # noqa: BLE001 — advisory signal;
                    # the annotation timestamp ages silence out
                    log.warning("victim-cost publish failed",
                                exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtpu-victimcost")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
