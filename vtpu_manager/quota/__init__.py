"""vtqm: the elastic quota market (QuotaMarket gate, default off).

The reference enforces a *static* split of each chip; vtqm lets a
chip's measured-idle TensorCore % flow between co-tenants with instant
shim-side reclaim:

- workload classes (``latency-critical`` vs ``throughput``) are
  normalized by the webhook into one pod annotation and stamped by the
  device plugin into the v3 config ABI;
- :mod:`ledger` is the node-local FileLock'd lease record whose epoch
  drives the C++ shim's config re-read;
- :mod:`market` is the device-plugin daemon granting/revoking bounded
  TTL'd leases against the vtuse reclaimable-headroom measurement;
- the scheduler's headroom score input (observe-only since PR 8)
  becomes a REAL term for latency-critical pods
  (utilization/headroom.py's ``headroom_score_term``), validated by
  replaying recorded decisions (scripts/vtpu_replay.py).

Gate off = byte-identical: no annotation stamped, no ledger file, no
score change, configs carry the zero bytes the pre-v3 layout carried.
"""

from __future__ import annotations

from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.quota.ledger import (QuotaLeaseLedger, STATE_EXPIRED,
                                       STATE_GRANTED, STATE_REVOKED,
                                       lease_is_active)
from vtpu_manager.quota.market import (CLASS_TO_ABI, QuotaMarketManager,
                                       effective_core,
                                       sum_effective_by_chip)
from vtpu_manager.util import consts

__all__ = [
    "QuotaLeaseLedger", "QuotaMarketManager", "CLASS_TO_ABI",
    "STATE_GRANTED", "STATE_REVOKED", "STATE_EXPIRED",
    "lease_is_active", "effective_core", "sum_effective_by_chip",
    "workload_class_of", "workload_class_abi", "parse_lease_summary",
]

# a lease-summary annotation older than this reads as no-signal (the
# pressure/headroom staleness rule)
MAX_LEASE_SUMMARY_AGE_S = 120.0


def workload_class_of(pod: dict) -> str:
    """The pod's normalized workload class ("" = unclassified). Reads
    ONLY the webhook-stamped annotation — hot paths never parse
    container specs (the program-fingerprint rule), and an un-admitted
    value that skipped normalization is ignored rather than trusted."""
    anns = (pod.get("metadata") or {}).get("annotations") or {}
    raw = anns.get(consts.workload_class_annotation(), "")
    return raw if raw in consts.WORKLOAD_CLASSES else ""


def workload_class_abi(cls: str) -> int:
    """Annotation value -> config ABI value (0 for unclassified)."""
    return CLASS_TO_ABI.get(cls, vc.WORKLOAD_CLASS_NONE)


def parse_lease_summary(raw: str | None, now: float | None = None,
                        max_age_s: float = MAX_LEASE_SUMMARY_AGE_S
                        ) -> dict[int, dict] | None:
    """Decode the node lease-summary annotation
    (``chip:lent:count;…@ts``, market.encode_annotation) into
    ``{chip: {"lent_core_pct": int, "leases": int}}``; None when
    absent, malformed, or stale — every bad shape degrades to
    no-signal, never to a wrong lent/borrowed claim."""
    from vtpu_manager.util import stalecodec
    split = stalecodec.split_stamp(raw)
    if split is None:
        return None
    body, ts = split
    if not stalecodec.is_fresh(ts, now, max_age_s):
        return None
    out: dict[int, dict] = {}
    for seg in body.split(";"):
        if not seg:
            continue
        parts = seg.split(":")
        if len(parts) != 3:
            return None
        try:
            chip, lent, count = int(parts[0]), int(parts[1]), \
                int(parts[2])
        except (TypeError, ValueError):
            return None
        out[chip] = {"lent_core_pct": max(lent, 0),
                     "leases": max(count, 0)}
    return out
