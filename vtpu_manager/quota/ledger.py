"""vtqm node-local quota-lease ledger.

The durable record of who lent what to whom on this node's chips — the
vtcc-lease discipline applied to quota: one JSON file under the node's
base dir, every mutation under a :class:`FileLock` on a sibling
``.flock`` (so the market manager, a restarted market manager, and any
diagnostic reader exclude each other), landed atomically via
tmp+rename. The file carries one monotone ``epoch`` bumped on EVERY
mutation; the market manager writes that epoch into each affected
tenant's ``vtpu.config`` header, which is the C++ shim's re-read
trigger (instant reclaim).

Liveness/crash rules (what the chaos harness asserts):

- every lease carries a wall-clock TTL; a ``granted`` lease past
  ``granted_at + ttl_s`` is *due* and the next manager pass expires it
  — a manager that crashes holding grants leaves only TTL-bounded
  over-grants, never immortal ones;
- a torn/garbage ledger file (partial-write crash) loads as EMPTY with
  a bumped epoch, never as a parse error: the reconcile pass then
  rewrites every config back to base rates — convergence beats
  recovering half a ledger;
- on manager start every ``granted`` lease is revoked (the
  restart-mid-revoke window means their enforcement state is unknown),
  so the market always restarts from base truth.
"""

from __future__ import annotations

import json
import logging
import os
import time

from vtpu_manager.util.flock import FileLock

log = logging.getLogger(__name__)

LEDGER_NAME = "quota_leases.json"

STATE_GRANTED = "granted"
STATE_REVOKED = "revoked"
STATE_EXPIRED = "expired"


def lease_is_active(lease: dict, now: float) -> bool:
    """Granted and inside its TTL. Both settle paths (revoke, expire)
    and the due-scan share this one predicate."""
    if lease.get("state") != STATE_GRANTED:
        return False
    return now < float(lease.get("granted_at", 0.0)) + \
        float(lease.get("ttl_s", 0.0))


class QuotaLeaseLedger:
    """FileLock'd, atomically-rewritten node lease file."""

    def __init__(self, base_dir: str, clock=time.time):
        self.path = os.path.join(base_dir, LEDGER_NAME)
        self.clock = clock

    def exists(self) -> bool:
        return os.path.exists(self.path)

    # -- raw document --------------------------------------------------------

    def load(self) -> dict:
        """The ledger document; a missing or torn file reads as empty.
        The caller that observes ``recovered=True`` must treat every
        on-disk config's lease state as unknown and reconcile to base
        (market.py's recovery rule). A RECOVERED epoch is re-based on
        wall seconds, not reset to 0: the shim skips config re-reads
        whose ``quota_epoch`` equals the one it last adopted, so a
        post-tear generation must never be able to reuse a pre-tear
        epoch value (mutation counts live nowhere near wall seconds)."""
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return {"epoch": 0, "leases": []}
        except (OSError, json.JSONDecodeError, ValueError):
            log.warning("quota ledger %s unreadable (torn write?); "
                        "recovering as empty", self.path)
            return {"epoch": self._recovery_epoch(), "leases": [],
                    "recovered": True}
        if not isinstance(doc, dict) or \
                not isinstance(doc.get("leases"), list):
            log.warning("quota ledger %s has a foreign shape; "
                        "recovering as empty", self.path)
            return {"epoch": self._recovery_epoch(), "leases": [],
                    "recovered": True}
        doc.setdefault("epoch", 0)
        return doc

    def _recovery_epoch(self) -> int:
        return int(self.clock()) & 0x7FFFFFFF

    def _store(self, doc: dict) -> None:
        doc.pop("recovered", None)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- mutations (each one locked round-trip, epoch bumped) ---------------

    def grant(self, chip: int, lender: str, borrower: str, pct: int,
              ttl_s: float, now: float | None = None) -> tuple[dict, int]:
        """Append one granted lease; returns (lease, new epoch)."""
        now = self.clock() if now is None else now
        with FileLock(f"{self.path}.flock"):
            doc = self.load()
            doc["epoch"] = int(doc["epoch"]) + 1
            lease = {
                "id": f"q{doc['epoch']}-{chip}-{len(doc['leases'])}",
                "chip": int(chip),
                "lender": lender,
                "borrower": borrower,
                "pct": int(pct),
                "granted_at": now,
                "ttl_s": float(ttl_s),
                "state": STATE_GRANTED,
                "updated_at": now,
                "epoch": doc["epoch"],
            }
            doc["leases"].append(lease)
            self._store(doc)
            return lease, doc["epoch"]

    def settle(self, lease_ids, state: str,
               now: float | None = None) -> int:
        """Mark leases revoked/expired; returns the new epoch (bumped
        once even for a batch — one epoch per ledger mutation is what
        the shim's re-read keys on, not per lease)."""
        assert state in (STATE_REVOKED, STATE_EXPIRED), state
        ids = set(lease_ids)
        now = self.clock() if now is None else now
        with FileLock(f"{self.path}.flock"):
            doc = self.load()
            touched = False
            for lease in doc["leases"]:
                if lease.get("id") in ids and \
                        lease.get("state") == STATE_GRANTED:
                    lease["state"] = state
                    lease["updated_at"] = now
                    touched = True
            if touched or doc.get("recovered"):
                doc["epoch"] = int(doc["epoch"]) + 1
            self._store(doc)
            return doc["epoch"]

    def compact(self, retain_s: float = 3600.0,
                now: float | None = None) -> None:
        """Drop settled leases older than the retention window so the
        file stays bounded; never drops granted ones."""
        now = self.clock() if now is None else now
        with FileLock(f"{self.path}.flock"):
            doc = self.load()
            kept = [l for l in doc["leases"]
                    if l.get("state") == STATE_GRANTED
                    or now - float(l.get("updated_at", 0.0)) < retain_s]
            if len(kept) != len(doc["leases"]):
                doc["leases"] = kept
                self._store(doc)

    # -- read-side cuts (no lock: a torn read is a stale read, and
    # every caller re-reads next pass) --------------------------------------

    def epoch(self) -> int:
        return int(self.load()["epoch"])

    def leases(self) -> list[dict]:
        return list(self.load()["leases"])

    def snapshot(self, now: float | None = None) -> "LedgerView":
        """Epoch, leases, active set, and deltas derived from ONE load
        — a market-pass phase must see a single ledger generation, not
        one per accessor (and must not pay one file read per cut)."""
        now = self.clock() if now is None else now
        doc = self.load()
        leases = list(doc["leases"])
        active = [l for l in leases if lease_is_active(l, now)]
        return LedgerView(epoch=int(doc["epoch"]), leases=leases,
                          active=active,
                          deltas=deltas_from(active))

    def active(self, now: float | None = None,
               chip: int | None = None) -> list[dict]:
        now = self.clock() if now is None else now
        return [l for l in self.load()["leases"]
                if lease_is_active(l, now)
                and (chip is None or l.get("chip") == chip)]

    def due(self, now: float | None = None) -> list[dict]:
        """Granted leases whose TTL ran out — the expiry work list."""
        now = self.clock() if now is None else now
        return [l for l in self.load()["leases"]
                if l.get("state") == STATE_GRANTED
                and not lease_is_active(l, now)]

    def deltas(self, now: float | None = None
               ) -> dict[tuple[str, int], int]:
        """(tenant, chip) -> net signed lease_core from ACTIVE leases —
        the exact numbers the config rewrite applies, derived in one
        place so the invariant check and the writer cannot disagree."""
        now = self.clock() if now is None else now
        return deltas_from([l for l in self.load()["leases"]
                            if lease_is_active(l, now)])


class LedgerView:
    """One generation of the ledger, read once (snapshot())."""

    __slots__ = ("epoch", "leases", "active", "deltas")

    def __init__(self, epoch: int, leases: list[dict],
                 active: list[dict],
                 deltas: dict[tuple[str, int], int]):
        self.epoch = epoch
        self.leases = leases
        self.active = active
        self.deltas = deltas


def deltas_from(active: list[dict]) -> dict[tuple[str, int], int]:
    out: dict[tuple[str, int], int] = {}
    for lease in active:
        chip = int(lease.get("chip", 0))
        pct = int(lease.get("pct", 0))
        bkey = (lease.get("borrower", ""), chip)
        lkey = (lease.get("lender", ""), chip)
        out[bkey] = out.get(bkey, 0) + pct
        out[lkey] = out.get(lkey, 0) - pct
    return out
