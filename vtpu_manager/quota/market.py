"""vtqm quota-market manager: the node daemon that lends idle quota.

Runs in the device plugin behind the QuotaMarket gate. Each pass:

1. **fold** its own vtuse :class:`UtilizationLedger` (a private
   instance — the headroom publisher keeps its own cursors, so the two
   daemons never race one ledger's state);
2. **expire** granted leases past their TTL;
3. **revoke** leases whose lender needs its quota back (measured
   envelope climbing into the lent range), whose signal went stale
   (confidence below the floor — a lease must never outlive the
   evidence it was granted on), or whose parties' configs vanished;
4. **grant** bounded, TTL'd increments of confidence-gated reclaimable
   headroom from *throughput*-class tenants to throttle-bound
   *latency-critical* tenants on the same chip;
5. **reconcile** every tenant's ``vtpu.config`` to the ledger's active
   deltas — one writer for grant/revoke/expiry/crash-recovery alike:
   desired ``lease_core`` per (tenant, chip) comes from
   :meth:`QuotaLeaseLedger.deltas`, the header ``quota_epoch`` is the
   ledger epoch, and the write is the same atomic tmp+rename the
   Allocate path uses. The C++ shim notices the epoch from its
   token-wait loop and re-reads — that is the instant-reclaim edge;
6. **publish** a compact per-chip lease summary node annotation (the
   /utilization fan-in's remote view) and emit one auditable record
   per grant/revoke/expiry into the vtexplain spool + vtrace timeline.

Safety invariant (chaos-asserted): for every chip, the sum of
``clamp(hard_core + lease_core, 0, 100)`` across resident tenants
never exceeds 100 — a lease moves quota, it never mints it. The
reconcile pass re-derives every delta from the ledger before writing,
and a torn ledger loads as empty, so every crash converges to base
rates within one pass.
"""

from __future__ import annotations

import logging
import threading
import time

from vtpu_manager import explain, trace
from vtpu_manager.config import vtpu_config as vc
from vtpu_manager.config.tenantdirs import iter_container_config_paths
from vtpu_manager.quota.ledger import (QuotaLeaseLedger, STATE_EXPIRED,
                                       STATE_GRANTED, STATE_REVOKED)
from vtpu_manager.resilience import failpoints
from vtpu_manager.util import consts
from vtpu_manager.util import stalecodec

log = logging.getLogger(__name__)

# class annotation value -> config ABI value (the plugin stamps the ABI
# side; the market reads it back from the configs it walks anyway)
CLASS_TO_ABI = {
    consts.WORKLOAD_CLASS_LATENCY_CRITICAL: vc.WORKLOAD_CLASS_LATENCY,
    consts.WORKLOAD_CLASS_THROUGHPUT: vc.WORKLOAD_CLASS_THROUGHPUT,
}

# how close (core %) the lender's measured envelope may come to its
# retained rate before the lease is reclaimed
REVOKE_MARGIN_PCT = 2.0
# extra headroom a grant must leave ABOVE the revoke margin, so a
# fresh lease is never born already inside its own revoke band (the
# grant/revoke hysteresis — without it a lender hovering at the edge
# oscillates lease-on/lease-off every pass)
GRANT_HEADROOM_PCT = 5.0

# vtslo-PR quota satellite (ROADMAP item (d), the feedback leg): the
# borrowed-vs-used verdict thresholds scaling the NEXT grant's step.
# A borrower measurably using >= WELL_USED of what it borrowed earns a
# doubled step (toward max_borrow — the evidence says the demand is
# real); one using < UNUSED gets a halved step AND a halved TTL
# (earlier expiry — borrowed-but-idle quota is exactly what the
# observe-only PR 14 rows exposed). In between, the step holds. The
# grant/revoke hysteresis (GRANT_HEADROOM + lender cooldown) and the
# per-chip <=100% conservation guard are untouched: this scales HOW
# MUCH is offered, never whether offering is safe.
WELL_USED_UTILIZATION = 0.6
UNUSED_UTILIZATION = 0.2


def borrowed_used_verdict(used_pct, base_alloc_pct, borrowed_pct):
    """used-of-borrowed core % — clamp(used - base_alloc, 0, borrowed).

    THE one formula (PR 14's /utilization ``borrowed_used`` rows, the
    ``vtpu_replay.py --utilization-file`` check, and the grant-step
    scaling all call it), so a recorded document replays the market's
    own arithmetic exactly. None = unjudgeable (no live signal)."""
    if used_pct is None or base_alloc_pct is None:
        return None
    borrowed = float(borrowed_pct)
    if borrowed <= 0:
        return None
    return min(max(float(used_pct) - float(base_alloc_pct), 0.0),
               borrowed)


def scaled_grant_step(prev_step: int, base_step: int, max_borrow: int,
                      used_pct, base_alloc_pct, borrowed_pct
                      ) -> tuple[int, float]:
    """(next step pct, ttl factor) from the borrowed-vs-used verdict —
    pure, so recorded ledgers + utilization documents replay it.
    ``prev_step`` is the borrower's current step (base_step when it has
    no history); no verdict (nothing borrowed / no live signal) resets
    to the base step and full TTL."""
    used_of = borrowed_used_verdict(used_pct, base_alloc_pct,
                                    borrowed_pct)
    if used_of is None:
        return base_step, 1.0
    utilization = used_of / float(borrowed_pct)
    if utilization >= WELL_USED_UTILIZATION:
        return min(max(prev_step * 2, 1), max_borrow), 1.0
    if utilization < UNUSED_UTILIZATION:
        return max(prev_step // 2, 1), 0.5
    return prev_step, 1.0


def effective_core(hard: int, lease: int) -> int:
    """clamp(hard + lease, 0, 100) — the C++ EffectiveCorePct mirror."""
    return max(0, min(100, int(hard) + int(lease)))


def sum_effective_by_chip(base_dir: str) -> dict[int, int]:
    """Per-chip sum of on-disk effective rates — the chaos invariant's
    ground truth, read straight off the configs the shims read."""
    out: dict[int, int] = {}
    for _uid, _label, path, _is_dra in \
            iter_container_config_paths(base_dir):
        try:
            cfg = vc.read_config(path)
        except (OSError, ValueError):
            continue
        for dev in cfg.devices:
            out[dev.host_index] = out.get(dev.host_index, 0) + \
                effective_core(dev.hard_core, dev.lease_core)
    return out


class _Tenant:
    """One (pod_uid, container_label) partition's config view."""

    __slots__ = ("key", "path", "cfg", "by_chip")

    def __init__(self, key: str, path: str, cfg: vc.VtpuConfig):
        self.key = key
        self.path = path
        self.cfg = cfg
        self.by_chip = {d.host_index: d for d in cfg.devices}


class QuotaMarketManager:
    def __init__(self, node_name: str, base_dir: str, util_ledger,
                 client=None, policy=None, interval_s: float = 5.0,
                 grant_step_pct: int = 10, max_borrow_pct: int = 40,
                 lease_ttl_s: float = 30.0, min_retain_pct: int = 5,
                 wait_frac_threshold: float = 0.2,
                 revoke_confidence: float = 0.35,
                 clock=time.time):
        from vtpu_manager.resilience.policy import RetryPolicy
        self.node_name = node_name
        self.base_dir = base_dir
        self.util = util_ledger
        self.client = client
        self.policy = policy or RetryPolicy(max_attempts=3,
                                            deadline_s=10.0)
        self.interval_s = interval_s
        self.grant_step_pct = grant_step_pct
        self.max_borrow_pct = max_borrow_pct
        self.lease_ttl_s = lease_ttl_s
        self.min_retain_pct = min_retain_pct
        self.wait_frac_threshold = wait_frac_threshold
        self.revoke_confidence = revoke_confidence
        self.clock = clock
        self.ledger = QuotaLeaseLedger(base_dir, clock=clock)
        self.grants_total = 0
        self.revokes_total = 0
        self.expiries_total = 0
        self.rewrites_total = 0
        # lender -> no-grants-until wall clock, set on every demand/
        # staleness revoke: the other half of the hysteresis (a revoked
        # lender must re-prove idleness across passes, not within one)
        self._lender_cooldown: dict[str, float] = {}
        self.cooldown_s = 2.0 * interval_s
        # borrower -> evidence-scaled grant step (quota item (d)'s
        # feedback leg): grows toward max_borrow while the borrower
        # measurably uses what it borrows, shrinks (with earlier
        # expiry) while it does not — pruned with tenant churn
        self._borrower_step: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- config view ---------------------------------------------------------

    def _tenants(self) -> dict[str, _Tenant]:
        out: dict[str, _Tenant] = {}
        for uid, label, path, _is_dra in \
                iter_container_config_paths(self.base_dir):
            try:
                cfg = vc.read_config(path)
            except (OSError, ValueError):
                continue     # a writer's crash window; next pass
            out[f"{uid}/{label}"] = _Tenant(f"{uid}/{label}", path, cfg)
        return out

    # -- one pass ------------------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        now = self.clock() if now is None else now
        try:
            self.util.fold(now_wall=now)
        except Exception:  # noqa: BLE001 — a torn fold only costs this
            # pass its freshness; confidence decay converges the market
            log.warning("quota market: utilization fold failed",
                        exc_info=True)
        tenants = self._tenants()
        # revoked lenders whose cooldown has lapsed drop out — the
        # dict must not grow forever over tenant churn
        self._lender_cooldown = {k: t for k, t
                                 in self._lender_cooldown.items()
                                 if t > now}
        # departed borrowers drop their learned step the same way
        self._borrower_step = {k: v for k, v
                               in self._borrower_step.items()
                               if k in tenants}
        self._expire(now)
        # one ledger read per phase (each phase may mutate it): every
        # decision inside a phase sees ONE generation
        self._revoke_stressed(tenants, now,
                              self.ledger.snapshot(now))
        self._grant(tenants, now, self.ledger.snapshot(now))
        self._reconcile(tenants, now)
        self._publish(now)
        # settled-lease retention: the file must not grow forever on a
        # long-lived node (granted leases are never dropped)
        self.ledger.compact(now=now)

    def _expire(self, now: float) -> None:
        due = self.ledger.due(now)
        if not due:
            return
        epoch = self.ledger.settle([l["id"] for l in due],
                                   STATE_EXPIRED, now)
        self.expiries_total += len(due)
        for lease in due:
            self._audit("expire", lease, epoch, now)

    def _revoke(self, leases: list[dict], now: float,
                why: str) -> None:
        if not leases:
            return
        epoch = self.ledger.settle([l["id"] for l in leases],
                                   STATE_REVOKED, now)
        # crash window: the ledger says revoked but no config reflects
        # it yet (partial-write tears the ledger itself) — recovery is
        # the reconcile pass / the restart rule, chaos-asserted
        failpoints.fire("quota.revoke", path=self.ledger.path,
                        count_leases=len(leases), why=why)
        self.revokes_total += len(leases)
        for lease in leases:
            self._audit("revoke", lease, epoch, now, why=why)

    def _revoke_stressed(self, tenants: dict[str, _Tenant],
                         now: float, view) -> None:
        import math
        active = view.active
        if not active:
            return
        states = {(s.pod_uid, s.container, s.host_index): s
                  for s in self.util.tenants()}
        deltas = view.deltas
        to_revoke: dict[str, dict] = {}
        reasons: dict[str, str] = {}
        for lease in active:
            lender, borrower = lease["lender"], lease["borrower"]
            chip = int(lease["chip"])
            lt = tenants.get(lender)
            if lt is None or tenants.get(borrower) is None or \
                    chip not in lt.by_chip:
                to_revoke[lease["id"]] = lease
                reasons[lease["id"]] = "party-gone"
                continue
            uid, _, label = lender.partition("/")
            state = states.get((uid, label, chip))
            conf = state.confidence(now) if state is not None else 0.0
            if state is None or conf <= self.revoke_confidence:
                # the staleness rule: a lease never outlives the
                # evidence it was granted on — no-signal lenders
                # reclaim to the exact pre-market rates
                to_revoke[lease["id"]] = lease
                reasons[lease["id"]] = "stale-signal"
                continue
            envelope = state.used_ewma + 2.0 * math.sqrt(
                max(state.used_var, 0.0))
            retained = effective_core(
                lt.by_chip[chip].hard_core,
                deltas.get((lender, chip), 0))
            if envelope >= retained - REVOKE_MARGIN_PCT:
                to_revoke[lease["id"]] = lease
                reasons[lease["id"]] = "lender-demand"
        for why in set(reasons.values()):
            self._revoke([l for lid, l in to_revoke.items()
                          if reasons[lid] == why], now, why)
        for lid, lease in to_revoke.items():
            # hysteresis applies to the lender's OWN signal problems
            # (demand, staleness) — a counterparty vanishing says
            # nothing about the lender's idleness
            if reasons[lid] != "party-gone":
                self._lender_cooldown[lease["lender"]] = \
                    now + self.cooldown_s

    def _grant(self, tenants: dict[str, _Tenant], now: float,
               view) -> None:
        states = {(s.pod_uid, s.container, s.host_index): s
                  for s in self.util.tenants()}
        deltas = view.deltas
        # quota item (d): the borrowed-vs-used step is scaled AT MOST
        # ONCE per borrower per tick — a multi-chip borrower must not
        # compound the doubling/halving once per chip it sits on
        tick_step: dict[str, tuple[int, float]] = {}

        def tenant_state(key: str, chip: int):
            uid, _, label = key.partition("/")
            return states.get((uid, label, chip))

        # chip -> tenants resident on it
        by_chip: dict[int, list[_Tenant]] = {}
        for t in tenants.values():
            for chip in t.by_chip:
                by_chip.setdefault(chip, []).append(t)

        for chip, residents in sorted(by_chip.items()):
            borrowers = []
            lenders = []
            for t in residents:
                dev = t.by_chip[chip]
                state = tenant_state(t.key, chip)
                delta = deltas.get((t.key, chip), 0)
                cls = t.cfg.workload_class
                if cls == vc.WORKLOAD_CLASS_LATENCY:
                    if state is None or state.confidence(now) <= 0.0:
                        continue    # no fresh evidence of the stall
                    if state.wait_frac < self.wait_frac_threshold:
                        continue    # not throttle-bound
                    if dev.core_limit == vc.CORE_LIMIT_NONE:
                        continue    # unthrottled: nothing to lend it
                    room = min(100 - effective_core(dev.hard_core,
                                                    delta),
                               self.max_borrow_pct - max(delta, 0))
                    if room > 0:
                        borrowers.append((t, dev, state, room, delta))
                elif cls == vc.WORKLOAD_CLASS_THROUGHPUT:
                    if state is None:
                        continue
                    if now < self._lender_cooldown.get(t.key, 0.0):
                        continue    # recently reclaimed: re-prove idle
                    lent = max(-delta, 0)
                    # GRANT_HEADROOM keeps a new lease outside its own
                    # revoke band (reclaim already subtracts the
                    # envelope, so this is margin on top of margin)
                    lendable = min(
                        state.reclaim_core_pct(now) - lent
                        - GRANT_HEADROOM_PCT,
                        dev.hard_core - lent - self.min_retain_pct)
                    if lendable >= 1.0:
                        lenders.append((t, dev, state, lendable))
            if not borrowers or not lenders:
                continue
            # most-stalled borrower first; most-idle lender first
            borrowers.sort(key=lambda b: -b[2].wait_frac)
            lenders.sort(key=lambda l: -l[3])
            for bt, bdev, bstate, room, delta in borrowers:
                # quota item (d) feedback: the borrower's NEXT step is
                # scaled by whether it measurably used what it already
                # borrowed (THE shared formula — replayable from a
                # recorded ledger + utilization document). Unused
                # borrowers also get a halved TTL: idle borrowed quota
                # expires back to its lender sooner.
                if bt.key not in tick_step:
                    tick_step[bt.key] = scaled_grant_step(
                        self._borrower_step.get(bt.key,
                                                self.grant_step_pct),
                        self.grant_step_pct, self.max_borrow_pct,
                        bstate.used_ewma
                        if bstate.confidence(now) > 0 else None,
                        bdev.hard_core, max(delta, 0))
                    self._borrower_step[bt.key] = tick_step[bt.key][0]
                step, ttl_factor = tick_step[bt.key]
                ttl_s = self.lease_ttl_s * ttl_factor
                for i, (lt, ldev, lstate, lendable) in \
                        enumerate(lenders):
                    pct = int(min(step, room, lendable))
                    if pct < 1:
                        continue
                    lease, epoch = self.ledger.grant(
                        chip, lt.key, bt.key, pct, ttl_s,
                        now)
                    # crash window: granted in the ledger, not yet in
                    # any config (partial-write tears the ledger); the
                    # reconcile/restart rules converge it
                    failpoints.fire("quota.lease",
                                    path=self.ledger.path,
                                    lease_id=lease["id"], chip=chip)
                    self.grants_total += 1
                    self._audit("grant", lease, epoch, now)
                    lenders[i] = (lt, ldev, lstate, lendable - pct)
                    room -= pct
                    if room < 1:
                        break

    def _reconcile(self, tenants: dict[str, _Tenant],
                   now: float) -> None:
        """Write the ledger's active deltas into the configs — the ONE
        writer for every path (grant, revoke, expiry, torn-ledger
        recovery, restart). Guards the conservation invariant before
        touching disk: if the desired state would oversubscribe a chip
        (a corrupt ledger), every lease on that chip is revoked and the
        pass re-runs against the settled ledger."""
        view = self.ledger.snapshot(now)
        desired_sum: dict[int, int] = {}
        for t in tenants.values():
            for chip, dev in t.by_chip.items():
                desired_sum[chip] = desired_sum.get(chip, 0) + \
                    effective_core(dev.hard_core,
                                   view.deltas.get((t.key, chip), 0))
        bad = [chip for chip, total in desired_sum.items()
               if total > 100]
        if bad:
            log.error("quota ledger would oversubscribe chip(s) %s; "
                      "revoking every lease there", bad)
            victims = [l for l in view.active
                       if l.get("chip") in bad]
            self._revoke(victims, now, "oversubscribed-ledger")
            view = self.ledger.snapshot(now)
        # deltas AND epoch from the same load: a config must never
        # carry one generation's epoch with another's lease values
        deltas = view.deltas
        epoch = view.epoch
        for t in tenants.values():
            want = {chip: deltas.get((t.key, chip), 0)
                    for chip in t.by_chip}
            if all(dev.lease_core == want[chip]
                   for chip, dev in t.by_chip.items()):
                continue
            for chip, dev in t.by_chip.items():
                dev.lease_core = want[chip]
            t.cfg.quota_epoch = epoch
            try:
                vc.write_config(t.path, t.cfg)
                self.rewrites_total += 1
            except OSError:
                # next pass retries; the shim keeps the old rates until
                # a coherent file lands (rename is atomic)
                log.warning("quota config rewrite failed for %s",
                            t.path, exc_info=True)

    # -- audit + publication -------------------------------------------------

    def _audit(self, op: str, lease: dict, epoch: int, now: float,
               why: str = "") -> None:
        rec = {"kind": "quota", "op": op, "node": self.node_name,
               "lease_id": lease.get("id"), "chip": lease.get("chip"),
               "lender": lease.get("lender"),
               "borrower": lease.get("borrower"),
               "pct": lease.get("pct"), "ttl_s": lease.get("ttl_s"),
               "epoch": epoch, "ts": now}
        if why:
            rec["why"] = why
        explain.record_raw(rec)
        for party, role in ((lease.get("borrower", ""), "borrower"),
                            (lease.get("lender", ""), "lender")):
            uid = party.partition("/")[0]
            if uid:
                trace.event(trace.context_for_uid(uid), f"quota.{op}",
                            role=role, chip=lease.get("chip"),
                            pct=lease.get("pct"), epoch=epoch,
                            **({"why": why} if why else {}))

    def encode_annotation(self, now: float) -> str:
        """Compact per-chip lease summary: ``chip:lent:count;…@ts`` —
        the pressure/headroom codec family (stale by timestamp)."""
        per_chip: dict[int, tuple[int, int]] = {}
        for lease in self.ledger.active(now):
            chip = int(lease["chip"])
            lent, count = per_chip.get(chip, (0, 0))
            per_chip[chip] = (lent + int(lease["pct"]), count + 1)
        body = ";".join(f"{chip}:{lent}:{count}"
                        for chip, (lent, count)
                        in sorted(per_chip.items()))
        return stalecodec.stamp(body, now)

    def _publish(self, now: float) -> None:
        if self.client is None:
            return
        try:
            self.policy.run(
                lambda: self.client.patch_node_annotations(
                    self.node_name,
                    {consts.node_quota_lease_annotation():
                     self.encode_annotation(now)}),
                op="quota.lease_patch")
        except Exception:  # noqa: BLE001 — advisory view; the codec's
            # timestamp ages a silent publisher out on every reader
            log.warning("quota lease annotation publish failed",
                        exc_info=True)

    # -- lifecycle -----------------------------------------------------------

    def recover(self) -> None:
        """The restart rule: a granted lease's enforcement state is
        unknown after a crash (we may have died mid-revoke, or between
        the ledger write and any config rewrite) — settle every carried
        lease and reconcile, so the market always restarts from base
        truth. Chaos drives this directly; start() runs it before the
        first pass."""
        now = self.clock()
        # EVERY still-granted lease settles — active ones AND ones
        # whose TTL ran out while no manager lived (they must not
        # linger "granted" forever just because nothing expired them)
        stale = [l for l in self.ledger.leases()
                 if l.get("state") == STATE_GRANTED]
        if stale:
            log.info("quota market restart: revoking %d carried "
                     "lease(s)", len(stale))
        self._revoke(stale, now, "manager-restart")
        self._reconcile(self._tenants(), now)

    def start(self) -> None:
        self.recover()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — one torn pass must
                    # not kill the market; TTLs bound any half-state
                    log.warning("quota market pass failed",
                                exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtqm-market")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
