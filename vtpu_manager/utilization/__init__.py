"""vtuse: per-tenant utilization ledger + reclaimable-headroom accounting.

The measurement substrate for the elastic-quota market and HBM
oversubscription (ROADMAP): ledger.py folds step rings + vtpu.config +
the duty feed into per-tenant allocated-vs-used records with
EWMA-smoothed, burstiness-discounted reclaimable headroom per chip;
headroom.py is the parse-cheap node-annotation codec (the scheduler's
observe-only score input this PR); rollup.py joins the node ledgers
into the monitor's /utilization cluster view that scripts/vtpu_smi.py
renders. Everything is behind the UtilizationLedger gate, default off
= byte-identical.
"""

from vtpu_manager.utilization.headroom import (ChipHeadroom, NodeHeadroom,
                                               headroom_score_input,
                                               parse_headroom)
from vtpu_manager.utilization.ledger import (HeadroomPublisher,
                                             UtilizationLedger,
                                             utilization_stats_for_pod)

__all__ = [
    "ChipHeadroom", "NodeHeadroom", "parse_headroom",
    "headroom_score_input", "UtilizationLedger", "HeadroomPublisher",
    "utilization_stats_for_pod",
]
