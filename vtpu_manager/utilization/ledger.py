"""vtuse per-node utilization ledger: allocated vs actually-used quota.

The measurement substrate the elastic-quota market and the HBM
oversubscription items consume (ROADMAP): today the raw signals exist —
step rings carry per-step duration/throttle-wait/HBM-high-water
(telemetry/stepring.py), the per-container vtpu.config carries the
assignment, and the tc_util feed carries the watcher's measured duty
share — but nothing folds them into an answer to "which chips are
overcommitted on paper but idle in practice, and by how much".

:class:`UtilizationLedger` is that fold, node-local. Per tenant
(pod_uid, container) x chip it maintains a windowed record of

    allocated_core_pct / used_core_pct / allocated_hbm /
    hbm_highwater / throttle_wait_frac

where ``used_core_pct`` prefers the tc_util watcher's measured duty
share and falls back to the ring-derived busy fraction
((duration - throttle_wait) / wall window), EWMA-smoothed with an EWMA
variance alongside. **Reclaimable headroom** per chip is

    sum over fresh tenants of
        max(0, allocated - (used_ewma + K * sigma)) * confidence

— the burstiness discount: a spiky tenant's effective use is its upper
envelope, not its mean, so its quota is never reported as reclaimable
just because it idles between bursts. HBM reclaim uses the lifetime
high-water directly (the high-water IS the burst envelope).

Staleness is explicit, the pressure-codec rule: every tenant carries a
confidence in [0, 1] that decays linearly to 0 over the staleness
budget after its last sample, and a no-signal tenant contributes ZERO
reclaimable — a dead publisher must decay to "don't know", never keep
serving its last claim (the quota market would lend against it).

The fold is **time-boxed**: ``fold(budget_s=...)`` processes rings
round-robin from where the previous fold stopped and charges rings it
could not reach to a dropped-fold counter, so a node with hundreds of
rings can never stall the monitor's scrape path — staleness accounting
(not blocking) absorbs the lag.
"""

from __future__ import annotations

import logging
import math
import os
import time

from vtpu_manager.resilience import failpoints
from vtpu_manager.telemetry import stepring
from vtpu_manager.util import consts
from vtpu_manager.utilization.headroom import ChipHeadroom, NodeHeadroom

log = logging.getLogger(__name__)

# EWMA smoothing for the used-core samples; ~past 3-4 windows dominate
EWMA_ALPHA = 0.3
# burstiness discount: reclaimable is judged against mean + K * sigma
BURST_SIGMA_K = 2.0
# a tenant with no sample for this long reads as no-signal (confidence
# 0); linear decay in between so one missed scrape doesn't zero it
STALENESS_S = 120.0

ALLOC_CORE = "vtpu_utilization_allocated_core_percent"
USED_CORE = "vtpu_utilization_used_core_percent"
ALLOC_HBM = "vtpu_utilization_allocated_hbm_bytes"
HBM_HW = "vtpu_utilization_hbm_highwater_bytes"
WAIT_FRAC = "vtpu_utilization_throttle_wait_fraction"
CONFIDENCE = "vtpu_utilization_confidence"
RECLAIM_CORE = "vtpu_reclaimable_headroom_core_percent"
RECLAIM_HBM = "vtpu_reclaimable_headroom_hbm_bytes"
RECLAIM_CONF = "vtpu_reclaimable_headroom_confidence"
FOLDS_DROPPED = "vtpu_utilization_folds_dropped_total"
FOLD_SECONDS = "vtpu_utilization_fold_seconds"


class _CommStat:
    """vtcomm per-ring measured-communication EWMA state.

    Built ONLY from records whose comm block is non-zero: a gate-off
    (or pre-arm) ring writes zeroed pad there, and reading those zeros
    as "measured zero communication" would flip the link-load
    publisher's weight chain on nodes where nothing is measured — the
    gate-off byte-identical contract. No comm bytes on the wire means
    no signal, never a zero claim."""

    __slots__ = ("duty_ewma", "bytes_per_step_ewma", "collectives_total",
                 "samples", "last_sample_wall")

    def __init__(self) -> None:
        self.duty_ewma = 0.0          # comm seconds per wall second
        self.bytes_per_step_ewma = 0.0
        self.collectives_total = 0
        self.samples = 0
        self.last_sample_wall = 0.0

    def observe(self, duty_frac: float, bytes_per_step: float,
                collectives: int, now_wall: float) -> None:
        duty_frac = min(max(duty_frac, 0.0), 1.0)
        if self.samples == 0:
            # seed with the first sample (the observe_used rule): a 0
            # start would understate a steady communicator for the
            # whole warm-up — the wrong direction for a signal the
            # scheduler steers contention away from
            self.duty_ewma = duty_frac
            self.bytes_per_step_ewma = bytes_per_step
        else:
            self.duty_ewma += EWMA_ALPHA * (duty_frac - self.duty_ewma)
            self.bytes_per_step_ewma += EWMA_ALPHA * (
                bytes_per_step - self.bytes_per_step_ewma)
        self.collectives_total += collectives
        self.samples += 1
        self.last_sample_wall = now_wall

    def confidence(self, now_wall: float) -> float:
        """1 fresh -> 0 no-signal, linear over the staleness budget —
        the _TenantChip rule, so a dead writer's last comm claim decays
        back to the duty-weighted behavior byte-for-byte."""
        if not self.samples or not self.last_sample_wall:
            return 0.0
        age = now_wall - self.last_sample_wall
        if age < 0:
            return 1.0
        return max(0.0, 1.0 - age / STALENESS_S)


class _TenantChip:
    """EWMA state for one (pod_uid, container) x chip partition."""

    __slots__ = ("pod_uid", "container", "pod_name", "pod_namespace",
                 "trace_id", "host_index", "uuid", "alloc_core_pct",
                 "alloc_hbm", "used_ewma", "used_var", "wait_frac",
                 "hbm_highwater", "last_sample_wall", "samples",
                 "workload_class")

    def __init__(self, pod_uid: str, container: str, host_index: int,
                 uuid: str):
        self.pod_uid = pod_uid
        self.container = container
        self.pod_name = ""
        self.pod_namespace = ""
        self.trace_id = ""
        self.host_index = host_index
        self.uuid = uuid
        self.alloc_core_pct = 0.0
        self.alloc_hbm = 0
        self.used_ewma = 0.0
        self.used_var = 0.0
        self.wait_frac = 0.0
        self.hbm_highwater = 0
        self.last_sample_wall = 0.0
        self.samples = 0
        # vtqm ABI class (vtovc reads it): WORKLOAD_CLASS_* int from the
        # tenant's config — keys the overcommit policy's per-class
        # ratios and the headroom annotation's class mix
        self.workload_class = 0

    def observe_used(self, used_pct: float, now_wall: float) -> None:
        used_pct = min(max(used_pct, 0.0), 100.0)
        if self.samples == 0:
            # seed with the first sample: starting the EWMA at 0 would
            # report a steady tenant as reclaimable for the warm-up
            # windows — exactly the wrong failure mode for a signal
            # quota lending trusts
            self.used_ewma = used_pct
            self.used_var = 0.0
        else:
            delta = used_pct - self.used_ewma
            self.used_ewma += EWMA_ALPHA * delta
            self.used_var = ((1.0 - EWMA_ALPHA) * self.used_var
                             + EWMA_ALPHA * delta * delta)
        self.samples += 1
        self.last_sample_wall = now_wall

    def confidence(self, now_wall: float) -> float:
        """1 fresh -> 0 no-signal, linear over the staleness budget.
        Never-sampled tenants are 0 by construction (allocated-but-
        never-observed quota is unknown, not reclaimable)."""
        if not self.samples or not self.last_sample_wall:
            return 0.0
        age = now_wall - self.last_sample_wall
        if age < 0:
            return 1.0      # clock step backwards: fresh, not garbage
        return max(0.0, 1.0 - age / STALENESS_S)

    def reclaim_core_pct(self, now_wall: float) -> float:
        conf = self.confidence(now_wall)
        if conf <= 0.0:
            return 0.0
        envelope = self.used_ewma + BURST_SIGMA_K * math.sqrt(
            max(self.used_var, 0.0))
        return max(0.0, self.alloc_core_pct - envelope) * conf

    def reclaim_hbm_bytes(self, now_wall: float) -> int:
        conf = self.confidence(now_wall)
        if conf <= 0.0:
            return 0
        return int(max(0, self.alloc_hbm - self.hbm_highwater) * conf)

    def to_wire(self, now_wall: float) -> dict:
        return {
            "pod_uid": self.pod_uid,
            "container": self.container,
            "pod_name": self.pod_name,
            "pod_namespace": self.pod_namespace,
            "trace_id": self.trace_id,
            "chip_index": self.host_index,
            "chip_uuid": self.uuid,
            "allocated_core_pct": round(self.alloc_core_pct, 2),
            "used_core_pct": round(self.used_ewma, 2),
            "allocated_hbm_bytes": self.alloc_hbm,
            "hbm_highwater_bytes": self.hbm_highwater,
            "throttle_wait_frac": round(self.wait_frac, 4),
            "reclaimable_core_pct": round(
                self.reclaim_core_pct(now_wall), 2),
            "reclaimable_hbm_bytes": self.reclaim_hbm_bytes(now_wall),
            "confidence": round(self.confidence(now_wall), 3),
            "stale": self.confidence(now_wall) <= 0.0,
        }


class _RingCursor:
    __slots__ = ("cursor", "last_poll_monotonic")

    def __init__(self) -> None:
        self.cursor = 0
        # None = never polled (the priming state) — NOT 0.0, which is a
        # legitimate monotonic stamp under an injected test clock
        self.last_poll_monotonic: float | None = None


class UtilizationLedger:
    """Per-node accountant folding rings + configs + the duty feed."""

    def __init__(self, node_name: str, chips: list,
                 base_dir: str = consts.MANAGER_BASE_DIR,
                 tc_path: str | None = None):
        self.node_name = node_name
        self.chips = list(chips)
        self.base_dir = base_dir
        self.tc_path = tc_path
        self._states: dict[tuple[str, str, int], _TenantChip] = {}
        self._cursors: dict[tuple[str, str], _RingCursor] = {}
        # ring-fold round-robin resume point (budget continuation)
        self._resume = 0
        self.folds_dropped_total = 0
        self.last_fold_s = 0.0
        self.last_fold_wall = 0.0
        # vtovc: per-ring spill activity from the v2 step records —
        # (steps, spilling_steps, spilled_bytes_gauge, wall_ts); the
        # node spill signal the overcommit policy publishes — plus the
        # cumulative event counters the collector's vtpu_node_spill_*
        # series export
        self._ring_spill: dict[tuple[str, str],
                               tuple[int, int, int, float]] = {}
        self.spill_events_total = 0
        self.fill_events_total = 0
        # vtcomm: per-ring measured-communication EWMA off the v3 comm
        # block — the measured comm-intensity feed the link-load
        # publisher prefers over the compute-duty heuristic
        self._ring_comm: dict[tuple[str, str], _CommStat] = {}
        self.comm_bytes_total = 0
        self.collectives_total = 0

    # -- discovery (same dir shapes as the collector's config join) ---------

    def _configs(self) -> list[tuple[str, str, object]]:
        """(pod_uid, container_label, VtpuConfig) — device-plugin AND
        DRA tenants via the ONE shared walk (config/tenantdirs.py):
        the owner-token join with the duty feed only matches the
        collector's if both produce identical labels."""
        from vtpu_manager.config.tenantdirs import iter_container_configs
        return [(pod_uid, label, cfg)
                for pod_uid, label, cfg, _is_dra, _mtime
                in iter_container_configs(self.base_dir)]

    def _tc_util_by_token(self) -> dict[tuple[int, int], int]:
        """(owner_token, chip_index) -> measured duty % from the node
        watcher feed; empty when the feed is absent (normal on nodes
        without TCWatcher) or unreadable (the tenant falls back to the
        ring-derived busy fraction)."""
        out: dict[tuple[int, int], int] = {}
        if not self.tc_path:
            return out
        try:
            from vtpu_manager.config.tc_watcher import TcUtilFile
            tc = TcUtilFile(self.tc_path)
            try:
                for chip in self.chips:
                    rec = tc.read_device(chip.index)
                    if rec is None:
                        continue
                    for proc in rec.procs:
                        key = (proc.owner_token, chip.index)
                        out[key] = out.get(key, 0) + proc.util
            finally:
                tc.close()
        except (OSError, ValueError):
            return {}
        return out

    # -- the fold ------------------------------------------------------------

    def fold(self, budget_s: float | None = None,
             now_mono: float | None = None,
             now_wall: float | None = None) -> int:
        """One accounting pass: re-read configs (allocation truth),
        tail each tenant's ring for a window sample, fold the duty feed.
        Returns how many EXISTING rings could not be read (the feed's
        last-scrape-error signal). Budget overruns drop ring folds (the
        counter) and resume round-robin next pass — never block."""
        failpoints.fire("util.fold", node=self.node_name)
        t0 = time.perf_counter()
        now_mono = time.monotonic() if now_mono is None else now_mono
        now_wall = time.time() if now_wall is None else now_wall
        failed = 0

        configs = self._configs()
        live_keys: set[tuple[str, str, int]] = set()
        ring_keys: list[tuple[str, str]] = []
        seen_rings: set[tuple[str, str]] = set()
        from vtpu_manager.config.vmem import fnv64
        token_of: dict[tuple[str, str], int] = {}
        devices_of: dict[tuple[str, str], list] = {}
        for pod_uid, container, cfg in configs:
            tkey = (pod_uid, container)
            token_of[tkey] = fnv64(f"{pod_uid}/{container}")
            devices_of.setdefault(tkey, []).extend(cfg.devices)
            if tkey not in seen_rings:
                seen_rings.add(tkey)
                ring_keys.append(tkey)
            for dev in cfg.devices:
                key = (pod_uid, container, dev.host_index)
                live_keys.add(key)
                state = self._states.get(key)
                if state is None:
                    state = self._states[key] = _TenantChip(
                        pod_uid, container, dev.host_index, dev.uuid)
                state.pod_name = cfg.pod_name
                state.pod_namespace = cfg.pod_namespace
                state.alloc_core_pct = float(dev.hard_core)
                state.alloc_hbm = int(dev.total_memory)
                state.workload_class = int(cfg.workload_class)
        # a removed tenant's rows go with it (same lifecycle as the
        # per-container limit gauges — the reaper owns stale dirs)
        for key in list(self._states):
            if key not in live_keys:
                del self._states[key]
        for tkey in list(self._cursors):
            if tkey not in seen_rings:
                del self._cursors[tkey]
                self._ring_spill.pop(tkey, None)
                self._ring_comm.pop(tkey, None)

        tc_util = self._tc_util_by_token()

        # ring folds, round-robin from the previous budget stop so every
        # ring is eventually reached even when each pass only affords a
        # few — budget exhaustion charges the REMAINDER to the dropped
        # counter rather than stalling the scrape. The budget is checked
        # AFTER each ring (progress floor: at least one ring folds per
        # pass even when the config walk already ate the budget, so a
        # pathological budget degrades to one-ring-per-scrape, never to
        # a ledger frozen at zero)
        n = len(ring_keys)
        folded = 0
        for i in range(n):
            tkey = ring_keys[(self._resume + i) % n]
            folded += 1
            failed += self._fold_ring(tkey, token_of[tkey],
                                      devices_of[tkey], tc_util,
                                      now_mono, now_wall)
            if budget_s is not None and folded < n and \
                    time.perf_counter() - t0 > budget_s:
                self.folds_dropped_total += n - folded
                self._resume = (self._resume + folded) % max(n, 1)
                break
        else:
            self._resume = 0
        self.last_fold_s = time.perf_counter() - t0
        self.last_fold_wall = now_wall
        return failed

    def _fold_ring(self, tkey: tuple[str, str], token: int,
                   devices: list, tc_util: dict,
                   now_mono: float, now_wall: float) -> int:
        pod_uid, container = tkey
        entry = f"{pod_uid}_{container.split('/', 1)[0]}"
        ring_path = os.path.join(self.base_dir, entry,
                                 consts.TELEMETRY_SUBDIR,
                                 consts.STEP_RING_NAME)
        cur = self._cursors.get(tkey)
        if cur is None:
            cur = self._cursors[tkey] = _RingCursor()
        total_alloc = sum(float(d.hard_core) for d in devices) or 1.0
        total_hbm = sum(int(d.total_memory) for d in devices) or 1

        records: list[stepring.StepRecord] = []
        trace_id = ""
        have_ring = os.path.isfile(ring_path)
        if have_ring:
            try:
                reader = stepring.StepRingReader(ring_path)
            except (OSError, ValueError) as e:
                log.warning("utilization: ring %s unreadable: %s",
                            ring_path, e)
                return 1
            try:
                trace_id = reader.trace_id
                records, cursor, _ = reader.poll(cur.cursor)
                cur.cursor = cursor
            finally:
                reader.close()

        if records:
            # vtovc spill signal: steps that paid a tier transition this
            # window + the footprint gauge off the newest record; a ring
            # gone quiet keeps its last value and ages out by wall ts
            spilling = sum(1 for r in records
                           if r.spill_events or r.fill_events)
            self._ring_spill[tkey] = (len(records), spilling,
                                      records[-1].spilled_bytes, now_wall)
            self.spill_events_total += sum(r.spill_events
                                           for r in records)
            self.fill_events_total += sum(r.fill_events for r in records)
        window_s = (now_mono - cur.last_poll_monotonic
                    if cur.last_poll_monotonic is not None else 0.0)
        # vtcomm: a window with ANY non-zero comm block is a measured
        # communication sample; all-zero comm blocks (gate off, pre-arm
        # shim, or a ring older than v3's writer) are NO signal — the
        # publisher must keep its duty-weighted behavior byte-for-byte
        comm_ns = sum(r.comm_time_ns for r in records)
        comm_bytes = sum(r.bytes_transferred for r in records)
        collectives = sum(r.collective_count for r in records)
        if comm_ns or comm_bytes or collectives:
            # lifetime totals accumulate UNCONDITIONALLY: the first
            # fold after a monitor restart has no window (the EWMA
            # below genuinely needs one) but its ring backlog still
            # HAPPENED — dropping it would undercount the movement
            # counters by up to a full ring per restart
            self.comm_bytes_total += comm_bytes
            self.collectives_total += collectives
            if window_s > 0:
                stat = self._ring_comm.get(tkey)
                if stat is None:
                    stat = self._ring_comm[tkey] = _CommStat()
                stat.observe(comm_ns / 1e9 / window_s,
                             comm_bytes / len(records), collectives,
                             now_wall)
        dur_sum = sum(r.duration_ns for r in records) / 1e9
        wait_sum = sum(r.throttle_wait_ns for r in records) / 1e9
        hbm_hw = max((r.hbm_highwater_bytes for r in records), default=0)
        busy_frac = 0.0
        if window_s > 0:
            busy_frac = max(0.0, dur_sum - wait_sum) / window_s
        wait_frac = wait_sum / dur_sum if dur_sum else 0.0

        for dev in devices:
            key = (pod_uid, container, dev.host_index)
            state = self._states.get(key)
            if state is None:
                continue
            state.trace_id = trace_id or state.trace_id
            # measured duty share from the watcher feed wins (it is the
            # chip's own accounting); the ring-derived busy fraction is
            # the fallback, apportioned across the tenant's devices by
            # allocated-core share (the ring is per tenant, not per chip)
            tc_sample = tc_util.get((token, dev.host_index))
            if tc_sample is not None:
                state.observe_used(float(tc_sample), now_wall)
            elif records and window_s > 0:
                share = float(dev.hard_core) / total_alloc
                state.observe_used(100.0 * busy_frac * share, now_wall)
            # an existing ring with no new records in the window is NOT
            # a sample: freshness keeps decaying toward no-signal (a
            # dead writer must never look "steadily idle = reclaimable")
            if records:
                state.wait_frac = wait_frac
                hbm_share = int(dev.total_memory) / total_hbm
                state.hbm_highwater = max(
                    state.hbm_highwater, int(hbm_hw * hbm_share))
        cur.last_poll_monotonic = now_mono
        return 0

    # -- vtovc policy inputs -------------------------------------------------

    _CLASS_KEYS = {1: "lat", 2: "thr"}     # ABI ints -> wire keys

    def hbm_fraction_samples(self, now_wall: float | None = None
                             ) -> dict[str, list[tuple[float, float]]]:
        """Per workload class, (highwater/allocated, confidence) per
        sampled tenant×chip — the overcommit policy's percentile input.
        Confidence carries the staleness decay, so the policy's
        min-confidence gate decays a dark class back to ratio 1.0."""
        now_wall = time.time() if now_wall is None else now_wall
        out: dict[str, list[tuple[float, float]]] = {}
        for s in self._states.values():
            if s.alloc_hbm <= 0 or not s.samples:
                continue
            key = self._CLASS_KEYS.get(s.workload_class, "def")
            out.setdefault(key, []).append(
                (min(s.hbm_highwater / s.alloc_hbm, 1.0),
                 s.confidence(now_wall)))
        return out

    def node_spill_signal(self, now_wall: float | None = None
                          ) -> tuple[float, int]:
        """(spill_frac, spilled_bytes) across the node's rings:
        fraction of recent steps that paid a spill/fill plus the live
        host-pool footprint sum — the thrash signal the scheduler's
        spill-rate pressure term reads. Rings silent past the staleness
        budget drop out (a dead writer must not pin a thrash claim)."""
        now_wall = time.time() if now_wall is None else now_wall
        steps = spilling = spilled = 0
        for n, spill_n, gauge, ts in self._ring_spill.values():
            if now_wall - ts > STALENESS_S:
                continue
            steps += n
            spilling += spill_n
            spilled += gauge
        frac = spilling / steps if steps else 0.0
        return min(max(frac, 0.0), 1.0), spilled

    # -- vtcomm measured comm-intensity feed ---------------------------------

    def comm_signals(self, now_wall: float | None = None
                     ) -> dict[tuple[str, str], tuple[float, float]]:
        """Per tenant ring, (measured comm link-duty EWMA, confidence)
        — the link-load publisher's preferred weight source. Only
        tenants with a live confidence appear: staleness decays a dead
        comm writer out of the map entirely, so the publisher's
        fallback chain lands on today's duty-weighted behavior
        byte-for-byte (the acceptance contract)."""
        now_wall = time.time() if now_wall is None else now_wall
        out: dict[tuple[str, str], tuple[float, float]] = {}
        for tkey, stat in self._ring_comm.items():
            conf = stat.confidence(now_wall)
            if conf <= 0.0:
                continue
            out[tkey] = (stat.duty_ewma, conf)
        return out

    def _compute_duty_of(self, tkey: tuple[str, str]) -> float:
        """The tenant's mean measured compute duty across its chips in
        [0,1] — the denominator of the measured comm-intensity figure
        (comm duty per unit compute duty, the bench's modeled-constant
        replacement)."""
        vals = [s.used_ewma / 100.0 for s in self._states.values()
                if (s.pod_uid, s.container) == tkey and s.samples]
        return sum(vals) / len(vals) if vals else 0.0

    def comm_rows(self, now_wall: float | None = None) -> list[dict]:
        """Per-tenant measured-communication rows for /utilization and
        vtpu-smi (CommTelemetry documents only)."""
        now_wall = time.time() if now_wall is None else now_wall
        rows = []
        for tkey in sorted(self._ring_comm):
            stat = self._ring_comm[tkey]
            conf = stat.confidence(now_wall)
            compute_duty = self._compute_duty_of(tkey)
            rows.append({
                "pod_uid": tkey[0],
                "container": tkey[1],
                # wall-denominated on purpose (comm seconds per wall
                # second — the link-occupancy figure the publisher
                # weighs); the STEP-denominated figure is
                # comm_time_frac in the vtrace splice and the
                # vtpu_tenant_comm_time_fraction gauge — distinct
                # names, distinct denominators
                "comm_duty_frac": round(stat.duty_ewma, 4),
                "comm_bytes_per_step": int(stat.bytes_per_step_ewma),
                "collectives_total": stat.collectives_total,
                # measured comm-intensity: link duty per unit compute
                # duty — the honest replacement for bench_ici's modeled
                # 1.6x constant (None until compute duty is measured)
                "comm_intensity": round(
                    stat.duty_ewma / compute_duty, 3)
                    if compute_duty > 0 else None,
                "confidence": round(conf, 3),
                "stale": conf <= 0.0,
            })
        return rows

    def class_mix(self) -> dict[str, int]:
        """Distinct resident CLASSIFIED tenants per workload-class key
        — the headroom annotation's mix segment (ROADMAP item a: lets a
        later score term prefer nodes with lender-class
        counterparties). Unclassified tenants are deliberately absent:
        they are never market counterparties (they neither lend nor
        borrow), and omitting them keeps the annotation's wire bytes
        unchanged on every deployment that stamps no classes — a
        pre-mix parser rejects the whole rollup on an unknown segment,
        so the mix must only appear where class-aware components (which
        ship with the new codec) are in play."""
        seen: dict[str, set] = {}
        for s in self._states.values():
            key = self._CLASS_KEYS.get(s.workload_class)
            if key is None:
                continue
            seen.setdefault(key, set()).add((s.pod_uid, s.container))
        return {k: len(v) for k, v in seen.items()}

    # -- outputs -------------------------------------------------------------

    def tenants(self) -> list[_TenantChip]:
        return sorted(self._states.values(),
                      key=lambda s: (s.pod_uid, s.container, s.host_index))

    def chip_rollup(self, now_wall: float | None = None
                    ) -> dict[int, dict]:
        """Per-chip aggregation across tenants: the headroom rollup."""
        now_wall = time.time() if now_wall is None else now_wall
        out: dict[int, dict] = {}
        for chip in self.chips:
            out[chip.index] = {
                "index": chip.index, "uuid": chip.uuid,
                "alloc_core_pct": 0.0, "used_core_pct": 0.0,
                "alloc_hbm_bytes": 0, "hbm_highwater_bytes": 0,
                "reclaim_core_pct": 0.0, "reclaim_hbm_bytes": 0,
                "confidence": 1.0, "tenants": 0,
            }
        for s in self._states.values():
            row = out.get(s.host_index)
            if row is None:
                continue        # stale config naming a removed chip
            conf = s.confidence(now_wall)
            row["alloc_core_pct"] += s.alloc_core_pct
            row["used_core_pct"] += s.used_ewma * conf
            row["alloc_hbm_bytes"] += s.alloc_hbm
            row["hbm_highwater_bytes"] += s.hbm_highwater
            row["reclaim_core_pct"] += s.reclaim_core_pct(now_wall)
            row["reclaim_hbm_bytes"] += s.reclaim_hbm_bytes(now_wall)
            row["confidence"] = min(row["confidence"], conf)
            row["tenants"] += 1
        for row in out.values():
            row["used_core_pct"] = round(row["used_core_pct"], 2)
            row["reclaim_core_pct"] = round(row["reclaim_core_pct"], 2)
            row["alloc_core_pct"] = round(row["alloc_core_pct"], 2)
            row["confidence"] = round(row["confidence"], 3)
        return out

    def headroom(self, now_wall: float | None = None) -> NodeHeadroom:
        """The annotation payload (utilization/headroom.py codec)."""
        now_wall = time.time() if now_wall is None else now_wall
        chips = {}
        for idx, row in self.chip_rollup(now_wall).items():
            chips[idx] = ChipHeadroom(
                alloc_core_pct=row["alloc_core_pct"],
                used_core_pct=row["used_core_pct"],
                reclaim_core_pct=row["reclaim_core_pct"],
                reclaim_hbm_bytes=row["reclaim_hbm_bytes"])
        return NodeHeadroom(chips=chips, ts=now_wall,
                            class_mix=self.class_mix())

    def to_wire(self, now_wall: float | None = None) -> dict:
        now_wall = time.time() if now_wall is None else now_wall
        chips = list(self.chip_rollup(now_wall).values())
        return {
            "node": self.node_name,
            "chips": chips,
            "tenants": [s.to_wire(now_wall) for s in self.tenants()],
            "reclaimable_core_pct": round(
                sum(c["reclaim_core_pct"] for c in chips), 2),
            "reclaimable_hbm_bytes": sum(
                c["reclaim_hbm_bytes"] for c in chips),
            "folds_dropped_total": self.folds_dropped_total,
            "last_fold_s": round(self.last_fold_s, 6),
        }

    def render(self, now_wall: float | None = None) -> str:
        """Prometheus text for the monitor's /metrics (gate on only)."""
        now_wall = time.time() if now_wall is None else now_wall
        node = self.node_name
        lines = [
            f"# HELP {ALLOC_CORE} Assigned core percent "
            f"(vtuse ledger view)",
            f"# TYPE {ALLOC_CORE} gauge",
        ]
        tenants = self.tenants()

        def tlabels(s: _TenantChip) -> str:
            return (f'node="{node}",pod_uid="{s.pod_uid}",'
                    f'container="{s.container}",uuid="{s.uuid}"')

        for s in tenants:
            lines.append(f"{ALLOC_CORE}{{{tlabels(s)}}} "
                         f"{s.alloc_core_pct:g}")
        lines += [f"# HELP {USED_CORE} EWMA of the tenant's measured "
                  f"core use on the chip",
                  f"# TYPE {USED_CORE} gauge"]
        for s in tenants:
            lines.append(f"{USED_CORE}{{{tlabels(s)}}} "
                         f"{round(s.used_ewma, 2):g}")
        lines += [f"# HELP {ALLOC_HBM} Assigned HBM cap "
                  f"(vtuse ledger view)",
                  f"# TYPE {ALLOC_HBM} gauge"]
        for s in tenants:
            lines.append(f"{ALLOC_HBM}{{{tlabels(s)}}} {s.alloc_hbm}")
        lines += [f"# HELP {HBM_HW} Step-ring HBM high-water attributed "
                  f"to the tenant's share of the chip",
                  f"# TYPE {HBM_HW} gauge"]
        for s in tenants:
            lines.append(f"{HBM_HW}{{{tlabels(s)}}} {s.hbm_highwater}")
        lines += [f"# HELP {WAIT_FRAC} Fraction of step time stalled in "
                  f"the throttle over the last fold window",
                  f"# TYPE {WAIT_FRAC} gauge"]
        for s in tenants:
            lines.append(f"{WAIT_FRAC}{{{tlabels(s)}}} "
                         f"{round(s.wait_frac, 4):g}")
        lines += [f"# HELP {CONFIDENCE} Sample freshness in [0,1]; 0 = "
                  f"no-signal (dead writer decayed out)",
                  f"# TYPE {CONFIDENCE} gauge"]
        for s in tenants:
            lines.append(f"{CONFIDENCE}{{{tlabels(s)}}} "
                         f"{round(s.confidence(now_wall), 3):g}")

        rollup = self.chip_rollup(now_wall)
        lines += [f"# HELP {RECLAIM_CORE} Allocated-but-unused core % "
                  f"per chip, EWMA + burstiness discounted",
                  f"# TYPE {RECLAIM_CORE} gauge"]
        for idx in sorted(rollup):
            row = rollup[idx]
            lines.append(f'{RECLAIM_CORE}{{node="{node}",'
                         f'uuid="{row["uuid"]}",index="{idx}"}} '
                         f'{row["reclaim_core_pct"]:g}')
        lines += [f"# HELP {RECLAIM_HBM} Allocated-minus-high-water HBM "
                  f"per chip, confidence discounted",
                  f"# TYPE {RECLAIM_HBM} gauge"]
        for idx in sorted(rollup):
            row = rollup[idx]
            lines.append(f'{RECLAIM_HBM}{{node="{node}",'
                         f'uuid="{row["uuid"]}",index="{idx}"}} '
                         f'{row["reclaim_hbm_bytes"]}')
        lines += [f"# HELP {RECLAIM_CONF} Min tenant confidence feeding "
                  f"the chip's reclaim figures (0 = no-signal)",
                  f"# TYPE {RECLAIM_CONF} gauge"]
        for idx in sorted(rollup):
            row = rollup[idx]
            conf = row["confidence"] if row["tenants"] else 0.0
            lines.append(f'{RECLAIM_CONF}{{node="{node}",'
                         f'uuid="{row["uuid"]}",index="{idx}"}} '
                         f'{conf:g}')
        lines += [f"# HELP {FOLDS_DROPPED} Ring folds skipped because "
                  f"the scrape-time budget ran out (resumed next pass)",
                  f"# TYPE {FOLDS_DROPPED} counter",
                  f'{FOLDS_DROPPED}{{node="{node}"}} '
                  f"{self.folds_dropped_total}",
                  f"# HELP {FOLD_SECONDS} Wall time of the last ledger "
                  f"fold",
                  f"# TYPE {FOLD_SECONDS} gauge",
                  f'{FOLD_SECONDS}{{node="{node}"}} '
                  f"{round(self.last_fold_s, 6):g}"]
        return "\n".join(lines) + "\n"


class HeadroomPublisher:
    """Daemon-side loop: fold the ledger, patch the node annotation.

    Runs in the device-plugin daemon (the node-annotation owner) behind
    the UtilizationLedger gate — the same shape as vttel's
    PressurePublisher. Failures are tolerated per tick; the codec's own
    timestamp ages a silent publisher out on the scheduler side."""

    def __init__(self, client, node_name: str, ledger: UtilizationLedger,
                 policy=None, interval_s: float = 15.0):
        import threading
        from vtpu_manager.resilience.policy import RetryPolicy
        self.client = client
        self.node_name = node_name
        self.ledger = ledger
        self.policy = policy or RetryPolicy(max_attempts=3, deadline_s=10.0)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = None

    def publish_once(self) -> NodeHeadroom:
        self.ledger.fold()
        hr = self.ledger.headroom()
        self.policy.run(
            lambda: self.client.patch_node_annotations(
                self.node_name,
                {consts.node_reclaimable_headroom_annotation():
                 hr.encode()}),
            op="utilization.headroom_patch")
        return hr

    def start(self) -> None:
        import threading

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.publish_once()
                except Exception:  # noqa: BLE001 — advisory signal; the
                    # annotation timestamp ages a silent failure out
                    log.warning("headroom publish failed", exc_info=True)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="vtuse-headroom")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


def utilization_stats_for_pod(base_dir: str, *keys: str) -> list[dict]:
    """One pod's used-vs-allocated rows straight off its ring + config —
    the ``vtrace --pod`` splice. ``keys`` may match the config-dir pod
    uid or the ring's trace id (the same join contract as
    telemetry.step_stats_for_pod). Offline one-shot, so the window is
    the resident records' own span (first start to last end), not a
    poll interval: a CLI invocation has no second poll to wait for."""
    from vtpu_manager.config import vtpu_config as vc
    wanted = {k for k in keys if k}
    out: list[dict] = []
    if not wanted or not os.path.isdir(base_dir):
        return out
    for entry in sorted(os.listdir(base_dir)):
        ring_path = os.path.join(base_dir, entry,
                                 consts.TELEMETRY_SUBDIR,
                                 consts.STEP_RING_NAME)
        if not os.path.isfile(ring_path):
            continue
        pod_uid, _, container = entry.partition("_")
        try:
            reader = stepring.StepRingReader(ring_path)
        except (OSError, ValueError):
            continue
        try:
            if not (wanted & {pod_uid, reader.trace_id}):
                continue
            records, _, _ = reader.poll(0)
            trace_id = reader.trace_id
        finally:
            reader.close()
        alloc_core = 0.0
        alloc_hbm = 0
        cfg_path = os.path.join(base_dir, entry, "config", "vtpu.config")
        try:
            cfg = vc.read_config(cfg_path)
            alloc_core = float(sum(d.hard_core for d in cfg.devices))
            alloc_hbm = int(sum(d.total_memory for d in cfg.devices))
        except (OSError, ValueError):
            pass
        dur_sum = sum(r.duration_ns for r in records) / 1e9
        wait_sum = sum(r.throttle_wait_ns for r in records) / 1e9
        span_s = 0.0
        if records:
            first = min(r.start_mono_ns for r in records)
            last = max(r.start_mono_ns + r.duration_ns for r in records)
            span_s = max((last - first) / 1e9, 1e-9)
        used_pct = 100.0 * max(0.0, dur_sum - wait_sum) / span_s \
            if span_s else 0.0
        out.append({
            "pod_uid": pod_uid,
            "container": container,
            "trace_id": trace_id,
            "allocated_core_pct": alloc_core,
            "used_core_pct": round(min(used_pct, 100.0), 2),
            "allocated_hbm_bytes": alloc_hbm,
            "hbm_highwater_bytes": max(
                (r.hbm_highwater_bytes for r in records), default=0),
            "throttle_wait_frac": round(
                wait_sum / dur_sum, 4) if dur_sum else 0.0,
        })
    return out
