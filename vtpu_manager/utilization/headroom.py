"""Reclaimable-headroom annotation: vtuse's feedback edge into the
scheduler — same codec family as the vttel node-pressure annotation.

The node daemon (device_plugin, behind the UtilizationLedger gate)
publishes the ledger's per-chip rollup as a node annotation over the
existing registry channel. Wire format is parse-cheap on purpose (the
snapshot path decodes it per node event, the TTL path per candidate):

    "<idx>:<alloc_core>:<used_core>:<reclaim_core>:<reclaim_hbm>;...@<ts>"

one ``;``-separated segment per chip, core fields in percent of one
chip, HBM in bytes, one wall-clock stamp for the whole rollup. The
timestamp makes staleness explicit — a daemon that stops publishing
must decay to "no signal", never pin its last claim forever (exactly
the pressure-codec rule; a reclaimable-headroom claim that outlives its
publisher is worse than no claim, because the quota market would lend
against it).

This PR the decoded signal is **observe-only**: both scheduler paths
fold it into the candidate state, log the score input it WOULD
contribute in the pod's trace span, and count it on /metrics — but
``headroom_score_input`` never reaches the score. The elastic-quota PR
flips it on against that recorded evidence.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from vtpu_manager.util import stalecodec

# staleness budget: the publisher cadence is seconds; a rollup older
# than this reads as no-signal (same constant family as
# telemetry/pressure.py — kept separate because the quota market may
# want a TIGHTER bound here than the soft pressure penalty needs)
MAX_HEADROOM_AGE_S = 120.0

# re-exported for existing importers; the one copy lives in stalecodec
FUTURE_SKEW_TOLERANCE_S = stalecodec.FUTURE_SKEW_TOLERANCE_S


@dataclass(frozen=True)
class ChipHeadroom:
    alloc_core_pct: float      # sum of assigned core % on the chip
    used_core_pct: float       # EWMA of measured use (fresh tenants only)
    reclaim_core_pct: float    # burstiness-discounted reclaimable core %
    reclaim_hbm_bytes: int     # allocated-minus-high-water HBM


@dataclass(frozen=True)
class NodeHeadroom:
    chips: dict[int, ChipHeadroom] = field(default_factory=dict)
    ts: float = 0.0
    # vtovc satellite (ROADMAP item a, class-mix-aware packing): the
    # resident workload-class mix — distinct tenants per class key
    # ("lat"/"thr"/"def") — so a later headroom score term can prefer
    # nodes with lender-class counterparties. Decoded on both scheduler
    # paths OBSERVE-ONLY (it rides this object onto the NodeEntry);
    # no score reads it yet.
    class_mix: dict[str, int] = field(default_factory=dict)

    def encode(self) -> str:
        segs = []
        if self.class_mix:
            # leading typed segment; emitted only when non-empty so a
            # mix-less publisher's wire bytes are unchanged
            segs.append("mix=" + ",".join(
                f"{k}:{n}" for k, n in sorted(self.class_mix.items())))
        segs += [
            f"{idx}:{ch.alloc_core_pct:.1f}:{ch.used_core_pct:.1f}:"
            f"{ch.reclaim_core_pct:.1f}:{ch.reclaim_hbm_bytes}"
            for idx, ch in sorted(self.chips.items())]
        return stalecodec.stamp(";".join(segs), self.ts)

    def total_reclaim_core_pct(self) -> float:
        return sum(c.reclaim_core_pct for c in self.chips.values())


def parse_headroom(raw: str | None, now: float | None = None,
                   max_age_s: float = MAX_HEADROOM_AGE_S
                   ) -> NodeHeadroom | None:
    """Decode the annotation; None when absent, malformed, or stale —
    every bad shape degrades to no-signal, never to a wrong claim."""
    split = stalecodec.split_stamp(raw)
    if split is None:
        return None
    body, ts = split
    if not stalecodec.is_fresh(ts, now, max_age_s):
        return None
    chips: dict[int, ChipHeadroom] = {}
    class_mix: dict[str, int] = {}
    for seg in body.split(";"):
        if not seg:
            continue
        if seg.startswith("mix="):
            # class-mix segment (vtovc satellite); garbage inside it
            # invalidates the whole rollup like any other segment
            for pair in seg[4:].split(","):
                key, _, n_raw = pair.partition(":")
                try:
                    class_mix[key] = max(int(n_raw), 0)
                except (TypeError, ValueError):
                    return None
            continue
        parts = seg.split(":")
        if len(parts) != 5:
            return None
        try:
            idx = int(parts[0])
            alloc, used, reclaim = (float(parts[1]), float(parts[2]),
                                    float(parts[3]))
            hbm = int(parts[4])
        except (TypeError, ValueError):
            return None
        if not all(math.isfinite(v) for v in (alloc, used, reclaim)):
            # NaN parses but poisons every min/max downstream — the
            # same garbage-means-no-signal rule as the pressure codec
            return None
        chips[idx] = ChipHeadroom(
            alloc_core_pct=max(alloc, 0.0),
            used_core_pct=max(used, 0.0),
            reclaim_core_pct=min(max(reclaim, 0.0), 100.0 * 64),
            reclaim_hbm_bytes=max(hbm, 0))
    return NodeHeadroom(chips=chips, ts=ts, class_mix=class_mix)


def headroom_is_fresh(hr: "NodeHeadroom | None",
                      now: float | None = None) -> bool:
    """Use-time staleness verdict (the pressure-penalty rule): the
    snapshot path caches the parsed rollup on the NodeEntry and a dead
    publisher emits no further events, so every consumer of a cached
    NodeHeadroom must re-judge freshness at the moment it acts on it."""
    if hr is None:
        return False
    return stalecodec.is_fresh(hr.ts, now, MAX_HEADROOM_AGE_S)


def headroom_score_input(hr: "NodeHeadroom | None",
                         now: float | None = None) -> float:
    """The raw headroom score input: total reclaimable core % across
    the node's chips (more lendable quota = better home for a
    burst-class pod). Staleness is re-judged HERE, not only at parse
    time — the snapshot path caches the parsed value on the NodeEntry
    and a dead publisher emits no further node events, so a use-time
    check is what makes the signal decay (the pressure-penalty rule).
    This is the value the vtexplain records carried observe-only since
    PR 8/9; the quota market scores ``headroom_score_term`` (the same
    input, capped) so recorded decisions replay exactly."""
    if hr is None:
        return 0.0
    if not stalecodec.is_fresh(hr.ts, now, MAX_HEADROOM_AGE_S):
        return 0.0
    return hr.total_reclaim_core_pct()


# the headroom term is a soft preference in the same currency as the
# pressure penalty (50 * frac <= 50) and strictly below the gang bonus
# (+100): it may break capacity ties toward lendable nodes, never
# overrule keeping a gang on its slice. The input SUMS reclaimable %
# across a node's chips, so a multi-chip node saturates the cap easily
# — a 100-scale cap would tie the gang bonus and flip gang members
# off-slice on any base-capacity difference.
HEADROOM_TERM_CAP = 50.0


def headroom_score_term(hr: "NodeHeadroom | None",
                        now: float | None = None) -> float:
    """vtqm: the REAL score term the QuotaMarket gate adds for
    latency-critical pods — ``min(headroom_score_input, cap)``.
    Defined ON the recorded observe-only input so
    ``scripts/vtpu_replay.py`` re-scores PR 9 decision spools with the
    byte-exact arithmetic the live filter applies, and so stale or
    no-confidence headroom (input 0.0) degrades to the exact
    pre-market placement."""
    return min(headroom_score_input(hr, now), HEADROOM_TERM_CAP)


def headroom_term_from_input(score_input: float) -> float:
    """The replay side of ``headroom_score_term``: recorded decisions
    carry the raw input; applying the cap here keeps the two
    derivations one formula."""
    return min(max(score_input, 0.0), HEADROOM_TERM_CAP)


# class-mix-aware packing (ROADMAP quota item (a); the PR 11
# observe-only resident class-mix decode made a REAL soft term): a
# latency-critical borrower prefers nodes with throughput LENDER
# residents, because reclaimable headroom without a lender-class
# counterparty is headroom the market cannot actually lend. Small on
# purpose — a counterparty tiebreak inside the headroom currency, not
# a new axis: per-lender bonus 5, capped at 15 (strictly below the
# headroom cap 50, the pressure ceiling 50, and the +100 gang bonus).
MIX_TERM_PER_LENDER = 5.0
MIX_TERM_CAP = 15.0

# wire key of the lender class in the class_mix segment
# (overcommit/ratio.py CLASS_KEYS: throughput tenants lend)
_LENDER_MIX_KEY = "thr"


def class_mix_term(hr: "NodeHeadroom | None",
                   now: float | None = None) -> float:
    """vtqm satellite: the class-mix score term for a latency-critical
    pod under the QuotaMarket gate. Rides the SAME annotation (and so
    the same staleness budget) as the headroom term: a stale or absent
    rollup — or one without the mix segment — contributes exactly 0.0,
    the byte-identical pre-mix score, in BOTH scheduler data paths."""
    if hr is None or not headroom_is_fresh(hr, now):
        return 0.0
    lenders = hr.class_mix.get(_LENDER_MIX_KEY, 0)
    if lenders <= 0:
        return 0.0
    return min(lenders * MIX_TERM_PER_LENDER, MIX_TERM_CAP)
