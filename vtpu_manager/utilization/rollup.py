"""vtuse cluster rollup: the node ledgers joined into one cluster view.

Served by ``cmd/device_monitor.py`` as ``/utilization`` (auth-gated
JSON) behind the UtilizationLedger gate. The node -> cluster fan-in is
pulled over the **existing registry channel** — node annotations the
device plugin already publishes (device registry, pressure, and the new
reclaimable-headroom rollup) plus the pod claim annotations the
scheduler already writes — rather than a new protocol: one apiserver
LIST answers "which chips are overcommitted on paper but idle in
practice" for the whole cluster, with no per-node scrape joins.

Per-tenant **live** use (used %, throttle-wait, high-water) is
node-local truth: it rides this monitor's own ledger for tenants
resident on this node; remote tenants carry their quota rows (decoded
from claim annotations) and their chips' rollup, and ``vtpu-smi``
pointed at a node's monitor shows that node's tenants live. Degrades
explicitly: no kube client -> node-local cut only, apiserver errors ->
the local cut plus an ``errors`` list, never a blocked scrape.
"""

from __future__ import annotations

import logging
import time

from vtpu_manager.device import types as dt
from vtpu_manager.device.claims import PodDeviceClaims
from vtpu_manager.resilience import failpoints
from vtpu_manager.telemetry import pressure as tel_pressure
from vtpu_manager.util import consts
from vtpu_manager.utilization import headroom as hr_mod
from vtpu_manager.utilization.ledger import UtilizationLedger

log = logging.getLogger(__name__)


class ClusterRollup:
    """Fold node annotations + pod claims + the local ledger into the
    /utilization document."""

    def __init__(self, ledger: UtilizationLedger, client=None,
                 cache_root: str | None = None,
                 fold_budget_s: float | None = None,
                 quota_dir: str | None = None,
                 overcommit: bool = False,
                 cluster_cache: bool = False,
                 comm: bool = False,
                 slo_ledger=None,
                 action_ledger=None,
                 health: bool = False,
                 frag: bool = False):
        self.ledger = ledger
        self.client = client
        self.cache_root = cache_root
        # vtqm (QuotaMarket gate): directory holding the node's lease
        # ledger. None (gate off) = the document carries no lease
        # fields at all — byte-identical /utilization
        self.quota_dir = quota_dir
        # vtovc (HBMOvercommit gate): False = the document carries no
        # overcommit/spill fields at all — byte-identical /utilization
        # (the vtqm pattern, asserted by test_overcommit)
        self.overcommit = overcommit
        # vtcs (ClusterCompileCache gate): False = the document carries
        # no warm-keys fields at all — byte-identical /utilization
        self.cluster_cache = cluster_cache
        # vtcomm (CommTelemetry gate): False = the document carries no
        # comm fields at all — byte-identical /utilization (the vtqm
        # pattern)
        self.comm = comm
        # vtslo (SLOAttribution gate): None = the document carries no
        # slo fields at all — byte-identical /utilization. Set, it is
        # the collector's SloLedger (already folded on the scrape
        # path; this fold only tops up since the last one)
        self.slo_ledger = slo_ledger
        # vtpilot (SLOAutopilot gate): None = the document carries no
        # autopilot block at all — byte-identical /utilization (the
        # vtqm pattern). Set, it is the controller's on-disk
        # ActionLedger; the block summarizes the last hour's actions.
        self.action_ledger = action_ledger
        # vtheal (HealthPlane gate): False = the document carries no
        # health fields at all — byte-identical /utilization (the vtqm
        # pattern). On, each chip row gains the ladder state off the
        # node's chip-health annotation and the document a fleet
        # unhealthy-chip headline (vtpu-smi's HEALTH column).
        self.health = health
        # vtfrag (FragObservatory gate): False = the document carries
        # no fragmentation fields at all — byte-identical /utilization
        # (the vtqm pattern). On, each node row gains its published
        # frag rollup and the document a fleet placeability block
        # (vtpu-smi's FRAG column + headline, the what-if doctor's
        # fleet context).
        self.frag = frag
        # same knob the collector's scrape fold uses; parsed ONCE here
        # (a malformed env value fails at construction, not per request)
        if fold_budget_s is None:
            import os
            fold_budget_s = float(
                os.environ.get("VTPU_UTIL_FOLD_BUDGET_S", "0.25"))
        self.fold_budget_s = fold_budget_s

    # -- cluster fan-in ------------------------------------------------------

    def _node_rows(self, now: float) -> tuple[list[dict], list[str]]:
        rows: list[dict] = []
        errors: list[str] = []
        if self.client is None:
            return rows, errors
        # vtovc: ONE vmem-ledger scan per collect for the local node's
        # per-chip SPILL column (the PR-10 one-generation rule — not
        # one open+mmap+scan per chip)
        local_spilled = self._local_spilled_by_chip() \
            if self.overcommit else {}
        try:
            nodes = self.client.list_nodes()
        except Exception as e:  # noqa: BLE001 — the rollup degrades to
            # the local cut on ANY apiserver shape of failure; the
            # error row says so instead of a silent half-view
            log.warning("utilization rollup node listing failed: %s", e)
            errors.append(f"list_nodes: {e}")
            return rows, errors
        reg_ann = consts.node_device_register_annotation()
        hr_ann = consts.node_reclaimable_headroom_annotation()
        pr_ann = consts.node_pressure_annotation()
        oc_ann = consts.node_overcommit_annotation()
        hp_ann = consts.node_chip_health_annotation()
        for node in nodes:
            meta = node.get("metadata") or {}
            anns = meta.get("annotations") or {}
            name = meta.get("name", "")
            registry = dt.decode_registry(anns.get(reg_ann))
            headroom = hr_mod.parse_headroom(anns.get(hr_ann), now=now)
            pressure = tel_pressure.parse_pressure(anns.get(pr_ann),
                                                   now=now)
            overcommit = None
            if self.overcommit:
                from vtpu_manager.overcommit import ratio as oc_mod
                overcommit = oc_mod.parse_overcommit(anns.get(oc_ann),
                                                     now=now)
            chiphealth = None
            if self.health:
                from vtpu_manager.health import codec as health_codec
                chiphealth = health_codec.parse_chip_health(
                    anns.get(hp_ann), now=now)
            frag = None
            if self.frag:
                from vtpu_manager.fragmentation import codec as frag_codec
                frag = frag_codec.parse_frag(
                    anns.get(consts.node_frag_annotation()), now=now)
            chips = []
            if registry is not None:
                for chip in registry.chips:
                    ch = headroom.chips.get(chip.index) \
                        if headroom else None
                    row = {
                        "index": chip.index, "uuid": chip.uuid,
                        "memory_bytes": chip.memory,
                        "split_count": chip.split_count,
                        "healthy": getattr(chip, "healthy", True),
                        "alloc_core_pct":
                            ch.alloc_core_pct if ch else None,
                        "used_core_pct":
                            ch.used_core_pct if ch else None,
                        "reclaim_core_pct":
                            ch.reclaim_core_pct if ch else None,
                        "reclaim_hbm_bytes":
                            ch.reclaim_hbm_bytes if ch else None,
                    }
                    if self.overcommit:
                        # vtpu-smi's VIRT column: the chip's capacity
                        # under the node's widest published class ratio
                        # (None = no live policy => physical admission)
                        row["virt_hbm_bytes"] = (
                            int(chip.memory * overcommit.max_ratio())
                            if overcommit else None)
                        # SPILL column: per-chip host-pool bytes are
                        # node-local truth (the vmem ledger); remote
                        # chips carry None like the other live columns
                        row["spilled_bytes"] = (
                            local_spilled.get(chip.index, 0)
                            if name == self.ledger.node_name
                            and local_spilled is not None else None)
                    if self.health:
                        # vtheal HEALTH column: the debounced ladder
                        # state off the fresh annotation; absence (or
                        # a stale/dark publisher) reads healthy — the
                        # cordon's own decay direction
                        state, _conf = chiphealth.chips.get(
                            chip.index, ("healthy", 0.0)) \
                            if chiphealth else ("healthy", 0.0)
                        row["health"] = state
                    chips.append(row)
            row_extra = {}
            if self.quota_dir:
                # raw lease-summary annotation rides to the quota fold
                # (popped there); absent when the gate is off so the
                # document stays byte-identical
                row_extra["_quota_lease_raw"] = anns.get(
                    consts.node_quota_lease_annotation())
            if self.overcommit:
                # vtovc node fields (gate on only — off keeps the
                # document byte-identical): the published per-class
                # ratios + the node's live spill signal
                row_extra["overcommit_ratios"] = \
                    dict(overcommit.ratios) if overcommit else None
                row_extra["overcommit_ratio"] = \
                    overcommit.max_ratio() if overcommit else None
                row_extra["spill_frac"] = \
                    overcommit.spill_frac if overcommit else None
                row_extra["spilled_bytes"] = \
                    overcommit.spilled_bytes if overcommit else None
            if self.health:
                # vtheal node fields (gate on only — off keeps the
                # document byte-identical): the fresh cordon headcount
                # and the publish timestamp (None = no fresh signal =
                # no cordon on this node)
                row_extra["unhealthy_chips"] = (
                    sum(1 for s, _c in chiphealth.chips.values()
                        if s != "healthy") if chiphealth else 0)
                row_extra["health_ts"] = \
                    chiphealth.ts if chiphealth else None
            if self.frag:
                # vtfrag node fields (gate on only — off keeps the
                # document byte-identical): the node's published
                # placeability rollup; None across the board = no
                # fresh signal (dark/stale publisher), which the fleet
                # block below counts but never averages in
                row_extra["frag_score"] = \
                    round(frag.score, 4) if frag else None
                row_extra["frag_free_chips"] = \
                    frag.free if frag else None
                row_extra["frag_classes"] = (
                    {str(k): v for k, v in sorted(frag.classes.items())}
                    if frag else None)
                row_extra["frag_ts"] = frag.ts if frag else None
            if self.cluster_cache:
                # vtcs warm-keys fields (gate on only — off keeps the
                # document byte-identical): which programs this node
                # can seed the fleet with, from its advertisement
                from vtpu_manager.clustercache import advertise as \
                    cc_advertise
                warm = cc_advertise.parse_warm_keys(
                    anns.get(consts.node_cache_keys_annotation()),
                    now=now)
                row_extra["warm_keys"] = \
                    len(warm.pairs) if warm else None
                # wire order is hottest-first — preserve it, vtpu-smi
                # shows the first few as "the hottest"
                row_extra["warm_fps"] = \
                    [fp for fp, _k in warm.pairs] if warm else None
            rows.append({
                **row_extra,
                "node": name,
                "local": name == self.ledger.node_name,
                "chips": chips,
                "mesh_domain":
                    registry.mesh_domain if registry else "",
                "headroom_ts": headroom.ts if headroom else None,
                "headroom_stale": headroom is None
                    and bool(anns.get(hr_ann)),
                "reclaim_core_pct": round(
                    headroom.total_reclaim_core_pct(), 2)
                    if headroom else None,
                "pressure_frac":
                    pressure.throttle_frac if pressure else None,
            })
        return rows, errors

    def _tenant_quota_rows(self, now: float
                           ) -> tuple[list[dict], list[str]]:
        """Cluster-wide quota rows from the claim annotations the
        scheduler/plugin already write — the paper side of the ledger
        for every node, joined with live use where this node's ledger
        has it."""
        rows: list[dict] = []
        errors: list[str] = []
        if self.client is None:
            return rows, errors
        try:
            pods = self.client.list_pods()
        except Exception as e:  # noqa: BLE001 — same degrade-to-local
            # contract as the node listing
            log.warning("utilization rollup pod listing failed: %s", e)
            errors.append(f"list_pods: {e}")
            return rows, errors
        real_ann = consts.real_allocated_annotation()
        pre_ann = consts.pre_allocated_annotation()
        live = {(s.pod_uid, s.container.split("/", 1)[0], s.host_index): s
                for s in self.ledger.tenants()}
        for pod in pods:
            meta = pod.get("metadata") or {}
            anns = meta.get("annotations") or {}
            raw = anns.get(real_ann) or anns.get(pre_ann)
            if not raw:
                continue
            try:
                claims = PodDeviceClaims.decode(raw)
            except (ValueError, TypeError):
                continue
            uid = meta.get("uid", "")
            node = (pod.get("spec") or {}).get("nodeName", "") or \
                anns.get(consts.predicate_node_annotation(), "")
            for container, clist in claims.containers.items():
                for claim in clist:
                    state = live.get((uid, container, claim.host_index))
                    rows.append({
                        "pod_uid": uid,
                        "pod_name": meta.get("name", ""),
                        "pod_namespace": meta.get("namespace", ""),
                        "container": container,
                        "node": node,
                        "chip_index": claim.host_index,
                        "chip_uuid": claim.uuid,
                        "allocated_core_pct": claim.cores,
                        "allocated_hbm_bytes": claim.memory,
                        "used_core_pct": round(state.used_ewma, 2)
                            if state else None,
                        "throttle_wait_frac": round(state.wait_frac, 4)
                            if state else None,
                        "hbm_highwater_bytes": state.hbm_highwater
                            if state else None,
                        "confidence": round(state.confidence(now), 3)
                            if state else None,
                        "live": state is not None,
                    })
        return rows, errors

    def _fold_quota_leases(self, tenant_rows: list[dict],
                           node_rows: list[dict],
                           now: float) -> dict | None:
        """vtqm: fold the node-local lease ledger into the tenant rows
        (lent/borrowed columns for vtpu-smi) and decode remote nodes'
        lease-summary annotations into the node rows. Local truth comes
        from the ledger file itself — the same node-local-live rule the
        used%/wait columns follow."""
        if not self.quota_dir:
            return None
        from vtpu_manager.quota import (QuotaLeaseLedger,
                                        parse_lease_summary)
        # remote nodes first (the stashed raw annotation must be popped
        # whether or not the local ledger read below succeeds)
        for nrow in node_rows:
            summary = parse_lease_summary(
                nrow.pop("_quota_lease_raw", None), now=now)
            if summary is not None:
                nrow["quota_lent_core_pct"] = sum(
                    c["lent_core_pct"] for c in summary.values())
                nrow["quota_leases"] = sum(
                    c["leases"] for c in summary.values())
        # ONE ledger generation for the whole document (a torn file
        # loads as recovered-empty, never raises): the lent/borrowed
        # columns, the active list, and the epoch must agree
        view = QuotaLeaseLedger(self.quota_dir).snapshot(now)
        leases, active = view.leases, view.active
        by_tenant_chip: dict[tuple[str, str, int], int] = {}
        for (tenant, chip), pct in view.deltas.items():
            uid, _, label = tenant.partition("/")
            # SUMMED per base container: a multi-request DRA claim's
            # partitions share the row key, and their net position —
            # never the iteration-last partition's value — is what the
            # lent/borrowed columns must show
            key = (uid, label.split("/", 1)[0], chip)
            by_tenant_chip[key] = by_tenant_chip.get(key, 0) + pct
        for row in tenant_rows:
            key = (row.get("pod_uid", ""),
                   str(row.get("container", "")).split("/", 1)[0],
                   row.get("chip_index"))
            delta = by_tenant_chip.get(key)
            if delta is None:
                continue
            if delta > 0:
                row["borrowed_core_pct"] = delta
            elif delta < 0:
                row["lent_core_pct"] = -delta
        # vtcomm-PR quota satellite (ROADMAP quota item (d), the
        # observe-only evidence leg): per active lease, did the
        # borrower USE what it borrowed? The borrower's measured
        # used%% comes from the vtuse ledger's apportioning rule (ring
        # busy fraction split by allocated-core share — the same
        # figure the tenant rows carry), so the borrowed-vs-used
        # verdict is re-derivable from any recorded /utilization
        # document: used_of_borrowed = clamp(used - base_alloc, 0,
        # pct). vtpu_replay.py --utilization-file replays exactly that
        # equation over a saved document.
        by_row = {}
        for row in tenant_rows:
            key = (row.get("pod_uid", ""),
                   str(row.get("container", "")).split("/", 1)[0],
                   row.get("chip_index"))
            by_row[key] = row
        borrowed_used = []
        for lease in active:
            uid, _, label = str(lease.get("borrower", "")).partition("/")
            key = (uid, label.split("/", 1)[0], lease.get("chip"))
            row = by_row.get(key)
            pct = int(lease.get("pct", 0))
            used = row.get("used_core_pct") if row else None
            base = row.get("allocated_core_pct") if row else None
            # THE shared formula (quota.market.borrowed_used_verdict):
            # the grant-step feedback and the replay check consume the
            # same arithmetic these rows publish
            from vtpu_manager.quota.market import borrowed_used_verdict
            used_of_borrowed = borrowed_used_verdict(used, base, pct)
            if used_of_borrowed is not None:
                used_of_borrowed = round(used_of_borrowed, 2)
            borrowed_used.append({
                "id": lease.get("id"),
                "chip": lease.get("chip"),
                "borrower": lease.get("borrower"),
                "pct": pct,
                "used_of_borrowed_pct": used_of_borrowed,
                "utilization": round(used_of_borrowed / pct, 3)
                    if used_of_borrowed is not None and pct else None,
                "live": used is not None,
            })
        return {
            "leases_active": len(active),
            "lent_core_pct_total": sum(int(l.get("pct", 0))
                                       for l in active),
            "epoch": int(view.epoch),
            "leases": [{k: l.get(k) for k in
                        ("id", "chip", "lender", "borrower", "pct",
                         "granted_at", "ttl_s", "state")}
                       for l in leases[-64:]],
            "borrowed_used": borrowed_used,
        }

    def _local_spilled_by_chip(self) -> "dict[int, int] | None":
        """Live host-pool bytes per chip off the node's vmem ledger —
        ONE open+scan per collect (vtovc; None when the ledger is
        absent/unreadable — the smi column renders '-', never a
        guess)."""
        try:
            from vtpu_manager.config.vmem import VmemLedger
            led = VmemLedger(consts.VMEM_NODE_CONFIG)
            try:
                out: dict[int, int] = {}
                for e in led.entries():
                    out[e.host_index] = out.get(e.host_index, 0) \
                        + e.spilled
                return out
            finally:
                led.close()
        except (OSError, ValueError):
            return None

    def _compile_cache_state(self) -> dict | None:
        if not self.cache_root:
            return None
        try:
            from vtpu_manager.compilecache.cache import node_totals
            counters, entries, size = node_totals(self.cache_root)
            return {"entries": entries, "size_bytes": size,
                    "hits": counters.get("hits", 0),
                    "misses": counters.get("misses", 0)}
        except (OSError, ValueError):
            return None

    # -- the document --------------------------------------------------------

    def collect(self, now: float | None = None) -> dict:
        """The /utilization document: node-local ledger detail plus the
        cluster cuts. Raises only what the failpoint injects — callers
        (the monitor route) wrap it; everything organic degrades to
        partial data with an ``errors`` list."""
        failpoints.fire("util.rollup", node=self.ledger.node_name)
        now = time.time() if now is None else now
        fold_errors: list[str] = []
        try:
            # /utilization must serve fresh local rows even when nothing
            # scrapes /metrics (same budget discipline as the scrape)
            self.ledger.fold(budget_s=self.fold_budget_s)
        except Exception as e:  # noqa: BLE001 — a torn fold serves the
            # last fold's (confidence-decaying) state plus an error row
            log.warning("utilization rollup fold failed: %s", e)
            fold_errors.append(f"fold: {e}")
        node_rows, node_errors = self._node_rows(now)
        tenant_rows, pod_errors = self._tenant_quota_rows(now)
        # local ledger rows the pod listing did not cover (no cluster
        # client, apiserver error, claim annotation gone) merge in,
        # shaped like the cluster rows so the ?pod=/?node= filters and
        # vtpu-smi treat both alike — cluster rows take precedence
        present = {(t["pod_uid"], t["container"], t["chip_index"])
                   for t in tenant_rows}
        local = self.ledger.to_wire(now)   # ONE wire derivation per
        # request: the merge below and the document's node block must
        # agree anyway, and the per-tenant row assembly is not free
        for t in local["tenants"]:
            key = (t["pod_uid"], t["container"].split("/", 1)[0],
                   t["chip_index"])
            if key not in present:
                tenant_rows.append(
                    dict(t, node=self.ledger.node_name, live=True))
        local["compile_cache"] = self._compile_cache_state()
        if self.overcommit:
            # vtovc local truth (gate on only): ring-reported spill
            # activity plus the pool directory's ground-truth bytes
            from vtpu_manager.overcommit.spill import pool_totals
            spill_frac, ring_bytes = self.ledger.node_spill_signal(now)
            pool_files, pool_bytes = pool_totals()
            local["spill"] = {
                "spill_frac": round(spill_frac, 4),
                "spilled_bytes": ring_bytes,
                "pool_files": pool_files,
                "pool_bytes": pool_bytes,
                "spill_events_total": self.ledger.spill_events_total,
                "fill_events_total": self.ledger.fill_events_total,
            }
        if self.comm:
            # vtcomm local truth (gate on only — off keeps the document
            # byte-identical): measured per-tenant communication rows
            # plus lifetime movement counters, and the comm columns
            # spliced onto this node's live tenant rows (per base
            # container — the ring is per tenant, not per chip)
            comm_rows = self.ledger.comm_rows(now)
            local["comm"] = {
                "tenants": comm_rows,
                "comm_bytes_total": self.ledger.comm_bytes_total,
                "collectives_total": self.ledger.collectives_total,
            }
            # staleness ladder: a dead comm writer's last EWMA must
            # never splice onto a live row as a current measurement —
            # decayed tenants keep their (stale-flagged) entry in the
            # comm block above but lose the COMM columns, the same
            # decay comm_signals() applies for the publisher
            by_tenant = {(c["pod_uid"],
                          c["container"].split("/", 1)[0]): c
                         for c in comm_rows if not c["stale"]}
            for row in tenant_rows:
                c = by_tenant.get(
                    (row.get("pod_uid", ""),
                     str(row.get("container", "")).split("/", 1)[0]))
                if c is not None and row.get("live"):
                    row["comm_duty_frac"] = c["comm_duty_frac"]
                    row["comm_intensity"] = c["comm_intensity"]
        slo_fleet = None
        if self.slo_ledger is not None:
            # vtslo local truth (gate on only — off keeps the document
            # byte-identical): the GOODPUT column on this node's live
            # tenant rows plus the fleet SLO headline block. Stale
            # attribution rows keep their (flagged) entry in the slo
            # block but never splice onto a live row — the comm-column
            # decay rule.
            try:
                self.slo_ledger.fold(now_wall=now)
            except Exception as e:  # noqa: BLE001 — a torn fold serves
                # the last fold's state plus an error row
                log.warning("slo fold failed in rollup: %s", e)
                fold_errors.append(f"slo_fold: {e}")
            slo_doc = self.slo_ledger.document(now)
            local["slo"] = {
                "tenants": slo_doc["tenants"],
                "verdicts": slo_doc["verdicts"][-16:],
                "regressions_total": slo_doc["regressions_total"],
            }
            slo_fleet = {**slo_doc["fleet"]}
            by_tenant = {(r["pod_uid"], r["container"].split("/", 1)[0])
                         : r for r in slo_doc["tenants"]
                         if not r["stale"]}
            for row in tenant_rows:
                s = by_tenant.get(
                    (row.get("pod_uid", ""),
                     str(row.get("container", "")).split("/", 1)[0]))
                if s is not None and row.get("live"):
                    row["goodput_ratio"] = s["goodput_ratio"]
        quota = self._fold_quota_leases(tenant_rows, node_rows, now)
        live_nodes = [r for r in node_rows
                      if r["reclaim_core_pct"] is not None]
        doc = {
            "generated_at": now,
            "node": local,
            "nodes": node_rows,
            "tenants": tenant_rows,
            "cluster": {
                "nodes": len(node_rows),
                "nodes_with_signal": len(live_nodes),
                "chips": sum(len(r["chips"]) for r in node_rows),
                "reclaimable_core_pct": round(
                    sum(r["reclaim_core_pct"] for r in live_nodes), 2),
                "tenant_rows": len(tenant_rows),
            },
            "errors": fold_errors + node_errors + pod_errors,
        }
        if quota is not None:
            doc["quota"] = quota
        if slo_fleet is not None:
            doc["slo"] = slo_fleet
        if self.action_ledger is not None:
            # vtpilot fleet headline (gate off = no key at all): what
            # the autopilot did in the last hour, by action, plus the
            # most recent action so vtpu-smi's one-liner needs no
            # second fetch
            try:
                recent = self.action_ledger.actions(since=now - 3600.0)
            except Exception as e:  # noqa: BLE001 — a torn ledger read
                # degrades to an empty trail, never a failed rollup
                log.warning("autopilot ledger read failed: %s", e)
                fold_errors.append(f"autopilot_ledger: {e}")
                recent = []
            by_action: dict[str, int] = {}
            for rec in recent:
                name = str((rec.get("action") or {}).get("action",
                                                         "unknown"))
                by_action[name] = by_action.get(name, 0) + 1
            doc["autopilot"] = {
                "actions_last_hour": len(recent),
                "by_action": by_action,
                "last_action": recent[-1] if recent else None,
            }
        if self.health:
            # vtheal fleet headline (gate off = no key at all): how
            # many chips the fleet is currently cordoning and where
            # the ladder put them — folded from the SAME chip rows the
            # per-node cut decodes, so the headline and the HEALTH
            # column can never disagree
            by_state: dict[str, int] = {}
            unhealthy = 0
            publishing = 0
            for nrow in node_rows:
                if nrow.get("health_ts") is not None:
                    publishing += 1
                for ch in nrow["chips"]:
                    state = ch.get("health")
                    if state and state != "healthy":
                        unhealthy += 1
                        by_state[state] = by_state.get(state, 0) + 1
            doc["health"] = {
                "nodes_publishing": publishing,
                "unhealthy_chips": unhealthy,
                "by_state": by_state,
            }
        if self.frag:
            # vtfrag fleet placeability block (gate off = no key at
            # all): the per-class placeable-gang histogram summed over
            # every fresh-publishing node, the fleet frag score (mean
            # over the same set), and the per-node rows — folded from
            # the SAME decoded annotations the node rows carry, so the
            # headline and the FRAG column can never disagree. This is
            # the block the FragHistory samples and the forecaster
            # contextualizes.
            gangs: dict[str, int] = {}
            scores = []
            free_sum = 0
            publishing = 0
            frag_rows = []
            for nrow in node_rows:
                if nrow.get("frag_ts") is None:
                    continue
                publishing += 1
                scores.append(float(nrow["frag_score"]))
                free_sum += int(nrow.get("frag_free_chips") or 0)
                for cls, count in (nrow.get("frag_classes")
                                   or {}).items():
                    gangs[cls] = gangs.get(cls, 0) + int(count)
                frag_rows.append({
                    "node": nrow["node"],
                    "score": nrow["frag_score"],
                    "free_chips": nrow["frag_free_chips"],
                    "classes": nrow["frag_classes"],
                })
            doc["fragmentation"] = {
                "nodes_publishing": publishing,
                "fleet_score": round(sum(scores) / len(scores), 4)
                    if scores else 0.0,
                "free_chips": free_sum,
                "placeable_gangs": {k: gangs[k]
                                    for k in sorted(gangs, key=int)},
                "nodes": frag_rows,
            }
        if self.overcommit:
            # vtcomm-PR vtovc satellite (ROADMAP vtovc item (a)): the
            # fleet-level overcommit policy view — which classes
            # oversubscribe where (per-class ratio spread across the
            # publishing nodes) plus the fleet spill-rate headline —
            # folded from the SAME node annotations the per-node rows
            # decode. Gate off = no key at all (byte-identical).
            per_class: dict[str, list] = {}
            spill_fracs = []
            spilled_sum = 0
            publishing = 0
            for nrow in node_rows:
                ratios = nrow.get("overcommit_ratios")
                if ratios is None:
                    continue
                publishing += 1
                for cls, ratio in ratios.items():
                    per_class.setdefault(cls, []).append(float(ratio))
                if nrow.get("spill_frac") is not None:
                    spill_fracs.append(float(nrow["spill_frac"]))
                spilled_sum += int(nrow.get("spilled_bytes") or 0)
            doc["overcommit"] = {
                "nodes_publishing": publishing,
                "classes": {
                    cls: {
                        "nodes": len(vals),
                        "min_ratio": round(min(vals), 3),
                        "max_ratio": round(max(vals), 3),
                        "mean_ratio": round(sum(vals) / len(vals), 3),
                    } for cls, vals in sorted(per_class.items())},
                "fleet_spill_frac_mean": round(
                    sum(spill_fracs) / len(spill_fracs), 4)
                    if spill_fracs else 0.0,
                "fleet_spill_frac_max": round(max(spill_fracs), 4)
                    if spill_fracs else 0.0,
                "fleet_spilled_bytes": spilled_sum,
            }
        return doc


def filter_document(doc: dict, node: str = "", pod: str = "") -> dict:
    """Apply the route's ?node= / ?pod= cuts to a collected document —
    pure function so the HTTP layer stays a thin shell (and tests drive
    the cuts without a server)."""
    out = dict(doc)
    if node:
        out["nodes"] = [r for r in doc.get("nodes", [])
                        if r.get("node") == node]
        out["tenants"] = [r for r in doc.get("tenants", [])
                         if r.get("node") == node]
    if pod:
        out["tenants"] = [r for r in out.get("tenants", [])
                         if pod in (r.get("pod_uid"), r.get("pod_name"))]
    return out
