"""Crash-window recovery: the bind-intent trail.

The allocation path commits state across three processes (scheduler
filter -> scheduler bind -> node plugin) with annotation patches as the
only channel. Two crash windows used to leave a pod wedged with nothing
reconciling it:

1. **filter commit -> Binding POST**: the filter patched the
   pre-allocation, bind patched "allocating", and then the scheduler
   died before the Binding POST. The pod stays Pending forever holding a
   stale commitment (the stuck grace frees the CAPACITY, but the pod's
   annotations still claim a node and no controller cleared them).
2. **Binding POST -> Allocate completion**: the pod is bound, status
   "allocating", and the plugin died mid-Allocate. No "failed" patch was
   written, so the reschedule controller's failed-status pass never
   fires.

The fix is one more field in the patch bind already makes: the
bind-intent annotation, ``<node>@<wall-seconds>``, stamped in the SAME
patch as the "allocating" status (one API call — no extra failure
window) and therefore guaranteed present before the Binding POST. The
reschedule controller then reaps both windows:

- intent expired + pod still unbound  -> scheduler crashed in window 1:
  clear the whole commitment (pre-allocation, predicate, intent, status)
  so the pod re-enters scheduling cleanly;
- intent expired + bound + status "allocating" + no real allocation ->
  plugin crashed in window 2: evict, sending the pod back through
  scheduling (the reference reschedule.go posture for unfulfillable
  commitments).

A successful Allocate patches status "succeed", which retires the intent
without another write.
"""

from __future__ import annotations

import time

from vtpu_manager.util import consts
from vtpu_manager.util import stalecodec


def encode_bind_intent(node: str, ts: float | None = None) -> str:
    return stalecodec.stamp(node, ts if ts is not None else time.time())


def parse_bind_intent(value: str | None) -> tuple[str, float] | None:
    """(node, wall-seconds) or None for absent/malformed. Malformed reads
    as absent — reaping must never trigger off garbage it cannot date."""
    split = stalecodec.split_stamp(value)
    if split is None or not split[0]:
        return None
    return split


def intent_expired(anns: dict, now: float, ttl_s: float) -> bool:
    parsed = parse_bind_intent(
        (anns or {}).get(consts.bind_intent_annotation()))
    if parsed is None:
        return False
    _, ts = parsed
    return now - ts > ttl_s


def commitment_clear_patch() -> dict:
    """Merge-patch annotation map that erases a dead scheduling
    commitment (None values delete in merge-patch semantics, which both
    the real client and the fake implement)."""
    return {
        consts.pre_allocated_annotation(): None,
        consts.predicate_node_annotation(): None,
        consts.predicate_time_annotation(): None,
        consts.bind_intent_annotation(): None,
        consts.allocation_status_annotation(): None,
        # vtha: a cleared commitment must also drop its fencing stamp, or
        # the re-scheduled pod would keep routing to the dead commitment's
        # shard and the next takeover would re-judge a fresh commitment by
        # a stale token
        consts.shard_fence_annotation(): None,
    }
