"""Failpoint registry: named fault-injection sites (etcd gofail pattern).

Every load-bearing step of the allocation path calls
``failpoints.fire("<site>")`` (the SITES catalog below is the canonical
list). In production the registry is empty and ``fire`` is one dict
lookup returning None — the cost contract the FaultInjection gate's
"default off" promise rests on, asserted by the chaos suite's gate-off
run. When a test (or a binary with ``FaultInjection=true`` plus the
``VTPU_FAILPOINTS`` env spec) arms a site, ``fire`` consults the armed
spec: a seeded RNG decides probabilistically, a count bounds total
fires, and the action runs:

- ``error``   raise an exception (KubeError with a chosen status for
              kube-facing sites, or any factory) — the transient-failure
              case RetryPolicy must absorb;
- ``latency`` sleep a fixed delay — the slow-dependency case deadlines
              must bound;
- ``crash``   raise :class:`CrashFailpoint`, a **BaseException**: broad
              ``except Exception`` recovery code cannot swallow it, so
              it propagates exactly like process death at that line
              (locks still release — the kernel would do the same for
              flocks on a real crash);
- ``partial-write`` truncate the file the site just wrote (ctx must
              carry ``path``) to a seeded fraction, then crash — the
              torn-file state a mid-write power cut leaves.

Determinism: one ``random.Random(seed)`` per enablement; the same seed
and the same call sequence replay the same injections (the chaos
harness logs its seed; ``CHAOS_SEED=n make test-chaos`` reproduces).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from random import Random

log = logging.getLogger(__name__)

# Canonical site catalog: name -> where it fires (docs/resilience.md
# carries the operator-facing version). Arming an unknown site is an
# error — a typo must not silently inject nothing.
SITES: dict[str, str] = {
    "kube.request": "client/kube.py _request + every FakeKubeClient verb",
    "kube.watch": "client/kube.py _watch + FakeKubeClient watch streams",
    "scheduler.filter_commit": "filter.py _commit, after the annotation "
                               "patch, before the assumed-cache insert",
    "scheduler.bind_patch": "bind.py, between the allocating/intent patch "
                            "and the Binding POST (the bind crash window)",
    "bind.batch": "scheduler/bindpipe.py, after each pod's intent patch "
                  "lands within a wave and before the wave's single lease "
                  "confirm (crash = a TORN WAVE: some pods of the batch "
                  "carry intents, none carry Bindings — the PR 4 reapers "
                  "must converge every one of them; error = the pod "
                  "degrades to the serial bind path, never the wave)",
    "snapshot.apply": "snapshot.py apply_event, before decode/apply",
    "plugin.allocate": "vnum.py _allocate_container, inside the Allocate "
                       "try block",
    "plugin.config_write": "vnum.py, after vtpu.config is written",
    "plugin.record_devices": "vnum.py _record_devices, after devices.json "
                             "is written",
    "registry.register": "registry/server.py handle_request, after "
                         "attestation, before the registration write",
    "trace.spool_flush": "trace/recorder.py flush, before spool I/O",
    "flock.acquire": "util/flock.py FileLock.acquire entry",
    "controller.evict": "controller/reschedule.py _evict entry",
    "lease.acquire": "scheduler/lease.py try_acquire, before the lease "
                     "GET/create/CAS sequence",
    "lease.renew": "scheduler/lease.py renew, before the CAS update (the "
                   "bind-time confirm() rides this site too)",
    "shard.handoff": "scheduler/shard.py takeover replay entry, after a "
                     "lease acquisition and before the shard accepts work",
    "dra.prepare": "kubeletplugin/device_state.py prepare_claim, after "
                   "the idempotency check, before any disk write",
    "dra.cdi_write": "kubeletplugin/device_state.py, after the CDI spec "
                     "lands on disk and before the checkpoint write "
                     "(partial-write tears the spec the runtime reads)",
    "cache.write": "compilecache/cache.py put, after the temp entry is "
                   "written and before the atomic rename (partial-write "
                   "= a torn executable that must be quarantined, never "
                   "loaded)",
    "cache.lease": "compilecache/cache.py, after the single-flight lease "
                   "is acquired and before the compile runs (crash = a "
                   "dead lease holder waiters must take over within the "
                   "stale-lease budget)",
    "cache.fetch": "clustercache/fetch.py _fetch_remote, after the peer "
                   "payload is staged to a temp file and before the "
                   "read-back verify (error = peer/transport failure the "
                   "ladder must absorb by compiling; latency = a slow "
                   "peer the timeout budget must bound; partial-write = "
                   "a torn payload mid-download that must fail "
                   "verification and never land as a servable entry)",
    "cache.advertise": "clustercache/advertise.py publish_once, after "
                       "the advertisement is encoded and before the "
                       "node-annotation patch (error = a failed publish "
                       "the annotation's own timestamp ages out — "
                       "peers decay to no-signal, never fetch from a "
                       "ghost)",
    "util.fold": "utilization/ledger.py fold entry (the scrape-time "
                 "ledger fold; error = a torn fold the collector must "
                 "flag without blocking /metrics, headroom decays to "
                 "no-signal instead of serving stale claims)",
    "util.rollup": "utilization/rollup.py ClusterRollup.collect entry "
                   "(the monitor's /utilization fan-in; error/latency "
                   "must never reach the /metrics path)",
    "explain.record": "explain/record.py ExplainRecorder.flush, before "
                      "spool I/O (error = spool unavailable, records "
                      "become counted drops; partial-write = a torn "
                      "spool line the doctor must skip). Fires on the "
                      "background flusher only — a wedged explain "
                      "plane must never block a filter pass",
    "explain.rollup": "explain/doctor.py collect entry (the /explain "
                      "fan-in on scheduler and monitor; error/latency "
                      "must hit only that route, never /metrics or a "
                      "scheduling pass)",
    "quota.lease": "quota/market.py grant path, after the ledger "
                   "records the lease and before any config rewrite "
                   "(crash = manager dies holding a grant no shim "
                   "enforces yet — TTL + the restart rule converge it; "
                   "partial-write = a torn lease ledger that must "
                   "recover as empty and reconcile every config to "
                   "base rates)",
    "quota.revoke": "quota/market.py revoke path, after the ledger "
                    "settles and before the reconcile pass rewrites "
                    "configs (crash = plugin restart mid-revoke: the "
                    "start() rule revokes carried leases and restores "
                    "base truth before new market activity)",
    "spill.copy": "overcommit/spill.py SpillPool.spill, after the tmp "
                  "pool file is written and before fsync+rename "
                  "(partial-write = a torn spill mid-copy: only a .tmp "
                  "orphan exists, the pool namespace and the vmem "
                  "ledger are untouched, the reaper deletes it)",
    "spill.budget": "overcommit/spill.py SpillPool.spill, at the "
                    "pre-write budget guard (error = budget exhausted: "
                    "the caller's allocation fails exactly as it would "
                    "have pre-vtovc — the spill arm only ever converts "
                    "failures into successes)",
    "ici.publish": "topology/linkload.py LinkLoadPublisher."
                   "publish_once, after the rollup is encoded and "
                   "before the node-annotation patch (error = a failed "
                   "publish the annotation's own timestamp ages out — "
                   "the scheduler's link_term decays to no-signal, "
                   "never steers on a ghost's contention claim)",
    "autopilot.act": "autopilot/controller.py _act, after every guard "
                     "passed and before the remediation dispatches "
                     "(error = a failed action that must start the "
                     "cooldown like a success — retry storms are the "
                     "flap the guards exist to prevent; crash = leader "
                     "death mid-decision the successor's election "
                     "absorbs)",
    "migrate.freeze": "autopilot/migrate.py migrate, after the intent "
                      "trail lands and before the tenant's configs "
                      "freeze (crash = a dead migrator whose intent "
                      "the token-aware reaper must unfreeze and clear; "
                      "error = a failed freeze that rolls back in "
                      "place)",
    "migrate.refill": "autopilot/migrate.py migrate, after the rebind "
                      "lands and before the unfreeze rewrites (crash = "
                      "the worst window — tenant frozen, pod already "
                      "rebound — the reaper must unfreeze on BOTH "
                      "source and target so the gang never stays "
                      "parked; the shim's VTPU_FREEZE_MAX_S fail-open "
                      "is the last-resort backstop)",
    "health.probe": "manager/device_manager.py HealthWatcher."
                    "check_once per chip AND health/publisher.py "
                    "_probe_chips (error/latency = a probe pass that "
                    "fails or drags — fail-open, no flip, only the "
                    "exec-failure counter; crash = watcher death "
                    "mid-pass the next interval absorbs)",
    "health.flip": "health/publisher.py publish_once, per ladder state "
                   "transition and before the annotation patch (crash "
                   "= the LAST published state stands until the "
                   "stalecodec timestamp ages the cordon out — a torn "
                   "flip can never publish; error = a lost publish "
                   "tick the next interval replays)",
    "health.rescue": "autopilot/actions.py rescue_gang, after the "
                     "guards passed and before the migration "
                     "dispatches (crash = leader death mid-rescue: "
                     "the intent trail + PR 17 reapers unfreeze the "
                     "gang and the successor's next eligible window "
                     "retries; error = a failed rescue that starts "
                     "the cooldown like a success)",
    "frag.publish": "fragmentation/publisher.py FragPublisher."
                    "publish_once, after the rollup is encoded and "
                    "before the node-annotation patch (error = a "
                    "failed publish the annotation's own timestamp "
                    "ages out — the fleet rollup drops the node to "
                    "no-signal, never capacity-plans on a ghost's "
                    "placeability claim)",
    "frag.rollup": "fragmentation/forecast.py what_if entry (the "
                   "monitor's /fragmentation what-if doctor; "
                   "error/latency must 503 only that route, never "
                   "/metrics or a scheduling pass)",
}

ACTIONS = ("error", "latency", "crash", "partial-write")


class CrashFailpoint(BaseException):
    """Simulated process death at a failpoint. BaseException on purpose:
    recovery code that catches ``Exception`` must not be able to survive
    a crash the way it could never survive a real one."""

    def __init__(self, site: str):
        super().__init__(f"crash failpoint fired at {site}")
        self.site = site


@dataclass
class _Spec:
    action: str
    p: float = 1.0
    count: int | None = None          # remaining fires; None = unlimited
    status: int = 503                 # for error action on kube sites
    latency_s: float = 0.001
    retry_after: float | None = None  # Retry-After carried by the KubeError
    exc: type | None = None           # overrides the KubeError default
    match: dict = field(default_factory=dict)   # ctx subset that must match


class _Stats:
    __slots__ = ("fires", "evaluations")

    def __init__(self) -> None:
        self.fires: dict[str, int] = {}
        self.evaluations = 0

    def total(self) -> int:
        return sum(self.fires.values())


# _ARMED is the whole fast-path contract: empty unless enable()+arm()
# ran, and fire()'s disabled path is exactly one .get() on it.
_ARMED: dict[str, _Spec] = {}
_lock = threading.Lock()
_rng = Random(0)
_enabled = False
_stats = _Stats()


def is_enabled() -> bool:
    return _enabled


def enable(seed: int = 0) -> None:
    """Turn the registry on (FaultInjection gate). Resets stats and the
    deterministic RNG; sites still need arm()."""
    global _enabled, _rng, _stats
    with _lock:
        _enabled = True
        _rng = Random(seed)
        _stats = _Stats()
        _ARMED.clear()


def disable() -> None:
    """Back to the fully cold path: clears every armed site and the
    stats (a disabled registry reports zero, matching its cost)."""
    global _enabled, _stats
    with _lock:
        _enabled = False
        _ARMED.clear()
        _stats = _Stats()


def arm(site: str, action: str, p: float = 1.0, count: int | None = None,
        status: int = 503, latency_s: float = 0.001,
        retry_after: float | None = None,
        exc: type | None = None, match: dict | None = None) -> None:
    if site not in SITES:
        raise KeyError(f"unknown failpoint site {site!r} "
                       f"(known: {sorted(SITES)})")
    if action not in ACTIONS:
        raise ValueError(f"unknown failpoint action {action!r}")
    if retry_after is not None and action != "error":
        raise ValueError("retry_after only applies to the error action")
    if not _enabled:
        raise RuntimeError("failpoints disabled: enable() (FaultInjection "
                           "gate) before arm()")
    with _lock:
        _ARMED[site] = _Spec(action=action, p=p, count=count, status=status,
                             latency_s=latency_s, retry_after=retry_after,
                             exc=exc, match=dict(match or {}))


def disarm(site: str) -> None:
    with _lock:
        _ARMED.pop(site, None)


def armed_sites() -> list[str]:
    return sorted(_ARMED)


def stats() -> dict:
    with _lock:
        return {"fires": dict(_stats.fires), "total": _stats.total(),
                "evaluations": _stats.evaluations}


def fire(site: str, **ctx) -> None:
    """The injection point. Disabled/unarmed cost: this one dict lookup."""
    spec = _ARMED.get(site)
    if spec is None:
        return
    _fire_armed(site, spec, ctx)


def _fire_armed(site: str, spec: _Spec, ctx: dict) -> None:
    with _lock:
        _stats.evaluations += 1
        if spec.match:
            for key, want in spec.match.items():
                if ctx.get(key) != want:
                    return
        if spec.count is not None and spec.count <= 0:
            return
        if spec.p < 1.0 and _rng.random() >= spec.p:
            return
        if spec.count is not None:
            spec.count -= 1
        _stats.fires[site] = _stats.fires.get(site, 0) + 1
        frac = 0.1 + 0.8 * _rng.random()     # partial-write cut point
    log.info("failpoint %s fired: %s %s", site, spec.action,
             {k: v for k, v in ctx.items() if k != "data"})
    _record_span(site, spec.action, ctx)
    if spec.action == "latency":
        time.sleep(spec.latency_s)
        return
    if spec.action == "error":
        raise _make_error(site, spec)
    if spec.action == "partial-write":
        _truncate(ctx.get("path"), frac)
        raise CrashFailpoint(site)
    raise CrashFailpoint(site)


def _make_error(site: str, spec: _Spec) -> Exception:
    if spec.exc is not None:
        return spec.exc(f"failpoint {site} injected error")
    # KubeError is the lingua franca of the sites this ships for; import
    # here to keep the module import-light (flock.py imports us).
    # retry_after rides the error like a real Retry-After header would,
    # so injected 429/503s exercise the RetryPolicy floor branch.
    from vtpu_manager.client.kube import KubeError
    return KubeError(spec.status, f"failpoint {site} injected error",
                     retry_after=spec.retry_after)


def _truncate(path, frac: float) -> None:
    if not path:
        return
    try:
        import os
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, int(size * frac)))
    except OSError:
        log.warning("partial-write failpoint could not truncate %r", path)


def _record_span(site: str, action: str, ctx: dict) -> None:
    """Injections land in the pod's vtrace timeline so a chaos run (or a
    staging soak with the gate on) shows WHERE the fault hit. Lazy import:
    trace -> recorder -> flock -> this module would otherwise cycle."""
    uid = ctx.get("pod_uid") or ""
    if not uid:
        return
    try:
        from vtpu_manager import trace
        trace.event(trace.context_for_uid(uid), f"failpoint.{site}",
                    action=action)
    except Exception:  # noqa: BLE001 — observability must never add faults
        log.debug("failpoint span emit failed", exc_info=True)


# -- env spec (binaries: FaultInjection gate + VTPU_FAILPOINTS) -------------

def arm_spec(spec: str) -> None:
    """Parse ``site=action(arg,k=v,...);site2=...`` and arm each entry.
    Grammar mirrors gofail's: the one positional arg is the status for
    ``error`` and the seconds for ``latency``; ``p=``/``count=`` bound
    the injection, and ``retry_after=<seconds>`` makes an injected
    KubeError carry the apiserver pacing hint (the RetryPolicy floor
    branch real 429s exercise). Example::

        VTPU_FAILPOINTS='kube.request=error(429,retry_after=2,p=0.01);flock.acquire=latency(0.05)'
    """
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad failpoint spec {part!r}")
        site, _, rhs = part.partition("=")
        site = site.strip()
        action, _, argstr = rhs.partition("(")
        action = action.strip()
        kwargs: dict = {}
        argstr = argstr.rstrip(")").strip()
        if argstr:
            for raw in argstr.split(","):
                raw = raw.strip()
                if not raw:
                    continue
                if "=" in raw:
                    key, _, val = raw.partition("=")
                    key = key.strip()
                    if key == "p":
                        kwargs["p"] = float(val)
                    elif key == "count":
                        kwargs["count"] = int(val)
                    elif key == "retry_after":
                        kwargs["retry_after"] = float(val)
                    else:
                        raise ValueError(
                            f"unknown failpoint option {key!r} in {part!r}")
                elif action == "error":
                    kwargs["status"] = int(raw)
                elif action == "latency":
                    kwargs["latency_s"] = float(raw)
                else:
                    raise ValueError(
                        f"positional arg {raw!r} invalid for {action!r}")
        arm(site, action, **kwargs)


def render_failpoint_metrics() -> str:
    """Prometheus lines for /metrics (scheduler routes + monitor)."""
    lines = ["# TYPE vtpu_failpoint_fires_total counter"]
    snap = stats()
    for site, count in sorted(snap["fires"].items()):
        lines.append(f'vtpu_failpoint_fires_total{{site="{site}"}} {count}')
    lines.append(f"# TYPE vtpu_failpoint_evaluations_total counter\n"
                 f"vtpu_failpoint_evaluations_total {snap['evaluations']}")
    return "\n".join(lines)
