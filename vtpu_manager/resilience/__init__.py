"""vtfault: failpoint injection + unified retry/backoff resilience.

Three pillars (reference: pkg/controller/reschedule/{reschedule,recovery}.go
survive node-side failure; this package makes the WHOLE control plane
survive its own):

- ``failpoints``: an etcd/gofail-style registry of named injection sites
  wired across every layer (kube client, scheduler commit/bind, snapshot
  apply, plugin Allocate/config, registry, trace spool, file locks),
  behind the ``FaultInjection`` feature gate — a disabled site costs one
  dict lookup.
- ``policy``: ``RetryPolicy`` (jittered exponential backoff under a
  deadline budget, Retry-After honored, retryable vs terminal KubeErrors
  distinguished) and ``CircuitBreaker`` for API-server operations; every
  previously ad-hoc ``except KubeError: pass`` site routes through them
  (enforced by the ``retry-hygiene`` vtlint rule).
- ``recovery``: the bind-intent crash trail — an annotation stamped
  before the Binding POST so a scheduler crash between predicate commit
  and bind, or a plugin crash mid-Allocate, leaves state the reschedule
  controller can reap.

The seeded chaos harness (tests/test_chaos.py, ``make test-chaos``)
drives the fake-clientset e2e path with failpoints firing at every
registered site and asserts the invariants that define correctness under
failure: no double-allocation, no leaked device or claim, every pod
consistently allocated or evicted/requeued.
"""

from __future__ import annotations

# Import-free on purpose: client/kube.py calls failpoints.fire() and
# policy.py imports KubeError from client/kube.py — re-exporting policy
# here would close that loop into a circular import. Import the
# submodules directly (vtpu_manager.resilience.{failpoints,policy,
# recovery}).
